"""ServingEngine: continuous batching over the compiled decode path.

The engine owns a fixed-slot batch (default 8 slots) of static KV
caches — the SAME buffers `nlp.generation` uses offline, stacked along
the batch axis with one `pos` PER SLOT — and exactly two compiled
programs touch them:

- one decode step, shared by all slots: sample each slot's next token
  from its held logits (per-slot temperature/top-k/top-p vectors, same
  math as CompiledGenerator via `sample_logits`/`_top_p_filter`), then
  one fixed-shape batched forward through the model where every row
  reads/writes its own cache position (the per-row `pos` vector path in
  `kv_cache_update`/`window_causal_mask`). Membership, lengths, and
  sampling params change BETWEEN invocations only — the program never
  retraces (the slot-granularity analogue of Ragged Paged Attention's
  one-kernel-for-uneven-lengths, PAPERS.md; keeping the hot loop one
  fixed program is what lets XLA fuse it, "Operator Fusion in XLA").
- one prefill per prompt length: a batch-1 forward over a fresh cache
  whose full KV rows are then written into the free slot of the shared
  buffers with a single dynamic_update_slice, plus that request's
  next-token logits into the held-logits row.

Correctness contract (tests/test_serving.py): a request decoded greedily
through the engine emits tokens bit-identical to running it ALONE
through CompiledGenerator greedy decode, regardless of what its
slot-neighbors are doing — per-row compute is row-independent and
membership changes only rewrite the changed slot's rows.

Weights enter both programs as closed-over constants (the measured
layout win of generation.py's _build); construct the engine AFTER any
weight rebinding (quantization etc.) — it snapshots model state.
"""
from __future__ import annotations

import itertools
import time
from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core import random as random_mod
from ..core.tensor import Tensor
from ..profiler import RecordEvent
from ..nlp.generation import (_pack_caches, _top_p_filter,
                              _unpack_caches, decode_model_step,
                              init_decode_caches)
from .metrics import ServingMetrics
from .request import Request, RequestOutput, RequestState, SamplingParams
from .scheduler import Scheduler

__all__ = ["ServingEngine"]


def _sample_rows(logits, key, temps, top_k, top_p, greedy):
    """Per-slot sampling over f32 logits [S, V]: each row applies ITS
    OWN temperature/top-k/top-p (vectors [S]); greedy rows take argmax
    of the raw logits — exactly CompiledGenerator's greedy step, so
    greedy requests stay bit-identical to offline decode. top_k == 0
    and top_p == 1.0 disable the respective filter for that row; the
    nucleus mask is the same `_top_p_filter` the offline path uses."""
    v = logits.shape[-1]
    g = jnp.argmax(logits, axis=-1)
    l = logits / temps[:, None]
    sorted_desc = -jnp.sort(-l, axis=-1)
    kidx = (jnp.clip(top_k, 1, v) - 1).astype(jnp.int32)
    kth = jnp.take_along_axis(sorted_desc, kidx[:, None], axis=-1)
    l = jnp.where((top_k > 0)[:, None] & (l < kth), -1e30, l)
    filt = _top_p_filter(l, top_p[:, None])
    l = jnp.where((top_p < 1.0)[:, None], filt, l)
    s = jax.random.categorical(key, l, axis=-1)
    return jnp.where(greedy, g, s)


class ServingEngine:
    """Online inference engine: submit requests at any time, pump
    `step()` (or call `run()`/`generate()`); requests join free slots,
    decode together in one compiled step, and retire on EOS /
    max-tokens / timeout / cancellation without perturbing neighbors.
    """

    def __init__(self, model, cache_spec=None, *, num_slots: int = 8,
                 max_len: int = 256, scheduler: Optional[Scheduler] = None,
                 metrics: Optional[ServingMetrics] = None,
                 max_queue: Optional[int] = None, clock=time.monotonic):
        if cache_spec is None:
            if not hasattr(model, "_decode_cache_spec"):
                raise ValueError(
                    "cache_spec not given and the model has no "
                    "_decode_cache_spec(); pass (n_layers, n_kv_heads, "
                    "head_dim) explicitly")
            cache_spec = model._decode_cache_spec()
        self.model = model
        self.n_layers, self.n_kv, self.head_dim = cache_spec
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self.scheduler = scheduler or Scheduler(self.num_slots,
                                                max_queue=max_queue)
        if self.scheduler.num_slots != self.num_slots:
            raise ValueError("scheduler.num_slots != engine num_slots")
        self.metrics = metrics or ServingMetrics()
        self._clock = clock
        self._id_counter = itertools.count()
        self._requests: Dict[str, Request] = {}
        # model-state snapshot: weights are constants in the compiled
        # programs (see module doc)
        params = list(model.parameters())
        buffers = [b for _, b in model.named_buffers()]
        self._state_tensors = params + buffers
        self._fp = next(
            (t._value.dtype for t in self._state_tensors
             if jnp.issubdtype(t._value.dtype, jnp.floating)),
            dtypes.get_default_dtype().np_dtype)
        # device state: stacked KV rows, per-slot positions, per-slot
        # held next-token logits (filled by prefill, advanced by decode)
        self._ct = _pack_caches(init_decode_caches(
            self.n_layers, self.num_slots, self.max_len, self.n_kv,
            self.head_dim, dtype=self._fp))
        self._pos = jnp.zeros((self.num_slots,), jnp.int32)
        self._last_logits = None      # [S, V] f32, lazy (V from prefill)
        # per-slot sampling vectors, rebuilt when membership changes
        self._vec_dirty = True
        self._temps = np.ones((self.num_slots,), np.float32)
        self._topk = np.zeros((self.num_slots,), np.int32)
        self._topp = np.ones((self.num_slots,), np.float32)
        self._greedy = np.ones((self.num_slots,), bool)
        self._active = np.zeros((self.num_slots,), bool)
        self._prefill_fns: Dict[int, object] = {}
        self._decode_fn = None
        self._spans: Dict[str, RecordEvent] = {}

    # -- compiled programs -------------------------------------------------
    def _swap_state(self, state_vals):
        originals = [t._value for t in self._state_tensors]
        for t, v in zip(self._state_tensors, state_vals):
            t._value = v
        return originals

    def _restore_state(self, originals):
        for t, v in zip(self._state_tensors, originals):
            t._value = v

    def _build_prefill(self, prompt_len: int):
        """Compiled per prompt length: batch-1 prefill over a fresh
        cache, then write the whole KV row + next-token logits into the
        free slot of the shared buffers."""
        model = self.model
        n_layers, n_kv, head_dim = self.n_layers, self.n_kv, self.head_dim
        max_len, fp = self.max_len, self._fp
        state_vals = [t._value for t in self._state_tensors]

        def prefill(state_vals, ct, pos, last_logits, prompt, slot):
            originals = self._swap_state(state_vals)
            try:
                caches = init_decode_caches(n_layers, 1, max_len, n_kv,
                                            head_dim, dtype=fp)
                logits_t, caches = model(Tensor(prompt), caches=caches)
                row = logits_t._value[:, -1, :].astype(jnp.float32)
                c1 = _pack_caches(caches)
                z = jnp.zeros((), jnp.int32)
                s = slot.astype(jnp.int32).reshape(())
                new_ct = tuple(
                    (jax.lax.dynamic_update_slice(
                        k, k1.astype(k.dtype), (s, z, z, z)),
                     jax.lax.dynamic_update_slice(
                        v, v1.astype(v.dtype), (s, z, z, z)),
                     ks, vs)
                    for (k, v, ks, vs), (k1, v1, _, _) in zip(ct, c1))
                pos = jax.lax.dynamic_update_slice(
                    pos, jnp.full((1,), prompt_len, jnp.int32), (s,))
                last_logits = jax.lax.dynamic_update_slice(
                    last_logits, row, (s, jnp.zeros((), jnp.int32)))
                return new_ct, pos, last_logits
            finally:
                self._restore_state(originals)

        return jax.jit(lambda ct, pos, ll, prompt, slot: prefill(
            state_vals, ct, pos, ll, prompt, slot))

    def _build_decode(self):
        """ONE fixed-shape step for all slots: sample from held logits
        with per-slot params, batched forward with per-row positions."""
        model = self.model
        state_vals = [t._value for t in self._state_tensors]

        def step(state_vals, ct, pos, last_logits, key, temps, top_k,
                 top_p, greedy, active):
            originals = self._swap_state(state_vals)
            try:
                nxt = _sample_rows(last_logits, key, temps, top_k,
                                   top_p, greedy)
                nxt = jnp.where(active, nxt, 0).astype(jnp.int32)
                caches = _unpack_caches(ct, pos)
                last, caches = decode_model_step(model, nxt[:, None],
                                                 caches)
                # only occupied slots advance; free rows stay frozen
                # (their stale rows are fully overwritten at reuse)
                new_pos = jnp.where(active, pos + 1, pos)
                return _pack_caches(caches), new_pos, last, nxt
            finally:
                self._restore_state(originals)

        return jax.jit(lambda ct, pos, ll, key, t, k, p, g, a: step(
            state_vals, ct, pos, ll, key, t, k, p, g, a))

    # -- request intake ----------------------------------------------------
    def add_request(self, prompt_ids, sampling: Optional[SamplingParams]
                    = None, request_id: Optional[str] = None,
                    on_token=None) -> Request:
        sampling = sampling or SamplingParams()
        if isinstance(prompt_ids, Tensor):
            prompt_ids = prompt_ids.numpy()
        prompt = np.asarray(prompt_ids).reshape(-1)
        if prompt.size >= self.max_len:
            raise ValueError(
                f"prompt length {prompt.size} >= engine max_len "
                f"{self.max_len}")
        if prompt.size + sampling.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt_len {prompt.size} + max_new_tokens "
                f"{sampling.max_new_tokens} exceeds engine max_len "
                f"{self.max_len}; lower max_new_tokens or grow the "
                "engine's cache")
        if request_id is None:
            request_id = f"req-{next(self._id_counter)}"
        if request_id in self._requests:
            raise ValueError(f"duplicate request_id {request_id!r}")
        req = Request(request_id, prompt, sampling, on_token=on_token,
                      arrival_t=self._clock())
        self._requests[request_id] = req
        self.scheduler.submit(req)
        self.metrics.on_submit(req)
        return req

    def cancel(self, request_id: str) -> bool:
        """Mark a request cancelled. Queued requests drop immediately;
        a running one is evicted at the next step boundary (its slot is
        then free for the next queued request)."""
        req = self._requests.get(request_id)
        if req is None or req.finished:
            return False
        if req.state is RequestState.QUEUED:
            self.scheduler.drop_queued(req)
            req._finish("cancelled", self._clock())
            self.metrics.on_finish(req, self._clock())
            return True
        req.state = RequestState.CANCELLED
        return True

    # -- step boundary: retire / admit / decode ----------------------------
    def _finish_and_free(self, req: Request, reason: str, now: float,
                         finished: List[RequestOutput]):
        if req.slot is not None:
            slot = req.slot
            self.scheduler.retire(slot)
            self._active[slot] = False
            self._vec_dirty = True
        req._finish(reason, now)
        self.metrics.on_finish(req, now)
        span = self._spans.pop(req.request_id, None)
        if span is not None:
            span.end()
        finished.append(req.output())

    def _evict(self, now: float, finished: List[RequestOutput]):
        for req in self.scheduler.expired(now):
            if req.state is RequestState.QUEUED:
                self.scheduler.drop_queued(req)
            self._finish_and_free(req, "timeout", now, finished)
        for req in self.scheduler.cancelled_running():
            self._finish_and_free(req, "cancelled", now, finished)

    def _admit(self, now: float):
        for slot, req in self.scheduler.assign():
            req.state = RequestState.PREFILL
            req.admitted_t = now
            span = RecordEvent(f"serving::request[{req.request_id}]")
            span.begin()
            self._spans[req.request_id] = span
            self._prefill(slot, req)
            req.state = RequestState.DECODE
            self._active[slot] = True
            self._vec_dirty = True
            self.metrics.on_admit(req, self._clock())

    def _prefill(self, slot: int, req: Request):
        plen = int(req.prompt_ids.size)
        fn = self._prefill_fns.get(plen)
        if fn is None:
            fn = self._prefill_fns[plen] = self._build_prefill(plen)
        if self._last_logits is None:
            vocab = int(getattr(getattr(self.model, "config", None),
                                "vocab_size", 0))
            if not vocab:
                # probe: one eager forward row tells us V
                lg = self.model(Tensor(jnp.asarray(
                    req.prompt_ids[None, :1], jnp.int32)))
                vocab = int(lg.shape[-1])
            self._last_logits = jnp.zeros((self.num_slots, vocab),
                                          jnp.float32)
        with RecordEvent(f"serving::prefill[{req.request_id}]"):
            self._ct, self._pos, self._last_logits = fn(
                self._ct, self._pos, self._last_logits,
                jnp.asarray(req.prompt_ids[None, :], jnp.int32),
                jnp.int32(slot))

    def _refresh_vectors(self):
        for s in range(self.num_slots):
            req = self.scheduler.running.get(s)
            if req is None:
                self._temps[s], self._topk[s] = 1.0, 0
                self._topp[s], self._greedy[s] = 1.0, True
                continue
            sp = req.sampling
            self._temps[s] = sp.temperature
            self._topk[s] = sp.top_k or 0
            self._topp[s] = sp.top_p if sp.top_p is not None else 1.0
            self._greedy[s] = sp.greedy
        self._vec_dirty = False

    def _decode(self, now_fn, finished: List[RequestOutput]):
        if self._decode_fn is None:
            self._decode_fn = self._build_decode()
        if self._vec_dirty:
            self._refresh_vectors()
        key = random_mod.next_key_host()
        with RecordEvent("serving::decode_step"):
            self._ct, self._pos, self._last_logits, toks = \
                self._decode_fn(
                    self._ct, self._pos, self._last_logits, key,
                    jnp.asarray(self._temps), jnp.asarray(self._topk),
                    jnp.asarray(self._topp), jnp.asarray(self._greedy),
                    jnp.asarray(self._active))
            toks = np.asarray(toks)   # sync point: host sees the tokens
        now = now_fn()
        for slot, req in list(self.scheduler.running.items()):
            tok = int(toks[slot])
            prev_t = req._last_token_t
            req._emit(tok, now)
            self.metrics.on_token(req, now)
            if prev_t is not None:
                self.metrics.on_inter_token(now - prev_t)
            sp = req.sampling
            if sp.eos_token_id is not None and tok == sp.eos_token_id:
                self._finish_and_free(req, "stop", now, finished)
            elif len(req.output_tokens) >= sp.max_new_tokens:
                self._finish_and_free(req, "length", now, finished)

    def step(self) -> List[RequestOutput]:
        """One scheduler round: evict (timeout/cancel), refill free
        slots (prefill), then one compiled decode step for everyone.
        Returns requests that finished this round."""
        finished: List[RequestOutput] = []
        now = self._clock()
        self._evict(now, finished)
        self._admit(now)
        if self.scheduler.running:
            self._decode(self._clock, finished)
        self.metrics.on_step(self.scheduler.queue_depth,
                             self.scheduler.occupancy, self.num_slots)
        return finished

    # -- conveniences ------------------------------------------------------
    @property
    def has_work(self) -> bool:
        return self.scheduler.has_work

    def run(self, max_steps: Optional[int] = None) -> List[RequestOutput]:
        """Pump steps until idle (or max_steps); returns everything that
        finished along the way."""
        out: List[RequestOutput] = []
        steps = 0
        while self.has_work:
            out.extend(self.step())
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return out

    def generate(self, prompts: Sequence, sampling=None
                 ) -> List[RequestOutput]:
        """Blocking batch API: submit all prompts, run to completion,
        return outputs in submission order."""
        if sampling is None or isinstance(sampling, SamplingParams):
            sampling = [sampling] * len(prompts)
        reqs = [self.add_request(p, sp) for p, sp in zip(prompts, sampling)]
        self.run()
        return [r.output() for r in reqs]
