"""paddle.static compatibility layer: Program / Executor / feed-fetch.

TPU-native replacement for the reference's declarative stack
(python/paddle/fluid/framework.py:5249 Program, executor.py:911
Executor/:1377 run, static/nn). The reference builds a ProgramDesc
protobuf and interprets it op-by-op (InterpreterCore); here a Program
RECORDS the op calls made while it is the current program (build-time
code runs once, exactly like static graph construction), and
Executor.run REPLAYS the recorded op DAG as ONE jitted XLA program per
feed signature — the "one XLA computation per program" executor design
(SURVEY.md §7), with feed/fetch by variable.

Buffer mutations (BN running stats, spectral-norm u/v) are
functionalized: a build-time `buffer._rebind(out)` is captured as a
program write-back, fetched with every run and rebound onto the live
buffer — so train-then-infer BN uses fresh statistics (reference BN
variable semantics, python/paddle/nn/layer/norm.py).

Known v1 deltas from the reference, by design:
- startup programs are no-ops on FIRST run: initializer ops already ran
  eagerly at layer construction (parameters are born initialized). A
  repeat run — the re-initialization idiom — warns loudly instead of
  silently doing nothing.
"""
from __future__ import annotations

import contextlib
import functools
import json
import os
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from ..core.dispatch import OpDef
from ..jit.api import InputSpec  # noqa: F401  (re-export, paddle parity)

__all__ = ["Program", "program_guard", "data", "Executor",
           "default_main_program", "default_startup_program",
           "enable_static", "disable_static", "in_static_mode",
           "InputSpec", "name_scope", "save_inference_model",
           "load_inference_model", "global_scope", "cpu_places",
           "device_places", "nn"]

_state = {
    "enabled": False,
    "main": None,
    "startup": None,
}


class _Node:
    __slots__ = ("op", "attrs", "in_ids", "out_ids", "single")

    def __init__(self, op, attrs, in_ids, out_ids, single):
        self.op = op
        self.attrs = attrs
        self.in_ids = in_ids
        self.out_ids = out_ids
        self.single = single


def _jsonable(obj):
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, np.dtype):
        return str(obj)
    return obj


class _OpView:
    """Read-only OpDesc facade (reference: framework OpDesc bindings)."""

    __slots__ = ("_node", "_prog")

    def __init__(self, node, prog):
        self._node = node
        self._prog = prog

    @property
    def type(self):
        return self._node.op.name

    def attr(self, name):
        return self._node.attrs.get(name)

    def all_attrs(self):
        return dict(self._node.attrs)

    def _names(self, ids):
        out = []
        for i in ids:
            t = self._prog._tensors.get(i)
            out.append(t.name if t is not None and t.name else str(i))
        return out

    @property
    def input_arg_names(self):
        return self._names(self._node.in_ids)

    @property
    def output_arg_names(self):
        return self._names(self._node.out_ids)

    def __repr__(self):
        return f"OpView({self.type})"


class Program:
    """Recorded op DAG (reference: framework.py:5249 class Program —
    desc/blocks replaced by the node list; random_seed/clone kept)."""

    def __init__(self):
        self._nodes: list[_Node] = []
        self._tensors: dict[int, Tensor] = {}   # strong refs: build-time
        self._feed_names: dict[str, int] = {}
        self._feed_shapes: dict[str, list] = {}  # declared (None dims)
        self._optimizer = None
        self._loss_id = None
        self._runner_cache: dict = {}
        self._version = 0
        self.random_seed = 0
        # functionalized buffer mutations (BN running stats, spectral
        # norm u/v): value-object id -> (producing out id, strong ref to
        # the value — keeping it alive prevents id() reuse from falsely
        # matching an unrelated array), and buffer tensor id -> out id
        # to write back after each run
        self._value_to_out: dict[int, tuple] = {}
        self._leaf_alias: dict[int, int] = {}

    # -- recording -----------------------------------------------------------
    def _record(self, op, attrs, in_tensors, out_tensors, single):
        # connectivity gate: record only ops reachable from the program
        # (feeds, params, recorded outputs). Disconnected eager work —
        # e.g. a metric computed between exe.run calls — must not grow
        # the program (it would force a re-jit every step) nor execute
        # dead nodes inside it.
        if not any(id(t) in self._tensors for t in in_tensors):
            return
        in_ids = []
        for t in in_tensors:
            self._tensors.setdefault(id(t), t)
            # a mutated buffer reads its latest functionalized value
            in_ids.append(self._leaf_alias.get(id(t), id(t)))
        out_ids = []
        for t in out_tensors:
            self._tensors[id(t)] = t
            out_ids.append(id(t))
            self._value_to_out[id(t._value)] = (id(t), t._value)
        self._nodes.append(_Node(op, dict(attrs), in_ids, out_ids,
                                 single))
        self._version += 1

    def _record_mutation(self, tensor, new_value):
        """A build-time `buffer._rebind(out._value)` becomes a program
        write-back: Executor.run fetches the out and rebinds the buffer
        (the mechanism jit/api.py uses for compiled buffer updates).
        Returns True when captured (the eager mutation is suppressed so
        placeholder values never pollute live buffers)."""
        entry = self._value_to_out.get(id(new_value))
        if entry is None or entry[1] is not new_value \
                or id(tensor) not in self._tensors:
            return False
        self._leaf_alias[id(tensor)] = entry[0]
        self._version += 1
        return True

    def _register_feed(self, name, tensor):
        self._feed_names[name] = id(tensor)
        self._tensors[id(tensor)] = tensor
        self._version += 1

    def register_optimizer(self, optimizer, loss):
        self._optimizer = optimizer
        self._loss_id = id(loss)
        self._version += 1

    # -- structure queries ---------------------------------------------------
    def _leaf_ids(self, feed_ids):
        produced = set()
        for n in self._nodes:
            produced.update(n.out_ids)
        feed = set(feed_ids)
        leaves, seen = [], set()
        for n in self._nodes:
            for i in n.in_ids:
                if i not in produced and i not in feed and i not in seen:
                    seen.add(i)
                    leaves.append(i)
        return leaves

    def _classify_leaves(self, feed_ids, trainable_ids=None):
        """trainable_ids: explicit id set, or None -> every trainable
        Parameter leaf (minimize() without parameters=, the canonical
        static idiom: the program's parameters are implicit)."""
        params, consts = [], []
        for i in self._leaf_ids(feed_ids):
            t = self._tensors[i]
            if trainable_ids is None:
                is_param = isinstance(t, Parameter) and t.trainable
            else:
                is_param = id(t) in trainable_ids
            if is_param:
                params.append(i)
            else:
                consts.append(i)
        return params, consts

    @staticmethod
    def _run_nodes(nodes, env):
        for n in nodes:
            fn = n.op.fwd
            out = (functools.partial(fn, **n.attrs) if n.attrs else fn)(
                *[env[i] for i in n.in_ids])
            if n.single:
                env[n.out_ids[0]] = out
            else:
                for i, o in zip(n.out_ids, out):
                    env[i] = o

    # -- paddle API ----------------------------------------------------------
    def clone(self, for_test=False):
        p = Program()
        p._nodes = list(self._nodes)
        p._tensors = dict(self._tensors)
        p._feed_names = dict(self._feed_names)
        p._feed_shapes = dict(self._feed_shapes)
        p._value_to_out = dict(self._value_to_out)
        p._leaf_alias = dict(self._leaf_alias)
        if not for_test:
            p._optimizer = self._optimizer
            p._loss_id = self._loss_id
        return p

    def global_block(self):
        return self

    @property
    def blocks(self):
        return [self]

    @property
    def ops(self):
        """Op views for program inspection (reference:
        program.global_block().ops over OpDesc): each has .type,
        .attr(name)/.all_attrs(), .input_arg_names/.output_arg_names."""
        return [_OpView(n, self) for n in self._nodes]

    def list_vars(self):
        return list(self._tensors.values())

    # -- prune / serialization (reference: framework/prune.cc,
    #    ProgramDesc serialize_to_string) --------------------------------
    def _clone_with_nodes(self, nodes):
        p = self.clone()
        p._nodes = list(nodes)
        p._runner_cache = {}
        p._version += 1
        return p

    def prune(self, targets):
        """Dead-op elimination: keep only ops on which the target
        tensors depend (reference: framework/prune.cc Prune). targets:
        Tensors (or names)."""
        keep_ids = set()
        for t in targets:
            if isinstance(t, Tensor):
                keep_ids.add(self._leaf_alias.get(id(t), id(t)))
            else:
                keep_ids.update(id(v) for v in self._tensors.values()
                                if v.name == t)
        needed = set(keep_ids)
        kept = []
        for n in reversed(self._nodes):
            if any(o in needed for o in n.out_ids):
                kept.append(n)
                needed.update(n.in_ids)
        return self._clone_with_nodes(reversed(kept))

    def serialize(self, path):
        """Persist the recorded program: op list (registry names +
        attrs + tensor-id wiring) as JSON, leaf tensor values as npz.
        Ops must be registry-registered (custom OpDef instances from
        to_static cannot round-trip — export those via jit.save)."""
        import json as _json
        from ..core.dispatch import _OPS
        for n in self._nodes:
            if _OPS.get(n.op.name) is not n.op:
                raise ValueError(
                    f"cannot serialize non-registry op {n.op.name!r}; "
                    f"use paddle.jit.save for traced programs")
        feed_ids = list(self._feed_names.values())
        leaf_ids = self._leaf_ids(feed_ids)
        meta = {
            "nodes": [{"op": n.op.name, "attrs": _jsonable(n.attrs),
                       "in": n.in_ids, "out": n.out_ids,
                       "single": n.single} for n in self._nodes],
            "feeds": {k: v for k, v in self._feed_names.items()},
            "feed_shapes": self._feed_shapes,
            "leaf_ids": leaf_ids,
            "names": {i: t.name for i, t in self._tensors.items()
                      if t.name},
        }
        with open(str(path) + ".program.json", "w") as f:
            _json.dump(meta, f)
        np.savez(str(path) + ".program.npz",
                 **{str(i): np.asarray(self._tensors[i]._value)
                    for i in leaf_ids})

    @staticmethod
    def deserialize(path):
        """Rebuild a Program serialized by .serialize(). Tensor ids are
        remapped to fresh placeholder Tensors."""
        import json as _json
        from ..core.dispatch import get_op
        with open(str(path) + ".program.json") as f:
            meta = _json.load(f)
        leaves = np.load(str(path) + ".program.npz")
        p = Program()
        id_map: dict[int, Tensor] = {}

        def tensor_for(old_id, is_leaf):
            old_id = int(old_id)
            if old_id not in id_map:
                if is_leaf and str(old_id) in leaves:
                    t = Tensor(jnp.asarray(leaves[str(old_id)]),
                               stop_gradient=True)
                else:
                    t = Tensor(jnp.zeros((), np.float32),
                               stop_gradient=True)
                t.name = meta["names"].get(str(old_id))
                id_map[old_id] = t
            return id_map[old_id]

        for old in meta["leaf_ids"]:
            tensor_for(old, True)
        for name, old in meta["feeds"].items():
            t = tensor_for(old, False)
            p._register_feed(name, t)
        p._feed_shapes = dict(meta["feed_shapes"])
        for nd in meta["nodes"]:
            for i in nd["in"]:
                tensor_for(i, True)
            for o in nd["out"]:
                tensor_for(o, False)
            p._nodes.append(_Node(
                get_op(nd["op"]), dict(nd["attrs"]),
                [id(id_map[int(i)]) for i in nd["in"]],
                [id(id_map[int(o)]) for o in nd["out"]], nd["single"]))
        for t in id_map.values():
            p._tensors.setdefault(id(t), t)
        p._version += 1
        return p

    def __repr__(self):
        return (f"Program(nodes={len(self._nodes)}, "
                f"feeds={list(self._feed_names)})")


def default_main_program() -> Program:
    if _state["main"] is None:
        _state["main"] = Program()
    return _state["main"]


def default_startup_program() -> Program:
    if _state["startup"] is None:
        _state["startup"] = Program()
    return _state["startup"]


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    """reference: static.program_guard."""
    prev_main, prev_start = _state["main"], _state["startup"]
    _state["main"] = main_program
    if startup_program is not None:
        _state["startup"] = startup_program
    try:
        yield
    finally:
        _state["main"] = prev_main
        _state["startup"] = prev_start


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


def _record_hook(op, attrs, in_tensors, out_tensors, single):
    prog = _state["main"]
    if prog is not None:
        prog._record(op, attrs, in_tensors, out_tensors, single)


def _rebind_hook(tensor, new_value):
    prog = _state["main"]
    if prog is None or not _state["enabled"]:
        return False
    return prog._record_mutation(tensor, new_value)


def enable_static():
    """paddle.enable_static parity: op calls now RECORD into the current
    default main program (and still execute on placeholder values, which
    is how shapes/params materialize at build time)."""
    from ..core import tensor as tensor_mod
    _state["enabled"] = True
    tensor_mod._static_hook = _record_hook
    tensor_mod._rebind_hook = _rebind_hook


def disable_static(place=None):
    from ..core import tensor as tensor_mod
    _state["enabled"] = False
    tensor_mod._static_hook = None
    tensor_mod._rebind_hook = None


def in_static_mode():
    return _state["enabled"]


def data(name, shape, dtype="float32", lod_level=0):
    """paddle.static.data parity: a named feed placeholder. Build-time
    code sees a dummy tensor (None/-1 dims become 1); Executor.run feeds
    the real value by name; save_inference_model re-reads the declared
    shape so None dims export shape-polymorphic."""
    from ..core import dtype as dtypes
    declared = list(shape)
    shape = [1 if (d is None or d < 0) else int(d) for d in shape]
    np_dtype = dtypes.to_np_dtype(dtype)
    t = Tensor(jnp.zeros(shape, np_dtype), stop_gradient=True, name=name)
    prog = default_main_program()
    prog._register_feed(name, t)
    prog._feed_shapes[name] = declared
    return t


class Executor:
    """reference: executor.py:911. run() compiles the recorded program
    once per feed signature and executes the cached XLA program."""

    def __init__(self, place=None):
        self.place = place

    def close(self):
        pass

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, **kwargs):
        feed = feed or {}
        if isinstance(program, _LoadedProgram):
            return program._run(feed, return_numpy)
        if program is None:
            program = default_main_program()
        if program is _state["startup"] or not program._nodes:
            # startup: params were initialized eagerly at construction.
            # A SECOND run of the startup program is the
            # re-initialization idiom — that we cannot honor (no
            # initializer ops are recorded), so reject loudly rather
            # than silently diverge from the reference
            if program is _state["startup"]:
                if getattr(program, "_startup_ran", False):
                    import warnings
                    warnings.warn(
                        "re-running the startup program does NOT "
                        "re-initialize parameters in paddle_tpu (they "
                        "are initialized eagerly at Layer "
                        "construction); rebuild the layers to "
                        "re-initialize",
                        RuntimeWarning, stacklevel=2)
                program._startup_ran = True
            return []
        fetch_list = fetch_list or []
        fetch_ids = []
        for f in fetch_list:
            if isinstance(f, Tensor):
                fetch_ids.append(id(f))
            elif isinstance(f, str):
                match = [id(t) for t in program._tensors.values()
                         if t.name == f]
                if not match:
                    raise KeyError(f"fetch var {f!r} not in program")
                fetch_ids.append(match[0])
            else:
                raise TypeError(f"bad fetch entry {f!r}")

        feed_names = sorted(feed)
        feed_ids = [program._feed_names[n] for n in feed_names]
        feed_vals = [jnp.asarray(feed[n]) for n in feed_names]

        if program._optimizer is not None:
            outs = self._run_train(program, feed_names, feed_ids,
                                   feed_vals, fetch_ids)
        else:
            outs = self._run_infer(program, feed_names, feed_ids,
                                   feed_vals, fetch_ids)
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]

    # -- inference path ------------------------------------------------------
    def _run_infer(self, program, feed_names, feed_ids, feed_vals,
                   fetch_ids):
        fetch_ids = [program._leaf_alias.get(i, i) for i in fetch_ids]
        key = ("infer", tuple(feed_names),
               tuple((v.shape, str(v.dtype)) for v in feed_vals),
               tuple(fetch_ids), program._version)
        entry = program._runner_cache.get(key)
        if entry is None:
            param_ids, const_ids = program._classify_leaves(feed_ids,
                                                            set())
            leaf_ids = param_ids + const_ids
            wb = sorted(program._leaf_alias.items())

            def pure(feed_vals, leaf_vals):
                env = dict(zip(feed_ids, feed_vals))
                env.update(zip(leaf_ids, leaf_vals))
                Program._run_nodes(program._nodes, env)
                return ([env[i] for i in fetch_ids],
                        [env[o] for _, o in wb])

            entry = (jax.jit(pure), leaf_ids, wb)
            program._runner_cache[key] = entry
        fn, leaf_ids, wb = entry
        leaf_vals = [program._tensors[i]._value for i in leaf_ids]
        outs, wb_vals = fn(feed_vals, leaf_vals)
        for (bid, _), v in zip(wb, wb_vals):
            program._tensors[bid]._value = v
        return outs

    # -- training path -------------------------------------------------------
    def _run_train(self, program, feed_names, feed_ids, feed_vals,
                   fetch_ids):
        opt = program._optimizer
        loss_id = program._loss_id
        # explicit parameters= wins; otherwise every trainable Parameter
        # leaf of the program (paddle's implicit-parameter semantics)
        trainable = ({id(p) for p in opt._parameter_list
                      if (p.trainable if isinstance(p, Parameter)
                          else not p.stop_gradient)}
                     if opt._parameter_list else None)
        fetch_ids = [program._leaf_alias.get(i, i) for i in fetch_ids]
        key = ("train", tuple(feed_names),
               tuple((v.shape, str(v.dtype)) for v in feed_vals),
               tuple(fetch_ids), program._version)
        entry = program._runner_cache.get(key)
        if entry is None:
            param_ids, const_ids = program._classify_leaves(
                feed_ids, trainable)
            decay = opt._decay if not getattr(opt, "_decoupled", False) \
                else 0.0
            clip = getattr(opt, "_grad_clip", None)
            extras = opt._per_param_extra(
                [program._tensors[i] for i in param_ids])
            wb = sorted(program._leaf_alias.items())

            def step(feed_vals, p_vals, const_vals, states, gstate, lr):
                def loss_of(pv):
                    env = dict(zip(feed_ids, feed_vals))
                    env.update(zip(param_ids, pv))
                    env.update(zip(const_ids, const_vals))
                    Program._run_nodes(program._nodes, env)
                    return env[loss_id], ([env[i] for i in fetch_ids],
                                          [env[o] for _, o in wb])

                (lossv, (fetches, wb_vals)), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(list(p_vals))
                if clip is not None:
                    # per-class clip semantics, same order (clip then
                    # decay) as the dygraph CompiledTrainStep
                    from ..nn.clip import apply_grad_clip_values
                    grads = apply_grad_clip_values(clip, grads)
                if decay:
                    grads = [g + decay * p
                             for p, g in zip(p_vals, grads)]
                new_p, new_s, gstate = opt._apply_updates(
                    p_vals, grads, states, gstate, lr, extras)
                return fetches, wb_vals, new_p, new_s, gstate

            entry = (jax.jit(step), param_ids, const_ids, wb)
            program._runner_cache[key] = entry
        fn, param_ids, const_ids, wb = entry
        params = [program._tensors[i] for i in param_ids]
        p_vals = [p._value for p in params]
        const_vals = [program._tensors[i]._value for i in const_ids]
        states = [opt._state_for(p) for p in params]
        if not hasattr(opt, "_gstate"):
            opt._gstate = {k: jnp.asarray(v) for k, v in
                           opt._global_state_spec().items()}
        lr = jnp.asarray(opt.get_lr(), dtype=jnp.float32)
        fetches, wb_vals, new_p, new_s, new_g = fn(
            feed_vals, p_vals, const_vals, states, opt._gstate, lr)
        opt._gstate = new_g
        off = getattr(opt, "_offload_put", None)
        for p, nv, ns in zip(params, new_p, new_s):
            p._rebind(nv)
            opt._accumulators[id(p)] = off(ns) if off is not None else ns
        for (bid, _), v in zip(wb, wb_vals):
            program._tensors[bid]._value = v
        return fetches


def global_scope():
    return default_main_program()


def cpu_places(device_count=None):
    from ..core.device import CPUPlace
    return [CPUPlace()]


def device_places(device_count=None):
    from ..core.device import TPUPlace
    import jax as _j
    n = device_count or len(_j.local_devices())
    return [TPUPlace(i) for i in range(n)]


# -- inference model save/load ----------------------------------------------

def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         program=None, **kwargs):
    """reference: static/io.py save_inference_model — exports the
    inference slice of the program (params baked) as the jit.save
    StableHLO artifact plus feed metadata."""
    from ..jit import save_load
    program = program or default_main_program()
    feed_vars = list(feed_vars)
    fetch_vars = list(fetch_vars)
    feed_ids = [id(v) for v in feed_vars]
    fetch_ids = [id(v) for v in fetch_vars]

    # None dims declared in static.data export shape-polymorphic — the
    # loaded model accepts any batch size, not the build placeholder's 1
    n_poly = sum(
        1 for v in feed_vars
        for d in program._feed_shapes.get(v.name, []) if d is None or
        (isinstance(d, int) and d < 0))
    sym = iter(jax.export.symbolic_shape(
        ", ".join(f"_b{i}" for i in range(n_poly)))) if n_poly else None
    input_specs = []
    for v in feed_vars:
        declared = program._feed_shapes.get(v.name)
        if declared and any(d is None or (isinstance(d, int) and d < 0)
                            for d in declared):
            dims = tuple(next(sym) if (d is None or d < 0) else int(d)
                         for d in declared)
            input_specs.append(jax.ShapeDtypeStruct(
                dims, np.dtype(v._value.dtype)))
        else:
            input_specs.append(v)
    param_ids, const_ids = program._classify_leaves(feed_ids)
    leaf_ids = param_ids + const_ids
    leaf_vals = [program._tensors[i]._value for i in leaf_ids]
    nodes = program._nodes

    def infer(*feeds):
        env = {i: f._value for i, f in zip(feed_ids, feeds)}
        env.update(zip(leaf_ids, leaf_vals))
        Program._run_nodes(nodes, env)
        return [Tensor(env[i]) for i in fetch_ids]

    save_load.save(infer, path_prefix, input_spec=input_specs)
    meta = {"feed_names": [v.name for v in feed_vars],
            "n_fetch": len(fetch_vars)}
    with open(str(path_prefix) + ".pdmeta.json", "w") as f:
        json.dump(meta, f)
    return None


class _LoadedProgram:
    def __init__(self, translated, feed_names):
        self._layer = translated
        self._feed_names = feed_names

    def _run(self, feed, return_numpy=True):
        vals = [Tensor(jnp.asarray(feed[n])) for n in self._feed_names]
        outs = self._layer(*vals)
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        if return_numpy:
            return [o.numpy() if isinstance(o, Tensor) else np.asarray(o)
                    for o in outs]
        return list(outs)


def load_inference_model(path_prefix, executor, **kwargs):
    """reference: static/io.py load_inference_model -> [program,
    feed_target_names, fetch_targets]."""
    from ..jit import save_load
    translated = save_load.load(str(path_prefix))
    meta_path = str(path_prefix) + ".pdmeta.json"
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        feed_names = meta["feed_names"]
        n_fetch = meta["n_fetch"]
    else:
        feed_names, n_fetch = [], 1
    prog = _LoadedProgram(translated, feed_names)
    return [prog, feed_names, list(range(n_fetch))]


from . import nn  # noqa: E402,F401
