"""Online serving bench: Poisson arrivals through the ServingEngine.

Drives `paddle_tpu.serving.ServingEngine` (paged KV pool + chunked
prefill) with a Poisson arrival trace (exponential inter-arrival gaps,
geometric-ish mixed prompt lengths and output budgets) against the
tiny GPT config on CPU or a GPT-124M-ish config on the chip. The SAME
trace runs once per paged-attention implementation — "kernel" (Pallas
ragged paged attention, the engine default) and "gather" (the
paged_kv_gather + dense SDPA cross-check path) — so the A/B shows up
in the bench trajectory. Prints ONE JSON line and writes the same
stable-schema report to BENCH_serving.json (override with --out,
suppress with --out -):

    {"bench": "serving", "schema_version": 19, "attn_impl": "kernel",
     "requests": ..., "ttft_p50_s": ..., "tokens_per_sec": ...,
     "decode_step_ms_p50": ..., "ab": {"kernel": {...},
     "gather": {...}}, "prefix_stats": {...}, "unified": {...},
     "spec": {...}, "chaos": {...}, ...}

Top-level numbers are the default ("kernel") run; "ab" holds the
per-impl summaries (tokens/s, TTFT, per-step decode wall time).

`--unified-ab` adds the unified-step A/B: the SAME Poisson trace under
a LONG-PROMPT-HEAVY mix runs once with the unified ragged
prefill+decode step ON (one compiled program, prefill packed into
spare decode capacity) and once OFF (the legacy alternating
prefill-bucket/decode families), recording client-observed TTFT
p50/p99, tokens/s, prefill-stall steps and packed tokens per step
under the report's "unified" key — and asserts TTFT p99 does not
regress with the unified step on (the stall-kill this step exists
for).

`--spec-ab` adds the speculative-decoding A/B: the SAME Poisson
arrivals over a TEMPLATED/CODE-HEAVY prompt mix (repeating template
blocks — the traffic shape the model-free n-gram/prompt-lookup
drafter exists for) run once with speculation off and once with
`spec="ngram"` (draft-then-verify through the unified ragged step,
serving/spec.py). Both runs collect every request's emitted tokens;
the report's "spec" section records accepted-tokens-per-step (the
per-decode-row burst size the verify pass confirmed), the
drafted-vs-accepted economics, and the tokens/s ratio — and the
script ASSERTS the two arms are token-identical, that
accepted-tokens-per-step beat 1.0, and that tokens/s did not regress
with speculation on. The same flag also replays a NATURAL-TEXT trace
(non-templated random prompts, the shape n-gram lookup collapses on)
through three arms — off, ngram, and the resident draft MODEL tier
(`spec="model"`, serving/draft.py) — and asserts the tier
separation: the model drafter's accepted-tokens-per-step strictly
beats ngram's, stays bit-identical to the no-spec oracle, and does
not regress tokens/s (the "spec.natural" report section).

`--grammar-ab` adds the structured-output A/B (schema v17): the SAME
Poisson arrivals over a templated prompt mix run three ways —
unconstrained ("off"), grammar-constrained ("on": a regex GrammarSpec
whose per-slot allow-mask rides the ONE unified step as operand
data), and grammar COMPOSED with speculative decoding ("spec"). The
report's "grammar" section records schema-valid stream counts per
arm, the masking counters, the composed arm's accepted-tokens-per-
step and the tokens/s ratio — and the script ASSERTS 100% validity
in both constrained arms, >= 1 invalid stream unconstrained, masking
actually ran, > 1.0 accepted tokens/step in the composed arm, and
throughput within a noise pin of the unconstrained arm (masks are
operand data, never a retrace).

`--fused-ab` adds the decode-megakernel A/B (schema v19): the
STANDARD Poisson trace replayed once with the megakernel off and once
on (PADDLE_TPU_MEGAKERNEL — each layer's KV quantize-then-scatter,
paged LoRA gather and attend walk fused into ONE dispatched op, with
greedy argmax + spec acceptance as kernel epilogues over the logits
tile). Fusion is bit-exact by construction, so the report's "fused"
section records the referees that CAN move: the launch-count probe's
registered-op dispatches per unified step and the census's modeled
page-walk bytes/token — and the script ASSERTS the arms are
token-identical, dispatches drop, and modeled bytes/token strictly
drops with the megakernel on.

`--chaos` replays the standard Poisson trace through a 2-replica HTTP
front-end TWICE — once fault-free, once with the FaultInjector
(serving/faults.py) killing one replica after the first token has
streamed. Every client is an SSE stream that counts its tokens; the
chaos run must deliver EVERY stream complete and exact
(truncated_streams == 0, asserted — replica death is a latency blip,
not data loss; mid-stream requests MIGRATE to the survivor). The
report's "chaos" section records truncated/migrated stream counts,
recovery p99 (worst client-observed inter-token gap across migrated
streams) and goodput vs the fault-free run.

`--overload` adds the graceful-degradation A/B: a DETERMINISTIC
virtual-time replay (the engine runs on a harness-driven clock that
advances a fixed dt per step, so the same numbers come out on any
machine) of a 3x-oversubscribed trace — a wave of long low-priority
requests saturating every slot, then a burst of high-priority
requests with tight placement deadlines — once with preemption ON
(the default: the blocked high-priority head preempts the
least-important resident, whose KV swaps to the host-RAM tier and
resumes later token-identically) and once OFF (pure backpressure).
The report's "overload" section records per-class goodput, deadline
misses, preemption/swap traffic and swap-in latency p99 — and the
script ASSERTS zero high-priority deadline misses with preemption on,
strictly better high-priority goodput than the off arm, and that a
priority-flat fault-free replay is bit-identical (same tokens, same
step count) with preemption on vs off (the machinery costs nothing
when it never fires).

`--autoscale-ab` adds the fleet-autoscaling A/B (schema v15): a
DETERMINISTIC diurnal wave — trough, peak, trough — replayed on one
shared virtual clock through (a) a fleet steered by the REAL
FleetController (serving/controlplane.py: util/queue/burn signals in,
scale-up at the peak, graceful drain back down, hysteresis +
cool-downs) starting from 1 replica, and (b) a peak-provisioned
FIXED fleet of n_max replicas. The report's "autoscale" section
records per-arm TTFT p50/p99, replica-seconds, the scaling decision
log and the replica-seconds ratio — and the script ASSERTS every
stream in both arms is exactly its token budget, the auto arm's TTFT
p99 stays within the SLO target at <= ~0.6x the fixed arm's
replica-seconds, scaling happened without flapping, and a steady
fixed-size trace is bit-token-identical with the controller attached
vs detached (the control plane steers placement and fleet size, never
math).

`--disagg-ab` adds the disaggregated prefill/decode A/B (schema
v16): a deterministic virtual-time replay of a mixed trace — a
steady decode-heavy floor of short requests plus a burst of LONG
prompts sharing one system prefix — through (a) a mixed 2-replica
fleet routed by load, where long prefill chunks pack into the same
unified steps the shorts decode through, and (b) the same two
engines split into a PREFILL specialist and a DECODE specialist
joined by the fleet KV fabric: the prefill engine's committed pages
ship as REAL transfer frames (engine.export_prefix_frame ->
import_prefix_frame, the wire bytes in the report) and the
continuation decodes where it never shares a step with a long
chunk. A restart-warmth leg snapshots a served engine's whole tree
(export_prefix_state), imports it into a FRESH engine, and compares
the next turn's TTFT against the warm donor and a cold engine. The
script ASSERTS client-observed TTFT p99 AND inter-token p99 BOTH
improve in the disagg arm, per-request token identity between arms,
and restored-TTFT at warm-hit cost, well under cold.

`--quant-ab` adds the quantized-serving A/B: the SAME burst trace
(every request arrives at t=0 — admission is page-limited, the shape
the residents-per-HBM-byte economics show up in) runs once with the
paged KV pool in fp and once in int8, both arms sized to the SAME HBM
page-byte budget. int8 code+scale pages cost ~half (CPU f32: ~1/6)
the bytes of fp pages, so the same budget buys proportionally more
pages — more concurrent residents, no queue-starved fp stragglers.
The report's "quant" section records per-arm tokens/s,
residents-at-peak, tokens-per-s-per-HBM-GB, the arms' token agreement
and the max next-token logit drift of an int8 vs fp paged prefill
through the model — and ASSERTS >= 1.5x residents at peak with int8
on, drift under the pinned epsilon, and no tokens/s regression.

`--obs-ab` adds the observability A/B (schema v14): the SAME Poisson
trace once with the WHOLE observability stack — the obs layer
(serving/obs.py: request-lifecycle tracer + flight recorder) AND the
PR-15 SLO tracker + cost census (serving/slo.py) — OFF and once ON.
Both arms collect every emitted token; the report's "obs" section
records per-arm tokens/s, the recorder's step/timeline counts, the
on arm's cost census (captured exactly once per compile, asserted),
its mean/max achieved utilization and its worst SLO state — and the
script ASSERTS the arms are token-identical, the on arm's tokens/s
is within the 3% noise pin of the off arm's (observability must be
free), the flight ring actually recorded the trace's steps, and that
`scripts/flight_dump.py` renders the on arm's ring into a non-empty
per-step table (the CI smoke of the postmortem tooling).

Every non-`--out -` run also APPENDS one line to
`BENCH_history.jsonl` next to the report — timestamp, git rev,
schema, and each produced section's headline tokens/s — so the
bench trajectory is an append-only series, with a stderr warning
when a section's headline drops > 10% vs the previous entry (the
regression sentinel).

`--lora-ab` adds the multi-tenant LoRA A/B (schema v13): a
mixed-tenant Poisson trace — K registered adapters under zipf
popularity plus base-model rows — runs (a) BATCHED through one
adapters-enabled engine (every tenant in the same unified step,
per-row gathered A/B deltas, a deliberately undersized paged adapter
pool so evict/spill churn is exercised) vs (b) the naive
merge-weights-per-tenant SERIAL fleet. The report's "lora" section
records per-arm tokens/s, the pool's load/evict/spill traffic and
the throughput ratio — and asserts every tenant's stream is
bit-token-identical to its dense-merged oracle and that the batched
arm strictly beats the serial fleet on tokens/s.

`--tp-ab` adds the multi-chip tensor-parallel A/B (schema v12): the
SAME burst trace through ONE replica on one device (mp=1, the oracle)
and through ONE replica spanning a dp1xmp2 mesh of simulated devices
(serving/tp.py: KV pools sharded over the kv-head axis, QKV
projections over whole heads, control plane replicated — the step
stays ONE compiled program). Both arms are sized to the SAME
PER-CHIP page-byte budget: each mp=2 chip holds a 1/mp slice of
every page, so the same per-chip bytes buy 2x the pages — more
concurrent residents per chip-HBM byte, the whole point of spanning
chips. The report's "tp" section records per-arm tokens/s,
residents-at-peak, the per-chip page bytes, and the sharded step's
compiled-HLO collective census — and the script ASSERTS the arms are
bit-token-identical (all-gathers never reassociate fp math), >= 1.5x
residents at the same per-chip budget, zero all-reduces, and exactly
ONE output all-gather per layer per step. CPU simulation caveat: the
mesh, shardings, collectives and token identity are real; per-chip
HBM bandwidth is modeled, the real-chip multi-host run is the
ROADMAP's open measurement.

`--prefix-share P` builds a shared-prefix trace instead of fully
random prompts: fraction P of the requests prepend one of K
(`--prefix-prompts`) fixed "system prompts" to their unique tail —
the traffic shape the automatic prefix cache (serving/prefix.py)
exists for. The SAME trace then runs once with the cache ON and once
OFF, and the report's "prefix" section records TTFT and
prefill-steps-per-request for both (plus hit rate / cached tokens),
so the cache's win is a number in the trajectory, not a claim.

`--prefix-share` also runs the GROUPED-vs-FLAT attention A/B (the
report's "grouped" section): the SAME shared-prefix trace, prefix
cache on both times, once with the prefix-sharing-aware grouped page
walk (PADDLE_TPU_GROUPED_ATTN, default on — shared pages stream from
HBM once per group) and once with the flat per-row walk. Both arms
collect every emitted token; the script ASSERTS the arms are
token-identical, that the grouped arm's modeled page-block reads per
step (counted by the CPU reference, `page_block_reads_total`) are
strictly below the flat arm's, and that tokens/s does not regress.
The saved-reads total and the per-step group-size histogram land in
the section — the ~Nx HBM claim as a number (CPU models the traffic;
the real-chip A/B is the ROADMAP's open measurement).

Usage:
    python scripts/serving_bench.py            # platform-sized run
    python scripts/serving_bench.py --smoke    # seconds-fast CI run
    python scripts/serving_bench.py --requests 64 --rate 50 --slots 8
    python scripts/serving_bench.py --prefix-share 0.8 --smoke
    python scripts/serving_bench.py --chaos --smoke  # replica-kill A/B
    python scripts/serving_bench.py --http --replicas 2   # + loopback
        # HTTP trace through serving/http (mixed SSE / non-stream
        # clients): client-observed TTFT p50/p99 and tokens/s land
        # under the report's "http" key, alongside the in-process
        # numbers
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "default")


def build_model(on_tpu: bool):
    import paddle_tpu as paddle
    from paddle_tpu.nlp import GPTConfig, GPTForCausalLM

    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768,
                        num_hidden_layers=12, num_attention_heads=12,
                        max_position_embeddings=2048,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
    else:
        cfg = GPTConfig(vocab_size=256, hidden_size=64,
                        num_hidden_layers=2, num_attention_heads=4,
                        intermediate_size=128,
                        max_position_embeddings=256,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
    model.eval()
    return model, cfg


# -- bench trajectory (BENCH_history.jsonl) ---------------------------------
# one line per bench run: timestamp, git rev, schema, platform, and
# the headline tokens/s of every section the run produced — so the
# bench trajectory is an append-only series instead of a single
# overwritten report, and a regression shows up as a dip in the file
# rather than a vanished number.
_SECTION_HEADLINES = {
    # section -> headline extractor (tokens/s-shaped number); missing
    # sections are simply absent from the entry
    "serving": lambda r: r.get("tokens_per_sec"),
    "unified": lambda r: r["unified"]["on"]["tokens_per_sec"],
    "spec": lambda r: r["spec"]["on"]["tokens_per_sec"],
    "fused": lambda r: r["fused"]["on"]["tokens_per_sec"],
    "obs": lambda r: r["obs"]["on"]["tokens_per_sec"],
    "grouped": lambda r: r["grouped"]["on"]["tokens_per_sec"],
    "quant": lambda r: r["quant"]["int8"]["tokens_per_sec"],
    "lora": lambda r: r["lora"]["batched"]["tokens_per_sec"],
    "tp": lambda r: r["tp"]["mp2"]["tokens_per_sec"],
    "http": lambda r: r["http"]["tokens_per_sec"],
    "chaos": lambda r: r["chaos"]["goodput_tokens_per_sec"],
    "autoscale": lambda r: r["autoscale"]["auto"][
        "tokens_per_virtual_s"],
    "disagg": lambda r: r["disagg"]["disagg"][
        "tokens_per_virtual_s"],
}

# a section's headline dropping more than this vs the PREVIOUS entry
# trips the regression sentinel (a stderr warning, not a hard fail —
# CPU smoke numbers are noisy; the trajectory is the evidence)
HISTORY_REGRESSION_FRACTION = 0.10


def _git_rev() -> str:
    import subprocess
    try:
        return subprocess.check_output(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
            stderr=subprocess.DEVNULL).decode().strip()
    except Exception:
        return "unknown"


def bench_history_entry(report: dict, *, t: float = None) -> dict:
    """One append-only trajectory line for `report`: schema, git rev,
    timestamp, and each produced section's headline tokens/s."""
    sections = {}
    for name, get in _SECTION_HEADLINES.items():
        if name != "serving" and name not in report:
            continue
        try:
            v = get(report)
        except (KeyError, TypeError):
            continue
        if v is not None:
            sections[name] = round(float(v), 4)
    t = time.time() if t is None else t
    return {"t": round(t, 3),
            "iso": time.strftime("%Y-%m-%dT%H:%M:%S",
                                 time.gmtime(t)) + "Z",
            "git_rev": _git_rev(),
            "schema_version": report.get("schema_version"),
            "platform": report.get("platform"),
            "requests": report.get("requests"),
            "sections": sections}


def check_history_regression(prev: dict, entry: dict,
                             threshold: float =
                             HISTORY_REGRESSION_FRACTION) -> list:
    """Warnings for every section whose headline dropped more than
    `threshold` vs `prev` (same-schema comparisons only would be too
    strict — the headline meaning is stable across schemas)."""
    warnings = []
    prev_s = prev.get("sections") or {}
    for name, v in (entry.get("sections") or {}).items():
        old = prev_s.get(name)
        if not old or old <= 0:
            continue
        drop = 1.0 - v / old
        if drop > threshold:
            warnings.append(
                f"bench section '{name}' headline dropped "
                f"{drop:.1%} vs previous entry "
                f"({old} -> {v} tokens/s; rev "
                f"{prev.get('git_rev')} -> {entry.get('git_rev')})")
    return warnings


def append_bench_history(path: str, entry: dict) -> list:
    """Append `entry` to the JSONL trajectory at `path` and return
    regression warnings vs the last prior entry (corrupt/missing
    lines are skipped, never fatal — history must not break the
    bench)."""
    prev = None
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    prev = json.loads(line)
                except ValueError:
                    continue
    except OSError:
        pass
    warnings = (check_history_regression(prev, entry)
                if prev is not None else [])
    with open(path, "a") as f:
        f.write(json.dumps(entry, sort_keys=True) + "\n")
    return warnings


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=None)
    ap.add_argument("--rate", type=float, default=None,
                    help="mean arrivals/sec of the Poisson trace")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--max-len", type=int, default=None)
    ap.add_argument("--max-new", type=int, default=None)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--pages", type=int, default=None,
                    help="pool size; default = dense-equivalent "
                    "(slots * ceil(max_len/page_size) + 1)")
    ap.add_argument("--chunk", type=int, default=None,
                    help="prefill chunk length (compiled shape)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny fast run (CI)")
    ap.add_argument("--prefix-share", type=float, default=0.0,
                    help="fraction of requests that share one of K "
                    "system prompts; > 0 adds a prefix-cache on/off "
                    "A/B over the same trace to the report")
    ap.add_argument("--prefix-prompts", type=int, default=4,
                    help="K: number of distinct shared system prompts")
    ap.add_argument("--unified-ab", action="store_true",
                    help="run the same Poisson trace under a "
                    "long-prompt-heavy mix with the unified ragged "
                    "step on vs off and record the TTFT/stall A/B")
    ap.add_argument("--spec-ab", action="store_true",
                    help="run the same Poisson arrivals over a "
                    "templated/code-heavy prompt mix with "
                    "speculative decoding off vs ngram and record "
                    "the accepted-tokens-per-step / tokens/s A/B "
                    "(token identity asserted), plus a natural-text "
                    "off/ngram/model tier-separation arm (the "
                    "resident draft model must strictly beat ngram "
                    "acceptance there)")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="draft budget per slot per step for "
                    "--spec-ab (the SpecConfig k knob)")
    ap.add_argument("--grammar-ab", action="store_true",
                    help="run the same Poisson arrivals with grammar-"
                    "constrained decoding off vs on (regex structured "
                    "output via the unified step's per-slot mask "
                    "operand) plus a spec+grammar composition arm; "
                    "asserts 100%% schema-valid streams with the "
                    "grammar on, >= 1 invalid stream off, bounded "
                    "tokens/s cost, and > 1.0 accepted tokens/step "
                    "in the composed arm")
    ap.add_argument("--fused-ab", action="store_true",
                    help="run the STANDARD Poisson trace with the "
                    "decode megakernel off vs on (per-layer "
                    "scatter+attend+LoRA fused into one dispatch, "
                    "greedy/spec acceptance as kernel epilogues); "
                    "asserts bit-token-identity across the arms, a "
                    "strictly lower modeled bytes/token, and fewer "
                    "registered-op dispatches per unified step")
    ap.add_argument("--quant-ab", action="store_true",
                    help="run the SAME burst trace with the paged KV "
                    "pool in fp vs int8 under the SAME HBM page-byte "
                    "budget (int8 pages are ~half the bytes, so the "
                    "budget buys more of them) and record the "
                    "residents-per-HBM-byte / tokens-per-s / "
                    "logit-drift A/B; asserts >= 1.5x residents at "
                    "peak with int8 on and bounded drift")
    ap.add_argument("--tp-ab", action="store_true",
                    help="run the SAME burst trace through one "
                    "single-device replica (mp=1 oracle) and one "
                    "replica spanning a dp1xmp2 mesh of simulated "
                    "devices under the SAME per-chip page-byte "
                    "budget; asserts bit-token identity, >= 1.5x "
                    "residents per chip, zero all-reduces and one "
                    "output all-gather per layer in the compiled "
                    "step")
    ap.add_argument("--lora-ab", action="store_true",
                    help="run the multi-tenant LoRA A/B: a mixed-"
                    "tenant Poisson trace (K adapters, zipf "
                    "popularity, plus base-model rows) served (a) "
                    "BATCHED through one adapters-enabled engine — "
                    "every tenant in the same unified step — vs (b) "
                    "the naive merge-weights-per-tenant SERIAL "
                    "fleet; asserts per-tenant token identity to "
                    "the dense-merged oracle, strictly better "
                    "tokens/s than the serial arm, and records the "
                    "adapter-pool load/evict/spill traffic")
    ap.add_argument("--lora-adapters", type=int, default=4,
                    help="K: distinct adapters in the --lora-ab trace")
    ap.add_argument("--lora-rank", type=int, default=4,
                    help="LoRA rank of the --lora-ab adapters")
    ap.add_argument("--obs-ab", action="store_true",
                    help="run the SAME Poisson trace with the "
                    "observability layer (request tracer + flight "
                    "recorder) off vs on; asserts token identity, "
                    "tokens/s within the 3%% noise pin, and that "
                    "flight_dump.py renders the recorded ring")
    ap.add_argument("--overload", action="store_true",
                    help="run the deterministic virtual-time 3x "
                    "overload trace (mixed priorities + deadlines) "
                    "with preemption on vs off and record the "
                    "graceful-degradation A/B")
    ap.add_argument("--overload-scale", type=int, default=1,
                    help="multiply the overload trace's request "
                    "counts (the slow soak uses > 1)")
    ap.add_argument("--autoscale-ab", action="store_true",
                    help="run the deterministic diurnal virtual-time "
                    "autoscaling A/B: a FleetController-steered fleet "
                    "(1..n replicas, graceful drain on the way down) "
                    "vs a peak-provisioned fixed fleet on the SAME "
                    "wave; asserts TTFT p99 within SLO at <= ~0.6x "
                    "the fixed fleet's replica-seconds, no flapping, "
                    "exact token streams, and controller on/off "
                    "bit-identity on a steady trace")
    ap.add_argument("--autoscale-max", type=int, default=4,
                    help="fleet ceiling (and the fixed arm's size) "
                    "for --autoscale-ab")
    ap.add_argument("--disagg-ab", action="store_true",
                    help="run the deterministic virtual-time "
                    "disaggregated prefill/decode A/B over the fleet "
                    "KV fabric: a mixed 2-replica fleet vs a prefill "
                    "specialist handing committed pages to a decode "
                    "specialist as real transfer frames, plus the "
                    "warm-restart (export/import_prefix_state) TTFT "
                    "comparison; asserts TTFT p99 AND inter-token "
                    "p99 both improve, per-request token identity "
                    "between arms, and restart TTFT at warm-hit cost")
    ap.add_argument("--http", action="store_true",
                    help="also drive the serving/http front-end over "
                    "loopback with the same Poisson trace")
    ap.add_argument("--chaos", action="store_true",
                    help="replay the trace through 2 HTTP replicas "
                    "fault-free AND with an injected replica kill "
                    "mid-load; asserts zero truncated streams")
    ap.add_argument("--replicas", type=int, default=2,
                    help="router replicas for --http")
    ap.add_argument("--out", default="BENCH_serving.json",
                    help="report path ('-' = print only)")
    args = ap.parse_args()

    if args.tp_ab:
        # the TP arm needs >= 2 devices; on a CPU-only machine force
        # the virtual 8-device mesh BEFORE jax initializes (the
        # tests/conftest.py strategy — a no-op when the flag is
        # already set, e.g. under pytest)
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    import jax
    import paddle_tpu as paddle  # noqa: F401
    from paddle_tpu.serving import SamplingParams, ServingEngine

    on_tpu = jax.devices()[0].platform == "tpu"
    model, cfg = build_model(on_tpu)

    if args.smoke:
        n_req = args.requests or 6
        rate = args.rate or 200.0
        max_new = args.max_new or 6
        max_len = args.max_len or 64
        chunk = args.chunk or 16
        prompt_lens = [3, 5, 8]
        long_prompt_lens = [3, 30, 40, 45]
        prefix_len = 24
    elif on_tpu:
        n_req = args.requests or 128
        rate = args.rate or 32.0
        max_new = args.max_new or 128
        max_len = args.max_len or 1024
        chunk = args.chunk or 128
        prompt_lens = [32, 64, 128, 256]
        long_prompt_lens = [32, 384, 512, 768]
        prefix_len = 256
    else:
        n_req = args.requests or 24
        rate = args.rate or 100.0
        max_new = args.max_new or 16
        max_len = args.max_len or 128
        chunk = args.chunk or 32
        prompt_lens = [4, 8, 12, 16]
        long_prompt_lens = [6, 60, 80, 100]
        prefix_len = 40

    rng = np.random.RandomState(args.seed)
    gaps = rng.exponential(1.0 / rate, size=n_req)
    arrivals = np.cumsum(gaps)               # seconds from t0
    share = float(args.prefix_share)
    if not (0.0 <= share <= 1.0):
        raise SystemExit("--prefix-share must be in [0, 1]")
    sys_prompts = [rng.randint(0, cfg.vocab_size,
                               size=prefix_len).astype(np.int64)
                   for _ in range(max(1, args.prefix_prompts))]
    prompts = []
    for _ in range(n_req):
        tail = rng.randint(0, cfg.vocab_size,
                           size=rng.choice(prompt_lens)).astype(np.int64)
        if share > 0.0 and rng.random_sample() < share:
            tail = np.concatenate(
                [sys_prompts[rng.randint(len(sys_prompts))], tail])
        prompts.append(tail)
    budgets = rng.randint(max(1, max_new // 2), max_new + 1, size=n_req)

    # the A/B: the SAME trace (arrivals, prompts, budgets) once per
    # paged-attention implementation, kernel first (the default)
    runs = {}
    for attn_impl in ("kernel", "gather"):
        runs[attn_impl] = run_trace(
            model, arrivals, prompts, budgets, slots=args.slots,
            max_len=max_len, page_size=args.page_size, pages=args.pages,
            chunk=chunk, attn_impl=attn_impl)

    # the unified-step A/B: the SAME arrivals under a LONG-PROMPT-HEAVY
    # mix (the traffic shape whose prefill chunks stall every resident
    # decoder on the alternating path) once with the unified ragged
    # step on, once off
    unified_runs = {}
    if args.unified_ab:
        # TTFT-focused load spike: more requests than slots arriving in
        # a burst (10x the base rate), long prompts, tiny output
        # budgets — the prefill-stall scenario whose TTFT spikes the
        # unified step exists to kill. Both runs replay the SAME
        # arrivals/prompts/budgets; only the step architecture differs.
        uni_n = max(n_req, 2 * args.slots)
        uni_arrivals = np.cumsum(
            rng.exponential(1.0 / (rate * 10.0), size=uni_n))
        long_prompts = [
            rng.randint(0, cfg.vocab_size,
                        size=rng.choice(long_prompt_lens))
            .astype(np.int64) for _ in range(uni_n)]
        ttft_budgets = rng.randint(1, 3, size=uni_n)
        for flag in (True, False):
            # best-of-2 per arm by TTFT p99: a single OS/GC hiccup in
            # a sub-100ms replay poisons a p99 of max-of-N samples;
            # the MIN across repeats is the stable statistic (same
            # convention as op_bench / decode_roofline timing)
            attempts = [run_trace(
                model, uni_arrivals, long_prompts, ttft_budgets,
                slots=args.slots, max_len=max_len,
                page_size=args.page_size, pages=args.pages,
                chunk=chunk, attn_impl="kernel", unified=flag)
                for _ in range(2)]
            unified_runs["on" if flag else "off"] = min(
                attempts,
                key=lambda r: r["snap"]["ttft_s"]["p99"] or 0.0)

    # the speculative-decoding A/B: the SAME Poisson arrivals over a
    # TEMPLATED/CODE-HEAVY prompt mix (repeating template blocks — the
    # shape prompt-lookup drafting wins on) once with speculation off,
    # once with the ngram drafter on. Both arms collect every emitted
    # token so the report can ASSERT the arms are token-identical.
    spec_runs = {}
    spec_n = spec_max_new = 0
    if args.spec_ab:
        if args.smoke:
            spec_max_new, tpl_len, tpl_reps = 16, 6, 3
        elif on_tpu:
            spec_max_new, tpl_len, tpl_reps = 96, 32, 4
        else:
            spec_max_new, tpl_len, tpl_reps = 24, 8, 3
        spec_n = max(n_req, 2 * args.slots)
        spec_arrivals = np.cumsum(
            rng.exponential(1.0 / rate, size=spec_n))
        templates = [rng.randint(0, cfg.vocab_size, size=tpl_len)
                     .astype(np.int64) for _ in range(2)]
        spec_prompts = []
        for _ in range(spec_n):
            head = rng.randint(0, cfg.vocab_size,
                               size=int(rng.randint(1, 4))
                               ).astype(np.int64)
            tpl = templates[rng.randint(len(templates))]
            spec_prompts.append(
                np.concatenate([head, np.tile(tpl, tpl_reps)]))
        spec_budgets = np.full(spec_n, spec_max_new)
        for mode in ("off", "on"):
            # best-of-3 per arm by tokens/s (the unified A/B's
            # hiccup-absorbing convention, one repeat deeper: the
            # spec arms' sub-second replays are the most
            # OS-jitter-sensitive sections in the file); tokens are
            # identical across attempts, so either attempt's list
            # works for the identity check
            attempts = [run_trace(
                model, spec_arrivals, spec_prompts, spec_budgets,
                slots=args.slots, max_len=max_len,
                page_size=args.page_size, pages=args.pages,
                chunk=chunk, attn_impl="kernel",
                spec=(False if mode == "off"
                      else f"ngram:{args.spec_k}"),
                collect_tokens=True) for _ in range(3)]
            for a in attempts[1:]:
                assert a["tokens"] == attempts[0]["tokens"], \
                    "spec arm not deterministic across repeats"
            spec_runs[mode] = max(
                attempts,
                key=lambda r: r["snap"]["tokens_per_sec"] or 0.0)
        # the NATURAL-TEXT tier-separation arm (PR 20): the same
        # Poisson discipline over NON-templated random prompts — the
        # traffic shape prompt-lookup collapses on (no repeated
        # n-grams to match) but the resident draft MODEL, which
        # shares the target's own early layers, keeps drafting.
        # Three arms on identical arrivals: off (the oracle), the
        # ngram drafter, the model drafter. The report pins the
        # separation: model accepted-tokens-per-step strictly above
        # ngram's, model tokens bit-identical to off, no tokens/s
        # regression.
        nat_arrivals = np.cumsum(
            rng.exponential(1.0 / rate, size=spec_n))
        nat_prompts = [
            rng.randint(0, cfg.vocab_size,
                        size=int(rng.randint(4, 12)))
            .astype(np.int64) for _ in range(spec_n)]
        nat_budgets = np.full(spec_n, max(8, spec_max_new // 2))
        for mode in ("off", "ngram", "model"):
            attempts = [run_trace(
                model, nat_arrivals, nat_prompts, nat_budgets,
                slots=args.slots, max_len=max_len,
                page_size=args.page_size, pages=args.pages,
                chunk=chunk, attn_impl="kernel",
                spec=(False if mode == "off"
                      else f"{mode}:{args.spec_k}"),
                collect_tokens=True) for _ in range(3)]
            for a in attempts[1:]:
                assert a["tokens"] == attempts[0]["tokens"], \
                    "natural spec arm not deterministic across repeats"
            spec_runs[f"nat_{mode}"] = max(
                attempts,
                key=lambda r: r["snap"]["tokens_per_sec"] or 0.0)

    # the decode-megakernel A/B: the STANDARD Poisson trace (the same
    # arrivals/prompts/budgets the main serving run replays) once with
    # the fused decode megakernel off, once on. Fusion is bit-exact by
    # construction, so the arms must emit identical tokens; the
    # numbers that CAN move — dispatches per unified step and modeled
    # page-walk bytes/token — come from the launch-count probe and
    # the fused-byte census riding each run's cost-census record.
    fused_runs = {}
    if args.fused_ab:
        for mode in ("off", "on"):
            # best-of-2 per arm by tokens/s (the spec A/B's
            # hiccup-absorbing convention); tokens are identical
            # across attempts, asserted
            attempts = [run_trace(
                model, arrivals, prompts, budgets, slots=args.slots,
                max_len=max_len, page_size=args.page_size,
                pages=args.pages, chunk=chunk, attn_impl="kernel",
                megakernel=(mode == "on"),
                collect_tokens=True) for _ in range(2)]
            for a in attempts[1:]:
                assert a["tokens"] == attempts[0]["tokens"], \
                    "fused arm not deterministic across repeats"
            fused_runs[mode] = max(
                attempts,
                key=lambda r: r["snap"]["tokens_per_sec"] or 0.0)

    # the grammar-constrained-decoding A/B: the SAME Poisson arrivals
    # over a templated prompt mix, three arms — unconstrained ("off"),
    # grammar-on ("on"), and grammar COMPOSED with speculative
    # decoding ("spec"). The grammar is a regex over token strings
    # (chr-identity vocab); the off arm replays the same trace/EOS so
    # the only delta is the per-slot mask operand riding the unified
    # step. Tokens are collected so the report can VALIDATE every
    # constrained stream against the grammar and show the off arm
    # does emit invalid ones.
    gram_runs = {}
    gram_n = gram_max_new = 0
    gram_spec_obj = gram_eos = None
    if args.grammar_ab:
        from paddle_tpu.serving import GrammarSpec
        gram_max_new = 12 if args.smoke else (48 if on_tpu else 16)
        gram_n = max(n_req, 2 * args.slots)
        gram_eos = cfg.vocab_size - 1
        gram_spec_obj = GrammarSpec(kind="regex", pattern="[A-C]+")
        gram_arrivals = np.cumsum(
            rng.exponential(1.0 / rate, size=gram_n))
        # templated prompts biased into the A-C token band so the
        # ngram drafter's proposals often ALREADY satisfy the grammar
        # (that overlap is what keeps the composed arm's acceptance
        # above 1.0 accepted tokens/step)
        gram_tpl = (np.asarray([ord("A"), ord("B"), ord("C")],
                               np.int64))
        gram_prompts = []
        for _ in range(gram_n):
            head = rng.randint(0, cfg.vocab_size,
                               size=int(rng.randint(1, 4))
                               ).astype(np.int64)
            gram_prompts.append(
                np.concatenate([head, np.tile(gram_tpl, 4)]))
        gram_budgets = np.full(gram_n, gram_max_new)
        for mode in ("off", "on", "spec"):
            # best-of-2 per arm by tokens/s (hiccup-absorbing, same
            # convention as the spec A/B); each arm is deterministic
            # across repeats, asserted below
            attempts = [run_trace(
                model, gram_arrivals, gram_prompts, gram_budgets,
                slots=args.slots, max_len=max_len,
                page_size=args.page_size, pages=args.pages,
                chunk=chunk, attn_impl="kernel",
                grammar=(mode != "off"),
                grammar_spec=(None if mode == "off"
                              else gram_spec_obj),
                eos=gram_eos,
                spec=(f"ngram:{args.spec_k}" if mode == "spec"
                      else False),
                collect_tokens=True) for _ in range(2)]
            for a in attempts[1:]:
                assert a["tokens"] == attempts[0]["tokens"], \
                    "grammar arm not deterministic across repeats"
            gram_runs[mode] = max(
                attempts,
                key=lambda r: r["snap"]["tokens_per_sec"] or 0.0)

    # the observability A/B: a DETERMINISTIC burst replay (every
    # request arrives at t=0, so both arms run the exact same engine
    # steps — a wall-clock Poisson replay would let arrival jitter
    # change the step count between arms) with the obs layer off vs
    # on. Tokens collected so the "observability never changes
    # output" claim is asserted; best-of-5 per arm by TRACE wall time
    # (the min absorbs OS hiccups in a sub-second CPU replay) so the
    # 3% cost pin measures the layer, not scheduler noise.
    obs_runs = {}
    obs_n = 0
    if args.obs_ab:
        obs_n = max(n_req, 4 * args.slots)
        obs_arrivals = np.zeros(obs_n)
        obs_prompts = [prompts[i % len(prompts)] for i in range(obs_n)]
        obs_budgets = np.asarray([budgets[i % len(budgets)]
                                  for i in range(obs_n)])
        for mode in ("off", "on"):
            # the off arm turns the WHOLE observability stack off —
            # obs layer, SLO tracker AND cost census — so the pin
            # prices everything PR 12 + PR 15 added to the hot path
            attempts = [run_trace(
                model, obs_arrivals, obs_prompts, obs_budgets,
                slots=args.slots, max_len=max_len,
                page_size=args.page_size, pages=args.pages,
                chunk=chunk, attn_impl="kernel", obs=(mode == "on"),
                slo=(None if mode == "on" else False),
                cost_census=(None if mode == "on" else False),
                collect_tokens=True) for _ in range(5)]
            for a in attempts[1:]:
                assert a["tokens"] == attempts[0]["tokens"], \
                    "obs arm not deterministic across repeats"
            obs_runs[mode] = min(attempts,
                                 key=lambda r: r["wall_s"])

    # the prefix-cache A/B: the SAME shared-prefix trace with the
    # radix cache on vs off (cache pre-warmed with the K system
    # prompts — steady-state behavior, not cold-start compile noise)
    prefix_runs = {}
    grouped_runs = {}
    if share > 0.0:
        for flag in (True, False):
            prefix_runs["on" if flag else "off"] = run_trace(
                model, arrivals, prompts, budgets, slots=args.slots,
                max_len=max_len, page_size=args.page_size,
                pages=args.pages, chunk=chunk, attn_impl="kernel",
                prefix_cache=flag, warm_prompts=sys_prompts)
        # the grouped-vs-flat attention A/B: same trace, cache ON both
        # times (groups only exist where pages are shared), once with
        # the grouped page walk and once flat. Tokens collected so the
        # bit-identity claim is asserted, not assumed. Best-of-2 per
        # arm by tokens/s (the hiccup-absorbing convention of the
        # other A/Bs — a sub-second CPU replay's throughput is OS
        # noise; the read counts are deterministic across attempts).
        for flag in (True, False):
            attempts = [run_trace(
                model, arrivals, prompts, budgets, slots=args.slots,
                max_len=max_len, page_size=args.page_size,
                pages=args.pages, chunk=chunk, attn_impl="kernel",
                prefix_cache=True, warm_prompts=sys_prompts,
                grouped=flag, collect_tokens=True) for _ in range(2)]
            for a in attempts[1:]:
                assert a["tokens"] == attempts[0]["tokens"], \
                    "grouped arm not deterministic across repeats"
            grouped_runs["on" if flag else "off"] = max(
                attempts,
                key=lambda r: r["snap"]["tokens_per_sec"] or 0.0)

    snap = runs["kernel"]["snap"]
    pool = snap["pool"]

    def _ms(v):
        return None if v is None else round(v * 1e3, 4)

    def _ab(run):
        s = run["snap"]
        return {
            "wall_s": round(run["wall_s"], 4),
            "tokens_per_sec": s["tokens_per_sec"],
            "ttft_p50_s": s["ttft_s"]["p50"],
            "ttft_p99_s": s["ttft_s"]["p99"],
            "decode_steps": s["decode_steps"],
            "decode_step_ms_p50": _ms(s["decode_step_s"]["p50"]),
            "decode_step_ms_p99": _ms(s["decode_step_s"]["p99"]),
            "completed": s["requests"]["completed"],
        }

    def _unified_summary(run):
        s = run["snap"]
        packed = s.get("packed_tokens_per_step") or {}
        return {
            "wall_s": round(run["wall_s"], 4),
            "tokens_per_sec": s["tokens_per_sec"],
            "ttft_p50_s": s["ttft_s"]["p50"],
            "ttft_p99_s": s["ttft_s"]["p99"],
            "inter_token_p99_s": s["inter_token_s"]["p99"],
            "decode_steps": s["decode_steps"],
            "unified_steps": s["unified_steps"],
            "prefill_stall_steps": s["prefill_stall_steps"],
            "packed_tokens_per_step_mean": packed.get("mean"),
            "packed_tokens_per_step_max": packed.get("max"),
            "completed": s["requests"]["completed"],
        }

    def _spec_summary(run):
        s = run["snap"]
        burst = s.get("spec_tokens_per_step") or {}
        return {
            "wall_s": round(run["wall_s"], 4),
            "tokens_per_sec": s["tokens_per_sec"],
            "ttft_p50_s": s["ttft_s"]["p50"],
            "inter_token_p50_s": s["inter_token_s"]["p50"],
            "unified_steps": s["unified_steps"],
            "spec_drafted_tokens": s.get("spec_drafted_tokens", 0),
            "spec_accepted_tokens": s.get("spec_accepted_tokens", 0),
            "accepted_tokens_per_step": burst.get("mean"),
            "completed": s["requests"]["completed"],
        }

    def _prefix_summary(run):
        s = run["snap"]
        n = s["requests"]["completed"] or 1
        pf = s.get("prefix") or {}
        return {
            "wall_s": round(run["wall_s"], 4),
            "ttft_p50_s": s["ttft_s"]["p50"],
            "ttft_p99_s": s["ttft_s"]["p99"],
            "prefill_chunks": s["prefill_chunks"],
            "prefill_chunks_per_request": s["prefill_chunks"] / n,
            "hit_rate": pf.get("hit_rate"),
            "cached_tokens": pf.get("cached_tokens", 0),
            "evicted_pages": pf.get("evicted_pages", 0),
            "cow_copies": pf.get("cow_copies", 0),
            "completed": s["requests"]["completed"],
        }

    report = {
        "bench": "serving",
        "schema_version": 19,
        "platform": jax.devices()[0].platform,
        "attn_impl": "kernel",
        "requests": n_req,
        "slots": args.slots,
        "max_len": max_len,
        "page_size": runs["kernel"]["page_size"],
        "num_pages": runs["kernel"]["num_pages"],
        "chunk_len": runs["kernel"]["chunk_len"],
        "arrival_rate_per_s": rate,
        "wall_s": round(runs["kernel"]["wall_s"], 4),
        "tokens_generated": snap["tokens_generated"],
        "tokens_per_sec": snap["tokens_per_sec"],
        "ttft_p50_s": snap["ttft_s"]["p50"],
        "ttft_p99_s": snap["ttft_s"]["p99"],
        "inter_token_p50_s": snap["inter_token_s"]["p50"],
        "decode_step_ms_p50": _ms(snap["decode_step_s"]["p50"]),
        "decode_step_ms_p99": _ms(snap["decode_step_s"]["p99"]),
        "queue_wait_p99_s": snap["queue_wait_s"]["p99"],
        "occupancy_mean": snap["occupancy_hist"]["mean"],
        "pool_utilization_mean": pool["utilization"]["mean"],
        "pool_utilization_max": pool["utilization"]["max"],
        "prefill_chunks": snap["prefill_chunks"],
        "prefill_stall_p99": snap["prefill_stall_hist"]["p99"],
        "decode_steps": snap["decode_steps"],
        "completed": snap["requests"]["completed"],
        "ab": {impl: _ab(run) for impl, run in runs.items()},
        # hit-rate/cached-token trajectory of the default (cache-on)
        # kernel run — nonzero only when the trace actually shares
        "prefix_stats": snap.get("prefix"),
    }
    if unified_runs:
        report["unified"] = {
            "long_prompt_lens": [int(x) for x in long_prompt_lens],
            "requests": uni_n,
            **{flag: _unified_summary(run)
               for flag, run in unified_runs.items()},
        }
    if spec_runs:
        on_s, off_s = (_spec_summary(spec_runs["on"]),
                       _spec_summary(spec_runs["off"]))
        ratio = (None if not off_s["tokens_per_sec"]
                 else (on_s["tokens_per_sec"] or 0.0)
                 / off_s["tokens_per_sec"])
        report["spec"] = {
            "requests": spec_n,
            "k": args.spec_k,
            "max_new": spec_max_new,
            "trace": "templated",
            "off": off_s,
            "on": on_s,
            "accepted_tokens_per_step":
                on_s["accepted_tokens_per_step"],
            "acceptance_rate": (
                None if not on_s["spec_drafted_tokens"]
                else on_s["spec_accepted_tokens"]
                / on_s["spec_drafted_tokens"]),
            "tokens_per_sec_ratio": ratio,
            "token_identical": (spec_runs["on"]["tokens"]
                                == spec_runs["off"]["tokens"]),
        }

        def _aps(s):
            # accepted tokens per unified step — robust when an arm's
            # burst histogram is empty (ngram on natural text)
            return (s["spec_accepted_tokens"]
                    / max(1, s["unified_steps"]))

        n_off = _spec_summary(spec_runs["nat_off"])
        n_ngram = _spec_summary(spec_runs["nat_ngram"])
        n_model = _spec_summary(spec_runs["nat_model"])
        report["spec"]["natural"] = {
            "trace": "natural",
            "requests": spec_n,
            "k": args.spec_k,
            "max_new": int(nat_budgets[0]),
            "off": n_off,
            "ngram": n_ngram,
            "model": n_model,
            "model_accepted_tokens_per_step": _aps(n_model),
            "ngram_accepted_tokens_per_step": _aps(n_ngram),
            "model_token_identical": (
                spec_runs["nat_model"]["tokens"]
                == spec_runs["nat_off"]["tokens"]),
            "ngram_token_identical": (
                spec_runs["nat_ngram"]["tokens"]
                == spec_runs["nat_off"]["tokens"]),
            "model_tokens_per_sec_ratio": (
                None if not n_off["tokens_per_sec"]
                else (n_model["tokens_per_sec"] or 0.0)
                / n_off["tokens_per_sec"]),
        }
    if fused_runs:
        def _fused_summary(run):
            s = run["snap"]
            cen = run.get("census") or {}
            disp = cen.get("unified_dispatch") or {}
            walk = cen.get("page_walk") or {}
            bpt = walk.get("modeled_bytes_per_token") or {}
            return {
                "wall_s": round(run["wall_s"], 4),
                "tokens_per_sec": s["tokens_per_sec"],
                "decode_step_ms_p50": _ms(s["decode_step_s"]["p50"]),
                # the two referees: registered-op dispatches in the
                # one traced step, and the arm's OWN modeled
                # bytes/token lane (fused model under the megakernel,
                # unfused otherwise)
                "dispatch_ops_per_step": disp.get("total"),
                "modeled_bytes_per_token": (
                    bpt.get("fused") if walk.get("megakernel")
                    else bpt.get("unfused")),
                "completed": s["requests"]["completed"],
            }

        f_off, f_on = (_fused_summary(fused_runs["off"]),
                       _fused_summary(fused_runs["on"]))
        report["fused"] = {
            "requests": n_req,
            "trace": "standard",
            "off": f_off,
            "on": f_on,
            "dispatch_ops_saved":
                (f_off["dispatch_ops_per_step"] or 0)
                - (f_on["dispatch_ops_per_step"] or 0),
            "modeled_bytes_per_token_ratio": (
                None if not f_off["modeled_bytes_per_token"]
                else (f_on["modeled_bytes_per_token"] or 0.0)
                / f_off["modeled_bytes_per_token"]),
            "token_identical": (fused_runs["on"]["tokens"]
                                == fused_runs["off"]["tokens"]),
        }
    if gram_runs:
        def _gram_summary(run):
            s = run["snap"]
            burst = s.get("spec_tokens_per_step") or {}
            valid = sum(
                1 for toks in run["tokens"]
                if gram_spec_obj.validates(
                    "".join(chr(t) for t in toks if t != gram_eos)))
            return {
                "wall_s": round(run["wall_s"], 4),
                "tokens_per_sec": s["tokens_per_sec"],
                "ttft_p50_s": s["ttft_s"]["p50"],
                "valid_streams": valid,
                "grammar_requests": s.get("grammar_requests", 0),
                "grammar_masked_steps":
                    s.get("grammar_masked_steps", 0),
                "grammar_masked_rows": s.get("grammar_masked_rows", 0),
                "grammar_rejected_drafts":
                    s.get("grammar_rejected_drafts", 0),
                "accepted_tokens_per_step": burst.get("mean"),
                "completed": s["requests"]["completed"],
            }

        g_off, g_on, g_spec = (_gram_summary(gram_runs["off"]),
                               _gram_summary(gram_runs["on"]),
                               _gram_summary(gram_runs["spec"]))
        g_ratio = (None if not g_off["tokens_per_sec"]
                   else (g_on["tokens_per_sec"] or 0.0)
                   / g_off["tokens_per_sec"])
        report["grammar"] = {
            "requests": gram_n,
            "max_new": gram_max_new,
            "kind": gram_spec_obj.kind,
            "pattern": gram_spec_obj.pattern,
            "eos": int(gram_eos),
            "off": g_off,
            "on": g_on,
            "spec": g_spec,
            "tokens_per_sec_ratio": g_ratio,
        }
    if obs_runs:
        def _obs_summary(run):
            s = run["snap"]
            # trace-level throughput (tokens over the replay wall):
            # both arms emit identical tokens over identical steps,
            # so the ratio is a pure wall-time comparison
            trace_tps = (s["tokens_generated"] / run["wall_s"]
                         if run["wall_s"] > 0 else 0.0)
            return {
                "wall_s": round(run["wall_s"], 4),
                "tokens_per_sec": trace_tps,
                "ttft_p50_s": s["ttft_s"]["p50"],
                "decode_steps": s["decode_steps"],
                "completed": s["requests"]["completed"],
            }

        on_o, off_o = (_obs_summary(obs_runs["on"]),
                       _obs_summary(obs_runs["off"]))
        flight = obs_runs["on"]["flight"]
        tracer = obs_runs["on"]["obs_stats"]["tracer"]
        on_snap = obs_runs["on"]["snap"]
        util = on_snap.get("achieved_util") or {}
        # the flight-dump smoke: the postmortem renderer must turn the
        # on arm's ring into a real per-step table (CI exercises the
        # 3am tooling, not just the recorder)
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from flight_dump import render_flight
        dump_text = render_flight(flight, name="obs-ab")
        dump_rows = [ln for ln in dump_text.splitlines()
                     if ln and ln.lstrip()[:1].isdigit()]
        report["obs"] = {
            "requests": obs_n,
            "trace": "burst",
            "repeats": 5,
            "off": off_o,
            "on": on_o,
            "tokens_per_sec_ratio": (
                None if not off_o["tokens_per_sec"]
                else (on_o["tokens_per_sec"] or 0.0)
                / off_o["tokens_per_sec"]),
            "noise_pin": 0.03,
            "token_identical": (obs_runs["on"]["tokens"]
                                == obs_runs["off"]["tokens"]),
            "flight_steps_recorded": flight["steps_recorded"],
            "flight_ring_capacity": flight["capacity"],
            "timelines_recorded": tracer["timelines"]
            + tracer["timelines_evicted"],
            "timeline_events_recorded": tracer["events_recorded"],
            "flight_dump_rows": len(dump_rows),
            # PR 15: the on arm also ran the SLO tracker + cost
            # census (the off arm ran neither — the pin above prices
            # the whole observability stack)
            "cost_census": obs_runs["on"]["census"],
            "census_captures": obs_runs["on"]["census_captures"],
            "achieved_util_mean": util.get("mean"),
            "achieved_util_max": util.get("max"),
            "slo_worst": (obs_runs["on"].get("slo") or {}).get(
                "worst"),
            "slo_events": (obs_runs["on"].get("slo") or {}).get(
                "events_total"),
        }
    if share > 0.0:
        report["prefix"] = {
            "share": share,
            "system_prompts": len(sys_prompts),
            "prefix_len": prefix_len,
            **{flag: _prefix_summary(run)
               for flag, run in prefix_runs.items()},
        }

        def _grouped_summary(run):
            s = run["snap"]
            steps = max(1, s["unified_steps"])
            gs = s.get("group_size_per_step") or {}
            return {
                "wall_s": round(run["wall_s"], 4),
                "tokens_per_sec": s["tokens_per_sec"],
                "unified_steps": s["unified_steps"],
                "page_block_reads_total":
                    s.get("page_block_reads_total", 0),
                "page_block_reads_per_step":
                    s.get("page_block_reads_total", 0) / steps,
                "shared_page_reads_saved_total":
                    s.get("shared_page_reads_saved_total", 0),
                "group_size_mean": gs.get("mean"),
                "group_size_max": gs.get("max"),
                "completed": s["requests"]["completed"],
            }

        on_g, off_g = (_grouped_summary(grouped_runs["on"]),
                       _grouped_summary(grouped_runs["off"]))
        report["grouped"] = {
            "share": share,
            "on": on_g,
            "off": off_g,
            "reads_per_step_ratio": (
                None if not off_g["page_block_reads_per_step"]
                else on_g["page_block_reads_per_step"]
                / off_g["page_block_reads_per_step"]),
            "tokens_per_sec_ratio": (
                None if not off_g["tokens_per_sec"]
                else (on_g["tokens_per_sec"] or 0.0)
                / off_g["tokens_per_sec"]),
            "token_identical": (grouped_runs["on"]["tokens"]
                                == grouped_runs["off"]["tokens"]),
        }
    if args.quant_ab:
        report["quant"] = quant_trace(
            model, cfg, slots=args.slots, seed=args.seed + 4,
            on_tpu=on_tpu)
    if args.lora_ab:
        report["lora"] = lora_trace(
            model, cfg, slots=args.slots, seed=args.seed + 6,
            on_tpu=on_tpu, k_adapters=args.lora_adapters,
            rank=args.lora_rank)
    if args.tp_ab:
        report["tp"] = tp_trace(
            model, cfg, slots=args.slots, seed=args.seed + 5,
            on_tpu=on_tpu)
    if args.overload:
        report["overload"] = overload_trace(
            model, cfg, slots=args.slots, seed=args.seed + 3,
            scale=max(1, args.overload_scale))
    if args.autoscale_ab:
        report["autoscale"] = autoscale_trace(
            model, cfg, slots=args.slots, seed=args.seed + 7,
            n_max=max(2, args.autoscale_max))
    if args.disagg_ab:
        report["disagg"] = disagg_trace(
            model, cfg, slots=args.slots, seed=args.seed + 8)
    if args.http:
        report["http"] = http_trace(
            model, cfg, n_req=n_req, rate=rate, max_new=max_new,
            max_len=max_len, chunk=chunk, prompt_lens=prompt_lens,
            slots=args.slots, page_size=args.page_size,
            pages=args.pages, replicas=args.replicas,
            seed=args.seed + 1)
    if args.chaos:
        report["chaos"] = chaos_trace(
            model, cfg, n_req=n_req, rate=rate, max_new=max_new,
            max_len=max_len, chunk=chunk, prompt_lens=prompt_lens,
            slots=args.slots, page_size=args.page_size,
            pages=args.pages, seed=args.seed + 2)

    print(json.dumps(report))
    if args.out != "-":
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
            f.write("\n")
        # append this run to the bench trajectory next to the report
        # and warn (stderr, non-fatal) when a section's headline
        # dropped > 10% vs the previous entry
        hist_path = os.path.join(
            os.path.dirname(os.path.abspath(args.out)),
            "BENCH_history.jsonl")
        for w in append_bench_history(hist_path,
                                      bench_history_entry(report)):
            print(f"WARNING: {w}", file=sys.stderr)
    for impl, run in runs.items():
        assert run["snap"]["requests"]["completed"] == n_req, \
            (impl, run["snap"]["requests"], n_req)
    for flag, run in prefix_runs.items():
        assert run["snap"]["requests"]["completed"] == n_req, \
            (flag, run["snap"]["requests"], n_req)
    for flag, run in unified_runs.items():
        assert run["snap"]["requests"]["completed"] == uni_n, \
            (flag, run["snap"]["requests"], uni_n)
    if unified_runs:
        on, off = report["unified"]["on"], report["unified"]["off"]
        # the acceptance numbers: packing really happened, the off
        # path really stalled, and client-observed TTFT p99 does not
        # regress with the unified step on (small tolerance absorbs
        # scheduler-noise on sub-ms CPU smoke steps)
        assert on["prefill_stall_steps"] == 0, report["unified"]
        assert off["prefill_stall_steps"] > 0, report["unified"]
        assert on["packed_tokens_per_step_max"] > 1, report["unified"]
        assert on["ttft_p99_s"] <= off["ttft_p99_s"] * 1.15, \
            report["unified"]
    if spec_runs:
        sp = report["spec"]
        # the acceptance numbers: the two arms emitted EXACTLY the
        # same tokens (draft-then-verify is a pure speedup, never a
        # quality knob), the verify pass really confirmed >1 token
        # per decode-row step on the templated trace, and throughput
        # did not regress with speculation on
        assert sp["token_identical"], "spec on/off token mismatch"
        assert sp["on"]["completed"] == sp["off"]["completed"] \
            == spec_n, sp
        assert sp["accepted_tokens_per_step"] is not None \
            and sp["accepted_tokens_per_step"] > 1.0, sp
        # no tokens/s regression — with the same scheduler-noise pin
        # the grouped/grammar A/Bs use: sub-second smoke arms get the
        # wide pin (at ~0.3s/arm one OS hiccup moves the ratio ~30%),
        # longer arms pin at 15%
        sp_noise = 2.0 if sp["on"]["wall_s"] < 1.0 else 1.15
        assert sp["on"]["tokens_per_sec"] >= \
            sp["off"]["tokens_per_sec"] / sp_noise, sp
        # the natural-text tier separation (PR 20): the model drafter
        # keeps working where n-gram lookup has nothing to match —
        # strictly more accepted tokens per step — while staying
        # bit-identical to the no-spec oracle and at least as fast
        nat = sp["natural"]
        assert nat["model_token_identical"], \
            "model spec natural-text token mismatch"
        assert nat["ngram_token_identical"], \
            "ngram spec natural-text token mismatch"
        assert nat["model_accepted_tokens_per_step"] > \
            nat["ngram_accepted_tokens_per_step"], nat
        assert nat["model"]["completed"] == nat["off"]["completed"] \
            == spec_n, nat
        nat_noise = 2.0 if nat["model"]["wall_s"] < 1.0 else 1.15
        assert nat["model"]["tokens_per_sec"] >= \
            nat["off"]["tokens_per_sec"] / nat_noise, nat
    if fused_runs:
        fu = report["fused"]
        # the acceptance numbers: fusion is a pure plumbing change
        # (bit-token-identical arms, whole trace served both ways),
        # the one program really dispatches FEWER registered ops with
        # the megakernel on, and the modeled page-walk bytes/token
        # strictly drops (stage traffic + per-projection adapter
        # streams folded into the fused pass)
        assert fu["token_identical"], "fused on/off token mismatch"
        assert fu["on"]["completed"] == fu["off"]["completed"] \
            == n_req, fu
        assert fu["dispatch_ops_saved"] > 0, fu
        assert fu["on"]["modeled_bytes_per_token"] is not None \
            and fu["off"]["modeled_bytes_per_token"] is not None \
            and fu["on"]["modeled_bytes_per_token"] \
            < fu["off"]["modeled_bytes_per_token"], fu
    if gram_runs:
        gm = report["grammar"]
        # the acceptance numbers: every constrained stream (grammar on,
        # and grammar composed with spec decode) is 100% schema-valid,
        # the unconstrained arm really emitted at least one invalid
        # stream (the constraint DID something), masking really ran,
        # all three arms served the whole trace, the composed arm's
        # verify pass still confirmed > 1 token per decode-row step
        # (grammar-compatible drafts survive the fused acceptance),
        # and the masked arm's throughput stays within a noise pin of
        # unconstrained (the mask is operand data, not a retrace)
        assert gm["on"]["valid_streams"] == gram_n, gm
        assert gm["spec"]["valid_streams"] == gram_n, gm
        assert gm["off"]["valid_streams"] < gram_n, gm
        assert gm["on"]["completed"] == gm["off"]["completed"] \
            == gm["spec"]["completed"] == gram_n, gm
        assert gm["on"]["grammar_requests"] == gram_n, gm
        assert gm["on"]["grammar_masked_steps"] > 0, gm
        assert gm["off"]["grammar_requests"] == 0, gm
        assert gm["spec"]["accepted_tokens_per_step"] is not None \
            and gm["spec"]["accepted_tokens_per_step"] > 1.0, gm
        # sub-second smoke arms get the wide scheduler-hiccup pin the
        # grouped A/B uses; longer arms pin at 15%
        gm_noise = 2.0 if gm["on"]["wall_s"] < 1.0 else 1.15
        assert gm["tokens_per_sec_ratio"] is not None \
            and gm["tokens_per_sec_ratio"] >= 1.0 / gm_noise, gm
    if obs_runs:
        ob = report["obs"]
        # the acceptance numbers: observability NEVER changes output
        # (bit-token-identical on vs off), both arms served the whole
        # trace, the throughput cost stays inside the 3% noise pin
        # (host-side dict work — if this trips, the layer got onto a
        # hot path), the ring really recorded the trace's steps and
        # every request got a timeline, and the flight-dump renderer
        # produced a row per recorded step
        assert ob["token_identical"], "obs on/off token mismatch"
        assert ob["on"]["completed"] == ob["off"]["completed"] \
            == ob["requests"], ob
        # the burst replay runs the same steps in both arms, so the
        # arms really are comparable — then the cost pin holds
        assert ob["on"]["decode_steps"] == ob["off"]["decode_steps"], ob
        assert ob["tokens_per_sec_ratio"] is not None \
            and ob["tokens_per_sec_ratio"] >= 1.0 - ob["noise_pin"], ob
        assert ob["flight_steps_recorded"] >= ob["on"]["decode_steps"], ob
        assert ob["timelines_recorded"] >= ob["requests"], ob
        assert ob["flight_dump_rows"] >= min(
            ob["flight_steps_recorded"], ob["flight_ring_capacity"]), ob
        # PR 15 acceptance: the cost census was captured EXACTLY once
        # per compiled step, achieved_util landed on every recorded
        # step (0 < mean <= 1), and the SLO tracker really evaluated
        # the trace's events (generous default targets: worst "ok")
        assert ob["cost_census"] is not None \
            and ob["cost_census"]["flops"] > 0, ob
        assert ob["census_captures"] == 1, ob
        assert ob["achieved_util_mean"] is not None \
            and 0.0 < ob["achieved_util_mean"] <= 1.0, ob
        assert ob["slo_events"] and ob["slo_worst"] == "ok", ob
    if share > 0.0:
        on, off = report["prefix"]["on"], report["prefix"]["off"]
        # the acceptance number: a warm cache must do strictly less
        # prefill work per request than no cache on a sharing trace
        assert on["prefill_chunks_per_request"] < \
            off["prefill_chunks_per_request"], report["prefix"]
        assert on["hit_rate"] and on["hit_rate"] > 0, report["prefix"]
        gr = report["grouped"]
        # the grouped-walk acceptance numbers: the two arms emitted
        # EXACTLY the same tokens (grouping is an HBM-traffic hint,
        # never a math change), the grouped arm's modeled page-block
        # reads per step are strictly below the flat arm's (shared
        # pages streamed once per group — the saved-reads counter
        # agrees), groups really formed (mean size > 1), and both
        # arms served the whole trace
        assert gr["token_identical"], "grouped on/off token mismatch"
        assert gr["on"]["completed"] == gr["off"]["completed"] \
            == n_req, gr
        assert gr["on"]["page_block_reads_per_step"] < \
            gr["off"]["page_block_reads_per_step"], gr
        assert gr["on"]["shared_page_reads_saved_total"] > 0, gr
        assert gr["off"]["shared_page_reads_saved_total"] == 0, gr
        assert gr["on"]["group_size_mean"] is not None \
            and gr["on"]["group_size_mean"] > 1.0, gr
        # no tokens/s regression — with the same scheduler-noise
        # tolerance the unified A/B uses: on CPU the smoke run models
        # the HBM traffic (the read counts above are the claim), it
        # cannot observe the bandwidth win itself. Sub-second smoke
        # arms get a wider pin: at ~0.2s/arm a single scheduler
        # hiccup moves the ratio ~30%, drowning the 15% margin.
        gr_noise = 1.5 if gr["on"]["wall_s"] < 1.0 else 1.15
        assert gr["tokens_per_sec_ratio"] is not None \
            and gr["tokens_per_sec_ratio"] >= 1.0 / gr_noise, gr
    if args.http:
        assert report["http"]["completed"] == n_req, report["http"]
    if args.chaos:
        chaos = report["chaos"]
        # the acceptance number: a replica kill mid-load truncates or
        # duplicates ZERO streams — every client got its exact greedy
        # sequence, mid-stream requests migrated to the survivor
        assert chaos["truncated_streams"] == 0, chaos
        assert chaos["completed"] == n_req, chaos
        if chaos["kills_fired"]:
            assert chaos["migrated_streams"] >= 1, chaos
    if args.autoscale_ab:
        az = report["autoscale"]
        # the acceptance numbers (exact — the shared virtual clock
        # makes both arms deterministic): every request in BOTH arms
        # finished with its exact token budget (autoscaling is a
        # capacity move, never a quality knob); the auto arm held
        # TTFT p99 within the SLO target while spending <= ~0.6x the
        # peak-provisioned fleet's replica-seconds; the controller
        # really scaled (up at the peak, back down after) without
        # flapping; and the steady fixed-size trace is bit-token-
        # identical with the controller attached vs detached
        assert az["auto"]["exact_streams"], az["auto"]
        assert az["fixed"]["exact_streams"], az["fixed"]
        assert az["auto"]["completed"] == az["fixed"]["completed"] \
            == az["requests"], az
        assert az["auto"]["ttft_p99_s"] <= az["slo_ttft_p99_s"], az
        assert az["replica_seconds_ratio"] <= 0.6, az
        assert len(az["auto"]["scale_ups"]) >= 1, az
        assert len(az["auto"]["scale_downs"]) >= 1, az
        assert az["flaps"] <= 8, az
        assert az["auto"]["peak_replicas"] <= az["n_max"], az
        assert az["steady"]["identical"], az["steady"]
    if args.disagg_ab:
        dz = report["disagg"]
        # the acceptance numbers (exact — per-engine virtual clocks
        # make both arms deterministic): every request in both arms
        # got its full token budget and the arms are bit-token-
        # identical per request (disaggregation is a placement move,
        # never a quality knob); the disagg arm improves TTFT p99
        # AND inter-token p99 TOGETHER (the whole point — specialists
        # kill the prefill/decode interference instead of trading one
        # tail for the other); pages really moved over the fabric
        # (handoffs happened, wire bytes are nonzero and counted);
        # and the restart leg's fresh-engine TTFT lands at warm-hit
        # cost (within 25% of the donor's warm turn), well under the
        # cold engine's
        assert dz["mixed"]["completed"] == dz["disagg"]["completed"] \
            == dz["requests"], dz
        assert dz["token_identical"], "disagg/mixed token mismatch"
        assert dz["disagg"]["ttft_p99_s"] < \
            dz["mixed"]["ttft_p99_s"], dz
        assert dz["disagg"]["itl_p99_s"] < \
            dz["mixed"]["itl_p99_s"], dz
        fabz = dz["disagg"]["fabric"]
        assert fabz["handoffs"] >= 1, fabz
        assert fabz["frame_bytes"] > 0 \
            and fabz["bytes_sent"] >= fabz["frame_bytes"], fabz
        assert fabz["grafted_pages"] >= 1 \
            and fabz["pages_sent"] >= fabz["grafted_pages"], fabz
        rz = dz["restart"]
        assert rz["token_identical"], rz
        assert rz["restored_pages"] >= 1, rz
        assert rz["restored_ttft_s"] <= 1.25 * rz["warm_ttft_s"], rz
        assert rz["restored_ttft_s"] < 0.6 * rz["cold_ttft_s"], rz
        assert rz["warm_ttft_s"] < rz["cold_ttft_s"], rz
    if args.overload:
        ov = report["overload"]
        on, off = ov["on"], ov["off"]
        # the acceptance numbers (exact — the virtual clock makes the
        # replay deterministic): with preemption ON no high-priority
        # request misses its deadline and all complete; OFF strands
        # them behind the full house until every deadline expires, so
        # high-priority goodput is STRICTLY better with preemption on;
        # low-priority requests still finish either way (degradation,
        # not starvation); and the priority-flat fault-free replay is
        # bit-identical with the machinery on vs off
        assert on["high_priority"]["deadline_misses"] == 0, ov
        assert on["high_priority"]["completed"] == \
            ov["requests_high"], ov
        assert off["high_priority"]["deadline_misses"] >= 1, ov
        assert ov["high_goodput_tokens_per_virtual_s"]["on"] > \
            ov["high_goodput_tokens_per_virtual_s"]["off"], ov
        assert on["preemptions"] >= 1 and off["preemptions"] == 0, ov
        assert on["swapped_out_pages"] >= 1, ov
        assert on["swapped_in_pages"] == on["swapped_out_pages"], ov
        assert on["low_priority"]["completed"] == \
            ov["requests_low"], ov
        assert ov["fault_free"]["identical"], ov
    if args.quant_ab:
        qt = report["quant"]
        # the acceptance numbers: under the SAME HBM page-byte budget
        # int8 admits >= 1.5x the residents at peak (that is the
        # point — more concurrent users per HBM byte), the one-step
        # logit drift stays under the pinned epsilon (a broken
        # scale path drifts by O(logit magnitude), not O(quant
        # noise)), throughput does not regress (the fp arm is
        # page-starved; int8's extra residents must show up as
        # tokens/s), and both arms served the whole trace
        assert qt["fp"]["completed"] == qt["int8"]["completed"] \
            == qt["requests"], qt
        assert qt["residents_ratio"] is not None \
            and qt["residents_ratio"] >= 1.5, qt
        assert qt["max_logit_drift"] <= qt["drift_epsilon"], qt
        assert qt["tokens_per_sec_ratio"] is not None \
            and qt["tokens_per_sec_ratio"] >= 1.0, qt
    if args.lora_ab:
        lr = report["lora"]
        # the acceptance numbers: every tenant's stream from the
        # BATCHED mixed-adapter engine is bit-token-identical to the
        # serial DENSE-MERGED (W + B·A) oracle fleet (multi-tenancy is
        # a packing move, never a quality knob), the batched arm's
        # trace throughput strictly beats serving the tenants one
        # merged engine at a time, and the paged adapter pool really
        # cycled (loads recorded; evict/spill traffic under the
        # deliberately undersized pool)
        assert lr["token_identical"], "lora batched/merged mismatch"
        assert lr["batched"]["completed"] == lr["requests"], lr
        assert lr["tokens_per_sec_ratio"] is not None \
            and lr["tokens_per_sec_ratio"] > 1.0, lr
        assert lr["adapter_pool"]["loads_total"] >= lr["adapters"], lr
        assert (lr["adapter_pool"]["evictions_total"]
                + lr["adapter_pool"]["spills_total"]) >= 1, lr
    if args.tp_ab:
        tp = report["tp"]
        # the acceptance numbers: the mesh arm emitted EXACTLY the
        # oracle's tokens (all-gathers never reassociate fp math —
        # spanning chips is a capacity move, never a quality knob),
        # the same per-chip page-byte budget admitted >= 1.5x the
        # residents at mp=2 (each chip holds 1/mp of every page),
        # and the compiled step's collective census matches the
        # model: ZERO all-reduces / reduce-scatters, exactly ONE
        # output all-gather per layer per step
        assert tp["token_identical"], "tp mp1/mp2 token mismatch"
        assert tp["mp1"]["completed"] == tp["mp2"]["completed"] \
            == tp["requests"], tp
        assert tp["residents_ratio"] is not None \
            and tp["residents_ratio"] >= 1.5, tp
        assert tp["collectives"]["all_reduce"] == 0, tp
        assert tp["collectives"]["reduce_scatter"] == 0, tp
        assert tp["output_collectives_per_layer_step"] == 1.0, tp
        assert tp["collectives"]["all_gather"] == tp["n_layers"], tp


def run_trace(model, arrivals, prompts, budgets, *, slots, max_len,
              page_size, pages, chunk, attn_impl, prefix_cache=None,
              warm_prompts=(), unified=None, spec=None,
              collect_tokens=False, kv_dtype=None, grouped=None,
              obs=None, mesh=None, collect_collectives=False,
              slo=None, cost_census=None, grammar=None,
              grammar_spec=None, eos=None, megakernel=None):
    """One Poisson-trace replay through a fresh engine pinned to
    `attn_impl` (and, for the prefix A/B, to `prefix_cache` on/off;
    for the unified-step A/B, to `unified` on/off; for the spec A/B,
    to `spec` — False forces speculation off, "ngram[:k]" turns the
    drafter on; for the quant A/B, to `kv_dtype` fp/int8; for the
    grouped-walk A/B, to `grouped` on/off); returns
    {snap, wall_s, engine-shape fields, and — with collect_tokens —
    every request's emitted token list in submission order, the
    spec/quant A/Bs' token evidence}. `warm_prompts` run to completion
    before the clock starts, so a prefix-cache run measures the steady
    state (system prompts resident) rather than cold compulsory
    misses."""
    from paddle_tpu.serving import SamplingParams, ServingEngine

    n_req = len(prompts)
    eng = ServingEngine(model, num_slots=slots, max_len=max_len,
                        page_size=page_size, num_pages=pages,
                        chunk_len=chunk, attn_impl=attn_impl,
                        prefix_cache=prefix_cache, unified=unified,
                        spec=spec, kv_dtype=kv_dtype, grouped=grouped,
                        obs=obs, mesh=mesh, slo=slo,
                        cost_census=cost_census, grammar=grammar,
                        megakernel=megakernel)
    # --grammar-ab: every trace request carries the grammar (and the
    # EOS a constrained stream needs to terminate); the off arm rides
    # the same eos so the two arms replay a comparable trace
    sp_kw = {}
    if eos is not None:
        sp_kw["eos_token_id"] = int(eos)
    if grammar_spec is not None:
        sp_kw["grammar"] = grammar_spec

    # warm the compiled programs so the trace measures steady state, not
    # XLA compile time: one request per distinct prompt length (chunk
    # bucketing folds these into O(log chunk) prefill traces)
    for pl in sorted({p.size for p in prompts}):
        eng.add_request(np.arange(1, pl + 1, dtype=np.int64),
                        SamplingParams(max_new_tokens=2))
    for wp in warm_prompts:
        eng.add_request(np.asarray(wp, dtype=np.int64),
                        SamplingParams(max_new_tokens=2))
    eng.run()
    eng.metrics.__init__()   # drop warmup from the report
    if eng.obs is not None:
        eng.obs.reset()      # ... and from the flight ring/timelines
    if eng.slo is not None:
        eng.slo.reset()      # ... and from the SLO burn windows
    # metrics.__init__ dropped the engine-wired fields: restore the
    # SLO hook + the census/capacity anchors next to the A/B tags
    eng.metrics.slo = eng.slo
    eng.metrics.step_capacity_tokens = eng.step_capacity_tokens
    eng.metrics.cost_census = eng._census
    eng.metrics.attn_impl = eng.attn_impl
    eng.metrics.unified = eng.unified
    eng.metrics.grouped = eng.grouped
    eng.metrics.megakernel = eng.megakernel
    eng.metrics.spec = None if eng.spec is None else eng.spec.mode
    eng.metrics.grammar = eng.grammar_on
    eng.metrics.kv_dtype = eng.kv_dtype
    eng.metrics.pool_bytes_per_page = eng.page_bytes
    eng.metrics.mesh = None if eng.tp is None else eng.tp.shape
    eng.metrics.mp, eng.metrics.dp = eng.mp, eng.dp
    eng.metrics.pool_shard_bytes_per_page = eng.page_bytes_per_chip

    t0 = time.monotonic()
    submitted = 0
    reqs = []
    while submitted < n_req or eng.has_work:
        now = time.monotonic() - t0
        while submitted < n_req and arrivals[submitted] <= now:
            reqs.append(eng.add_request(
                prompts[submitted],
                SamplingParams(max_new_tokens=int(budgets[submitted]),
                               **sp_kw)))
            submitted += 1
        if eng.has_work:
            eng.step()
        elif submitted < n_req:
            time.sleep(min(0.001, arrivals[submitted] - now))
    wall = time.monotonic() - t0
    out = {"snap": eng.metrics.snapshot(), "wall_s": wall,
           "page_size": eng.page_size, "num_pages": eng.num_pages,
           "chunk_len": eng.chunk_len, "page_bytes": eng.page_bytes,
           "page_bytes_per_chip": eng.page_bytes_per_chip}
    if collect_tokens:
        out["tokens"] = [list(r.output_tokens) for r in reqs]
    if collect_collectives and eng.tp is not None:
        # compiled-HLO ground truth of the sharded step's collectives
        out["collectives"] = eng.collective_counts()
    if eng.obs is not None:
        out["flight"] = eng.obs.flight.snapshot()
        out["obs_stats"] = eng.obs.stats()
    out["census"] = eng.cost_census()
    out["census_captures"] = eng._census_captures
    if eng.slo is not None:
        out["slo"] = eng.slo.snapshot()
    return out


def tp_trace(model, cfg, *, slots, seed, on_tpu, repeats=2):
    """--tp-ab: one single-device replica (mp=1, the oracle) vs ONE
    replica spanning a dp1xmp2 mesh, the SAME burst trace, both arms
    under the SAME PER-CHIP page-byte budget. An mp=2 chip holds a
    1/mp kv-head slice of every page, so its per-page cost halves and
    the same per-chip bytes buy 2x the pages — the mp=1 arm is
    page-starved at the budget, the mesh arm admits ~2x the
    residents. Tokens are collected and must be BIT-identical (the
    sharded step's only collective is the bit-exact per-layer output
    all-gather — the compiled-HLO census in the report proves it:
    zero all-reduces, exactly one output all-gather per layer)."""
    from paddle_tpu.serving import ServingEngine

    slots = max(int(slots), 8)
    if on_tpu:
        plen, max_new, page_size, max_len, chunk = 64, 64, 16, 256, 64
    else:
        plen, max_new, page_size, max_len, chunk = 12, 8, 8, 64, 16
    n_layers = int(cfg.num_hidden_layers)
    n_req = 3 * slots
    req_pages = -(-(plen + max_new) // page_size)
    rng = np.random.RandomState(seed)
    prompts = [rng.randint(0, cfg.vocab_size, size=plen)
               .astype(np.int64) for _ in range(n_req)]
    arrivals = np.zeros(n_req)                 # burst: page-limited
    budgets = np.full(n_req, max_new)

    # the SAME per-chip byte budget for both arms: enough mp=1 pages
    # for a third of the slots to hold a full request each; the mesh
    # arm's per-chip page cost is 1/mp of that, so the same budget
    # buys mp x the pages
    probe = ServingEngine(model, num_slots=2, max_len=max_len,
                          page_size=page_size, num_pages=2,
                          chunk_len=chunk)
    chip_page_bytes = {1: probe.page_bytes_per_chip,
                       2: probe.page_bytes_per_chip // 2}
    fp_alloc = req_pages * max(2, slots // 3)
    budget_bytes = fp_alloc * chip_page_bytes[1]
    pages = {1: fp_alloc + 1,
             2: int(budget_bytes // chip_page_bytes[2]) + 1}

    runs = {}
    for mp in (1, 2):
        attempts = [run_trace(
            model, arrivals, prompts, budgets, slots=slots,
            max_len=max_len, page_size=page_size, pages=pages[mp],
            chunk=chunk, attn_impl="kernel",
            mesh=(None if mp == 1 else f"dp1mp{mp}"),
            collect_tokens=True, collect_collectives=True)
            for _ in range(max(1, repeats))]
        for a in attempts[1:]:
            assert a["tokens"] == attempts[0]["tokens"], \
                "tp arm not deterministic across repeats"
        runs[mp] = max(attempts,
                       key=lambda r: r["snap"]["tokens_per_sec"] or 0.0)

    def arm(run):
        s = run["snap"]
        occ = s.get("occupancy_hist") or {}
        peak = int(round((occ.get("max") or 0.0) * slots))
        trace_tps = (s["tokens_generated"] / run["wall_s"]
                     if run["wall_s"] > 0 else 0.0)
        return {
            "wall_s": round(run["wall_s"], 4),
            "mesh": s.get("mesh") or "off",
            "num_pages": run["num_pages"],
            "page_bytes": run["page_bytes"],
            "page_bytes_per_chip": run["page_bytes_per_chip"],
            "chip_pool_bytes": ((run["num_pages"] - 1)
                                * run["page_bytes_per_chip"]),
            "tokens_per_sec": trace_tps,
            "engine_window_tokens_per_sec": s["tokens_per_sec"],
            "residents_at_peak": peak,
            "residents_per_chip_hbm_gb":
                peak / (budget_bytes / 2**30),
            "ttft_p50_s": s["ttft_s"]["p50"],
            "ttft_p99_s": s["ttft_s"]["p99"],
            "completed": s["requests"]["completed"],
        }

    a1, a2 = arm(runs[1]), arm(runs[2])
    coll = runs[2]["collectives"]
    return {
        "slots": slots,
        "requests": n_req,
        "prompt_len": plen,
        "max_new": max_new,
        "page_size": page_size,
        "mesh": "dp1xmp2",
        "mp": 2,
        "n_layers": n_layers,
        "per_chip_budget_bytes": int(budget_bytes),
        "token_identical": (runs[1]["tokens"] == runs[2]["tokens"]),
        "residents_ratio": (
            None if not a1["residents_at_peak"]
            else a2["residents_at_peak"] / a1["residents_at_peak"]),
        "tokens_per_sec_ratio": (
            None if not a1["tokens_per_sec"]
            else a2["tokens_per_sec"] / a1["tokens_per_sec"]),
        # compiled-HLO census of the sharded step (the modeled pin:
        # one output all-gather per layer, nothing else)
        "collectives": coll,
        "output_collectives_per_layer_step":
            coll["all_gather"] / max(1, n_layers),
        "mp1": a1,
        "mp2": a2,
    }


def kv_logit_drift(model, cfg, plen, page_size):
    """Accuracy half of the quant A/B: ONE prompt prefilled through
    the model against a paged fp cache vs a paged int8 (code+scale
    page) cache — max abs difference of the next-token logits. This
    is the drift a single step's reads inject; the trace-level token
    agreement in the report shows how it compounds."""
    import jax.numpy as jnp
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.nlp.generation import DecodeCache

    n_layers, n_kv, head_dim = model._decode_cache_spec()
    mp = -(-plen // page_size)
    n_pages = mp + 1
    rng = np.random.RandomState(9)
    ids = Tensor(jnp.asarray(
        rng.randint(0, cfg.vocab_size, size=(1, plen)), jnp.int32))
    pt = Tensor(jnp.asarray(np.arange(1, n_pages).reshape(1, mp),
                            jnp.int32))
    fpdt = next((p._value.dtype for p in model.parameters()
                 if jnp.issubdtype(p._value.dtype, jnp.floating)),
                jnp.float32)
    logits = {}
    for dtype in ("fp", "int8"):
        caches = []
        for _ in range(n_layers):
            pos = Tensor(jnp.zeros((1,), jnp.int32),
                         stop_gradient=True)
            if dtype == "int8":
                z8 = jnp.zeros((n_pages, page_size, n_kv, head_dim),
                               jnp.int8)
                zs = jnp.zeros((n_pages, page_size, n_kv),
                               jnp.float32)
                caches.append(DecodeCache(
                    Tensor(z8, stop_gradient=True),
                    Tensor(z8, stop_gradient=True), pos,
                    Tensor(zs, stop_gradient=True),
                    Tensor(zs, stop_gradient=True), page_table=pt))
            else:
                zf = jnp.zeros((n_pages, page_size, n_kv, head_dim),
                               fpdt)
                caches.append(DecodeCache(
                    Tensor(zf, stop_gradient=True),
                    Tensor(zf, stop_gradient=True), pos,
                    page_table=pt))
        lg, _ = model(ids, caches=caches)
        logits[dtype] = np.asarray(
            lg._value[:, -1, :].astype(jnp.float32))
    return float(np.max(np.abs(logits["fp"] - logits["int8"])))


def quant_trace(model, cfg, *, slots, seed, on_tpu, repeats=2):
    """--quant-ab: fp vs int8 paged KV pool under the SAME HBM
    page-byte budget. The budget is set so the fp arm can hold only
    ~half the slots' page budgets at once (page-limited admission —
    the regime quantization exists for); the int8 arm spends the SAME
    bytes on proportionally more (code+scale) pages. Every request
    arrives at t=0, so peak residency is a property of the budget,
    not of arrival luck. Greedy everywhere; both arms' tokens are
    collected so the report can show agreement (int8 is lossy — the
    assert is on residents/drift/throughput, token agreement is
    evidence, not a gate)."""
    slots = max(int(slots), 8)
    if on_tpu:
        plen, max_new, page_size, max_len, chunk = 64, 64, 16, 256, 64
    else:
        plen, max_new, page_size, max_len, chunk = 12, 8, 8, 64, 16
    n_req = 3 * slots
    req_pages = -(-(plen + max_new) // page_size)
    rng = np.random.RandomState(seed)
    prompts = [rng.randint(0, cfg.vocab_size, size=plen)
               .astype(np.int64) for _ in range(n_req)]
    arrivals = np.zeros(n_req)                 # burst: page-limited
    budgets = np.full(n_req, max_new)

    # the SAME byte budget for both arms: enough fp pages for a third
    # of the slots to hold a full request each (fp arm page-starved,
    # int8 arm buys ~2x+ the pages for the same bytes)
    probe = {}
    for dtype in ("fp", "int8"):
        from paddle_tpu.serving import ServingEngine
        probe[dtype] = ServingEngine(
            model, num_slots=2, max_len=max_len, page_size=page_size,
            num_pages=2, chunk_len=chunk, kv_dtype=dtype).page_bytes
    fp_alloc = req_pages * max(2, slots // 3)
    budget_bytes = fp_alloc * probe["fp"]
    pages = {"fp": fp_alloc + 1,
             "int8": int(budget_bytes // probe["int8"]) + 1}

    runs = {}
    for dtype in ("fp", "int8"):
        # best-of-N per arm by tokens/s (the hiccup-absorbing
        # convention of the other A/Bs); tokens are deterministic
        # across attempts per arm
        attempts = [run_trace(
            model, arrivals, prompts, budgets, slots=slots,
            max_len=max_len, page_size=page_size, pages=pages[dtype],
            chunk=chunk, attn_impl="kernel", kv_dtype=dtype,
            collect_tokens=True) for _ in range(max(1, repeats))]
        for a in attempts[1:]:
            assert a["tokens"] == attempts[0]["tokens"], \
                "quant arm not deterministic across repeats"
        runs[dtype] = max(
            attempts,
            key=lambda r: r["snap"]["tokens_per_sec"] or 0.0)

    def arm(run):
        s = run["snap"]
        occ = s.get("occupancy_hist") or {}
        peak = int(round((occ.get("max") or 0.0) * slots))
        # trace-level throughput: every emitted token over the whole
        # replay wall — the number the ratio below gates on. (The
        # engine's busy-window tokens_per_sec is also reported, but
        # on CPU it flatters the fp arm: int8 steps pay host-side
        # quant math yet the arm finishes the TRACE faster because
        # twice the residents share each step; on HBM-bound hardware
        # both numbers move the same way.)
        trace_tps = (s["tokens_generated"] / run["wall_s"]
                     if run["wall_s"] > 0 else 0.0)
        return {
            "wall_s": round(run["wall_s"], 4),
            "num_pages": run["num_pages"],
            "page_bytes": run["page_bytes"],
            "pool_bytes": (run["num_pages"] - 1) * run["page_bytes"],
            "tokens_per_sec": trace_tps,
            "engine_window_tokens_per_sec": s["tokens_per_sec"],
            "residents_at_peak": peak,
            "tokens_per_sec_per_hbm_gb":
                trace_tps / (budget_bytes / 2**30),
            "ttft_p50_s": s["ttft_s"]["p50"],
            "ttft_p99_s": s["ttft_s"]["p99"],
            "decode_step_ms_p50": (
                None if s["decode_step_s"]["p50"] is None
                else round(s["decode_step_s"]["p50"] * 1e3, 4)),
            "completed": s["requests"]["completed"],
        }

    fp_a, q8_a = arm(runs["fp"]), arm(runs["int8"])
    tok_fp = [t for stream in runs["fp"]["tokens"] for t in stream]
    tok_q8 = [t for stream in runs["int8"]["tokens"] for t in stream]
    agree = sum(1 for a, b in zip(tok_fp, tok_q8) if a == b)
    total = max(1, max(len(tok_fp), len(tok_q8)))
    drift = kv_logit_drift(model, cfg, plen, page_size)
    return {
        "slots": slots,
        "requests": n_req,
        "prompt_len": plen,
        "max_new": max_new,
        "page_size": page_size,
        "hbm_budget_bytes": int(budget_bytes),
        # single-step fp-vs-int8 logit drift must stay under this pin
        # (rowwise int8 holds ~0.4% relative error per read; measured
        # ~9e-4 on the CPU smoke model — the pin leaves ~50x headroom
        # while still catching a broken scale path, which drifts by
        # O(logit magnitude))
        "drift_epsilon": 0.05,
        "max_logit_drift": drift,
        "token_agreement": agree / total,
        "residents_ratio": (
            None if not fp_a["residents_at_peak"]
            else q8_a["residents_at_peak"]
            / fp_a["residents_at_peak"]),
        "tokens_per_sec_ratio": (
            None if not fp_a["tokens_per_sec"]
            else q8_a["tokens_per_sec"] / fp_a["tokens_per_sec"]),
        "fp": fp_a,
        "int8": q8_a,
    }


def _merged_gpt(cfg, weights):
    """The dense-merged oracle model for one adapter: rebuild the
    bench GPT from the same seed, then fold `scale * A @ B` into the
    projection weights — q/k/v into the fused qkv_proj's interleaved
    per-head [h, H, 3D] layout, o into out_proj. Serving the merge is
    the naive per-tenant fleet; its greedy tokens are the ground
    truth the batched multi-adapter engine must reproduce bit-for-
    bit."""
    import paddle_tpu as paddle
    from paddle_tpu.nlp import GPTForCausalLM

    paddle.seed(0)                  # the build_model seed
    m = GPTForCausalLM(cfg)
    m.eval()
    h = cfg.hidden_size
    H = cfg.num_attention_heads
    D = h // H
    for li, layer in enumerate(m.gpt.layers):
        att = layer.attn
        w = att.qkv_proj.weight.numpy().copy().reshape(h, H, 3 * D)
        for j, proj in enumerate(("q", "k", "v")):
            A, B = weights.layers[li][proj]
            delta = weights.scale * (np.asarray(A) @ np.asarray(B))
            w[:, :, j * D:(j + 1) * D] += delta.reshape(h, H, D)
        att.qkv_proj.weight.set_value(w.reshape(h, 3 * h))
        A, B = weights.layers[li]["o"]
        att.out_proj.weight.set_value(
            att.out_proj.weight.numpy().copy()
            + weights.scale * (np.asarray(A) @ np.asarray(B)))
    return m


def lora_trace(model, cfg, *, slots, seed, on_tpu, k_adapters=4,
               rank=4):
    """The multi-tenant LoRA A/B (`--lora-ab`): ONE mixed-tenant
    Poisson trace — K adapters under zipf popularity plus base-model
    rows — served two ways:

    (a) BATCHED: one adapters-enabled engine; every request carries
        its adapter_id and all tenants share the ONE unified step
        (per-row gathered A/B deltas). The adapter pool is
        deliberately UNDERSIZED (K/2 pages) so the trace exercises
        park/evict/spill churn, not just steady state.
    (b) SERIAL MERGED: the naive fleet — per tenant, fold the adapter
        into the dense weights (W + B·A·scale) and run that tenant's
        requests through its own plain engine, one tenant at a time.

    The serial arm IS the correctness oracle: the batched arm must
    emit bit-identical tokens per request. The performance claim is
    trace throughput — one engine packing every tenant into shared
    steps beats serving tenants back-to-back."""
    from paddle_tpu.serving import (SamplingParams, ServingEngine,
                                    make_random_lora)

    if on_tpu:
        n_req, max_new, plens = 64, 32, [16, 32, 64]
    else:
        n_req, max_new, plens = 24, 10, [4, 6, 10]
    rng = np.random.RandomState(seed)
    h = cfg.hidden_size
    H = cfg.num_attention_heads
    D = h // H
    weights = [make_random_lora(cfg.num_hidden_layers, h, H * D,
                                H * D, rank=rank, rng=rng, amp=0.2)
               for _ in range(k_adapters)]
    # zipf-ish popularity over {base, adapter 1..K}: tenant i drawn
    # with weight 1/(i+1); the first K requests hit each adapter once
    # so every tenant (and the pool churn) is exercised even on the
    # smoke trace
    zipf = np.array([1.0 / (i + 1) for i in range(k_adapters + 1)])
    zipf /= zipf.sum()
    assign = [1 + (i % k_adapters) if i < k_adapters
              else int(rng.choice(k_adapters + 1, p=zipf))
              for i in range(n_req)]
    prompts = [rng.randint(0, cfg.vocab_size,
                           size=int(rng.choice(plens))).astype(np.int64)
               for _ in range(n_req)]
    # burst arrivals for BOTH arms: the claim is structural (one
    # engine packs every tenant into shared steps; the serial fleet
    # pays a low-occupancy replay per tenant), so neither arm should
    # carry Poisson gap noise
    arrivals = np.zeros(n_req)
    budgets = np.full(n_req, max_new)

    def replay(eng, idxs, arrs, adapter_ids=None):
        t0 = time.monotonic()
        submitted, reqs = 0, []
        while submitted < len(idxs) or eng.has_work:
            now = time.monotonic() - t0
            while submitted < len(idxs) and arrs[submitted] <= now:
                i = idxs[submitted]
                aid = (adapter_ids[i] if adapter_ids is not None
                       else 0)
                reqs.append(eng.add_request(
                    prompts[i],
                    SamplingParams(max_new_tokens=int(budgets[i]),
                                   adapter_id=aid)))
                submitted += 1
            if eng.has_work:
                eng.step()
            elif submitted < len(idxs):
                time.sleep(min(0.001, arrs[submitted] - now))
        wall = time.monotonic() - t0
        return wall, [list(r.output_tokens) for r in reqs]

    # -- arm (a): one batched multi-adapter engine ------------------------
    # pool holds K-1 adapters: enough that tenant packing is the
    # common case, small enough that the K-th tenant forces real
    # park/evict/spill churn through the trace
    pool_pages = max(2, k_adapters - 1)
    eng = ServingEngine(model, num_slots=slots, max_len=128,
                        adapters=True, adapter_pages=pool_pages,
                        adapter_ranks=(rank,))
    aids = [eng.adapters.register(f"tenant-{i}", w)
            for i, w in enumerate(weights)]
    assert aids == list(range(1, k_adapters + 1))
    # warm the compiled step + the one-trace adapter upload (steady
    # state, not compile time); warmup requests drain before t0
    for pl in sorted({p.size for p in prompts}):
        eng.add_request(np.arange(1, pl + 1, dtype=np.int64),
                        SamplingParams(max_new_tokens=2, adapter_id=1))
    eng.run()
    eng.metrics.__init__()
    wall_b, tokens_b = replay(eng, list(range(n_req)), arrivals,
                              adapter_ids=assign)
    snap_b = eng.metrics.snapshot()
    pool_stats = eng.adapters.stats()
    tokens_total = sum(len(t) for t in tokens_b)
    eng.drain()

    # -- arm (b): serial merged-weights fleet (the oracle) ----------------
    wall_s = 0.0
    tokens_s: dict = {}
    for tenant in range(k_adapters + 1):
        idxs = [i for i in range(n_req) if assign[i] == tenant]
        if not idxs:
            continue
        m = model if tenant == 0 else _merged_gpt(cfg,
                                                  weights[tenant - 1])
        e = ServingEngine(m, num_slots=slots, max_len=128)
        for pl in sorted({prompts[i].size for i in idxs}):
            e.add_request(np.arange(1, pl + 1, dtype=np.int64),
                          SamplingParams(max_new_tokens=2))
        e.run()
        # tenants replay back-to-back: each group's arrivals restart
        # at 0 (generous to the serial arm — no cross-tenant waiting)
        arrs = [0.0] * len(idxs)
        w, toks = replay(e, idxs, arrs)
        wall_s += w
        for i, t in zip(idxs, toks):
            tokens_s[i] = t
        e.drain()
    identical = all(tokens_b[i] == tokens_s[i] for i in range(n_req))
    tps_b = tokens_total / wall_b if wall_b > 0 else 0.0
    total_s = sum(len(t) for t in tokens_s.values())
    tps_s = total_s / wall_s if wall_s > 0 else 0.0
    return {
        "requests": n_req,
        "adapters": k_adapters,
        "rank": rank,
        "adapter_pool_pages": pool_pages,
        "popularity": "zipf",
        "batched": {
            "wall_s": round(wall_b, 4),
            "tokens_per_sec": tps_b,
            "ttft_p50_s": snap_b["ttft_s"]["p50"],
            "completed": snap_b["requests"]["completed"],
        },
        "serial_merged": {
            "wall_s": round(wall_s, 4),
            "tokens_per_sec": tps_s,
            "engines": k_adapters + 1,
        },
        "tokens_per_sec_ratio": (tps_b / tps_s) if tps_s else None,
        "token_identical": identical,
        "adapter_pool": pool_stats,
    }


def overload_trace(model, cfg, *, slots, seed, scale=1):
    """--overload: the graceful-degradation A/B on a DETERMINISTIC
    virtual clock. The engine's injected clock advances a fixed `dt`
    per scheduler round, so admission, deadline expiry and preemption
    decisions are bit-reproducible on any machine — the assertions
    below are exact, not statistical. The trace is 3x oversubscribed:
    `2 * slots` long LOW-priority requests (priority 5) arrive at 3x
    the sustainable service rate and saturate every slot, then a burst
    of HIGH-priority requests (priority 0) lands with a placement
    deadline far shorter than any resident's remaining runtime. With
    preemption ON the blocked high-priority head preempts the
    least-important residents (KV swapped to the host tier; they
    resume later, token-identically — the engine suite asserts the
    oracle) and every deadline is met; with preemption OFF every
    high-priority request waits behind a full house and deadline-fails
    (504). A third, priority-flat FAULT-FREE replay runs with
    preemption on vs off and must be bit-identical (same tokens, same
    step count): the machinery costs nothing when it never fires."""
    from paddle_tpu.serving import SamplingParams, ServingEngine

    dt = 0.01                     # virtual seconds per engine round
    high_new, plen = 8, 8
    n_low, n_high = 2 * slots * scale, slots * scale
    # the margins must stay wide AND deterministic at any scale: the
    # high burst is `scale` waves deep (slots per wave), so wave w's
    # placement deadline covers the queueing among the highs
    # themselves — w waves of high service — while every deadline
    # stays far below the OFF arm's wait (slots turn over only as low
    # residents finish, one every ~low_new/slots rounds deep into the
    # backlog, so all but the luckiest first-wave highs wait far past
    # their deadline without preemption)
    low_new = min(40 + 40 * scale, 200)
    deadline_base = 16 * dt
    # sustainable ~= slots finishing every low_new steps; 3x that
    low_gap = (low_new * dt) / (3.0 * slots)
    rng = np.random.RandomState(seed)
    prompts, arrivals, budgets, priorities, deadlines = [], [], [], [], []
    for i in range(n_low):
        prompts.append(rng.randint(0, cfg.vocab_size, size=plen)
                       .astype(np.int64))
        arrivals.append(i * low_gap)
        budgets.append(low_new)
        priorities.append(5)
        deadlines.append(None)
    t_high = n_low * low_gap + 10 * dt      # every slot saturated
    for i in range(n_high):
        prompts.append(rng.randint(0, cfg.vocab_size, size=plen)
                       .astype(np.int64))
        arrivals.append(t_high + i * dt)
        budgets.append(high_new)
        priorities.append(0)
        deadlines.append(deadline_base
                         + (i // slots) * (high_new + 6) * dt)

    def run(preempt, with_high=True):
        vt = [0.0]
        n = len(prompts) if with_high else n_low
        eng = ServingEngine(model, num_slots=slots, max_len=256,
                            page_size=8, chunk_len=16,
                            clock=lambda: vt[0], preempt=preempt)
        eng.add_request(np.arange(1, plen + 1, dtype=np.int64),
                        SamplingParams(max_new_tokens=2))
        eng.run()                  # compile-warm outside the clock
        eng.metrics.__init__()
        eng.metrics.attn_impl = eng.attn_impl
        eng.metrics.unified = eng.unified
        wall0 = time.monotonic()
        reqs, submitted = [], 0
        while submitted < n or eng.has_work:
            while submitted < n and arrivals[submitted] <= vt[0]:
                reqs.append(eng.add_request(
                    prompts[submitted],
                    SamplingParams(
                        max_new_tokens=int(budgets[submitted]),
                        priority=int(priorities[submitted]),
                        deadline_s=deadlines[submitted])))
                submitted += 1
            if eng.has_work:
                eng.step()
            vt[0] += dt
        snap = eng.metrics.snapshot()
        eng.drain()
        hi = [r for r in reqs if r.sampling.priority == 0]
        lo = [r for r in reqs if r.sampling.priority != 0]

        def cls(rs):
            return {
                "requests": len(rs),
                "completed": sum(1 for r in rs
                                 if r.finish_reason in ("stop",
                                                        "length")),
                "deadline_misses": sum(1 for r in rs
                                       if r.finish_reason
                                       == "deadline"),
                "tokens": sum(len(r.output_tokens) for r in rs),
            }

        return {
            "virtual_s": round(vt[0], 4),
            "wall_s": round(time.monotonic() - wall0, 4),
            "steps": snap["decode_steps"],
            "tokens_generated": snap["tokens_generated"],
            "preemptions": snap["preemptions"],
            "swapped_out_pages": snap["swapped_out_pages"],
            "swapped_in_pages": snap["swapped_in_pages"],
            "swap_in_p99_s": snap["swap_in_s"]["p99"],
            "high_priority": cls(hi),
            "low_priority": cls(lo),
            "token_streams": [list(r.output_tokens) for r in reqs],
        }

    on, off = run(True), run(False)
    flat_on, flat_off = run(True, with_high=False), \
        run(False, with_high=False)
    fault_free_identical = (
        flat_on["token_streams"] == flat_off["token_streams"]
        and flat_on["steps"] == flat_off["steps"])
    # goodput = completed high-priority tokens per virtual second
    def goodput(r):
        return r["high_priority"]["tokens"] / r["virtual_s"]
    for r in (on, off, flat_on, flat_off):
        del r["token_streams"]    # evidence, not report payload
    return {
        "slots": slots,
        "scale": scale,
        "virtual_dt_s": dt,
        "rate_multiplier": 3.0,
        "deadline_s": deadline_base,
        "deadline_max_s": max(d for d in deadlines if d is not None),
        "requests_low": n_low,
        "requests_high": n_high,
        "on": on,
        "off": off,
        "high_goodput_tokens_per_virtual_s": {
            "on": goodput(on), "off": goodput(off)},
        "fault_free": {"on": flat_on, "off": flat_off,
                       "identical": fault_free_identical},
    }


def autoscale_trace(model, cfg, *, slots, seed, n_max=4):
    """--autoscale-ab (schema v15): reactive burn-rate autoscaling vs
    a peak-provisioned fixed fleet on a DETERMINISTIC diurnal
    virtual-time trace. The whole fleet shares one harness-driven
    clock advancing a fixed `dt` per round, so arrivals, placement,
    every scaling decision and every token are bit-reproducible on
    any machine. The trace is a diurnal wave: a trough one replica
    serves at ~30% utilization, a peak needing the whole fleet, and a
    long trough back down. The AUTO arm starts at 1 replica and lets
    a REAL FleetController (serving/controlplane.py — the same
    decide() the router's control loop calls, fed the same
    util/queue/burn signals, on the same virtual clock) grow and
    shrink the fleet between 1 and n_max with graceful drain on the
    way down; the FIXED arm keeps all n_max replicas up the whole
    time (peak provisioning). Both arms must complete every request
    with its exact token budget; the auto arm must hold TTFT p99
    within the SLO target while spending <= ~0.6x the fixed arm's
    replica-seconds, without flapping. A STEADY trough-rate trace
    also runs at fixed fleet size with the controller attached
    (min == max, so it can observe but never actuate) vs detached,
    and must be bit-token-identical with the same step count — the
    control plane is pure host-side steering, never math."""
    from paddle_tpu.serving import (ControlPlaneConfig, FleetController,
                                    FleetSignals, SLOConfig,
                                    SamplingParams, ServingEngine,
                                    slo_placement_rank)

    dt = 0.01                     # virtual seconds per fleet round
    plen, n_new = 6, 8
    chunk = 16
    # one request holds a slot for ~(1 prefill chunk + n_new decode)
    # rounds, so one replica sustains ~slots/(1+n_new) requests per
    # round; phase rates are fractions of that one-replica capacity
    cap_rps = slots / float(1 + n_new) / dt
    phases = [(0.8, 0.30 * cap_rps),       # trough: 1 replica, ~30%
              (1.2, 2.50 * cap_rps),       # peak: needs the fleet
              (1.6, 0.30 * cap_rps)]       # trough: scale back down
    slo_cfg = SLOConfig(ttft_p99_s=0.30, itl_p99_s=0.5,
                        fast_window_s=0.5, slow_window_s=2.5,
                        min_events=8)
    rng = np.random.RandomState(seed)
    arrivals, t0 = [], 0.0
    for dur, phase_rate in phases:
        k = int(round(dur * phase_rate))
        # deterministic uniform spacing inside each phase — the wave
        # shape is the experiment, Poisson jitter would just blur it
        arrivals.extend(t0 + (j + 1) * (dur / k) for j in range(k))
        t0 += dur
    prompts = [rng.randint(0, cfg.vocab_size, size=plen)
               .astype(np.int64) for _ in arrivals]
    n = len(arrivals)

    def run(n_engines, n_start, cp_cfg, arrival_list, prompt_list):
        """One virtual-time fleet replay. `cp_cfg=None` detaches the
        controller entirely (fixed fleet, load-only placement)."""
        vt = [0.0]
        engines = []
        for _ in range(n_engines):
            eng = ServingEngine(model, num_slots=slots, max_len=64,
                                page_size=8, chunk_len=chunk,
                                clock=lambda: vt[0], slo=slo_cfg)
            eng.add_request(np.arange(1, plen + 1, dtype=np.int64),
                            SamplingParams(max_new_tokens=2))
            eng.run()              # compile-warm outside the clock
            engines.append(eng)
        ctrl = (None if cp_cfg is None
                else FleetController(cp_cfg, clock=lambda: vt[0]))
        active = list(range(n_start))
        parked = list(range(n_start, n_engines))
        draining: list = []
        census = engines[0].cost_census() or {}
        wall0 = time.monotonic()
        reqs, submitted = [], 0
        replica_seconds = steps_total = 0.0
        peak_replicas = len(active)
        ups, downs = [], []

        def live():
            return [i for i in active if i not in draining]

        def place(prompt):
            # the router's ranking mirrored on the sim fleet: SLO
            # state first (controller attached), then load, then a
            # stable index tie-break
            cands = live() or active
            key = {}
            for i in cands:
                e = engines[i]
                sr = (slo_placement_rank(e.slo.worst_state())
                      if ctrl is not None else 0)
                key[i] = (sr, e.scheduler.queue_depth,
                          len(e.scheduler.running), i)
            best = min(cands, key=lambda i: key[i])
            return engines[best].add_request(
                prompt, SamplingParams(max_new_tokens=n_new))

        def signals():
            ids = live()
            fb = sb = 0.0
            for i in ids:
                f, s = engines[i].slo.worst_burns(now=vt[0])
                fb, sb = max(fb, f), max(sb, s)
            mu = (sum(len(engines[i].scheduler.running)
                      for i in ids) / (len(ids) * slots)
                  if ids else 0.0)
            return FleetSignals(
                replicas=len(ids), fast_burn=fb, slow_burn=sb,
                mean_util=mu,
                queue_depth=sum(engines[i].scheduler.queue_depth
                                for i in ids),
                capacity_tokens=int(census.get("capacity_tokens")
                                    or slots * chunk),
                flops_per_token=float(
                    census.get("flops_per_token") or 0.0))

        def actuate(decision, want):
            nonlocal peak_replicas
            if decision.action == "scale_up":
                added = 0
                while len(live()) < want:
                    if draining:           # cancel an in-flight drain
                        draining.pop(0)
                    elif parked:
                        active.append(parked.pop(0))
                    else:
                        break
                    added += 1
                if added:
                    ups.append({"t": round(vt[0], 3), "n": added,
                                "reason": decision.reason})
                peak_replicas = max(peak_replicas, len(active))
            elif decision.action == "scale_down":
                ids = live()
                if len(ids) > 1:
                    victim = min(ids, key=lambda i: (
                        len(engines[i].scheduler.running)
                        + engines[i].scheduler.queue_depth, i))
                    draining.append(victim)
                    downs.append({"t": round(vt[0], 3),
                                  "reason": decision.reason})

        scaling = ctrl is not None and \
            cp_cfg.min_replicas < cp_cfg.max_replicas
        n_arm = len(arrival_list)
        while submitted < n_arm or any(engines[i].has_work
                                       for i in active):
            while submitted < n_arm \
                    and arrival_list[submitted] <= vt[0]:
                reqs.append(place(prompt_list[submitted]))
                submitted += 1
            if ctrl is not None:
                d = ctrl.decide(signals())
                if scaling:
                    actuate(d, d.desired)
            for i in list(active):
                if engines[i].has_work:
                    engines[i].step()
                    steps_total += 1
            for i in list(draining):
                if not engines[i].has_work:
                    draining.remove(i)
                    active.remove(i)
                    parked.append(i)
            replica_seconds += len(active) * dt
            vt[0] += dt
        for eng in engines:
            eng.drain()
        ttfts = sorted(r.first_token_t - r.arrival_t for r in reqs)
        return {
            "virtual_s": round(vt[0], 4),
            "wall_s": round(time.monotonic() - wall0, 4),
            "completed": sum(1 for r in reqs
                             if r.finish_reason == "length"),
            "exact_streams": all(
                r.finish_reason == "length"
                and len(r.output_tokens) == n_new for r in reqs),
            "token_streams": [list(r.output_tokens) for r in reqs],
            "steps": int(steps_total),
            "replica_seconds": round(replica_seconds, 4),
            "tokens_per_virtual_s": round(
                sum(len(r.output_tokens) for r in reqs) / vt[0], 4),
            "ttft_p50_s": round(ttfts[len(ttfts) // 2], 4),
            "ttft_p99_s": round(
                ttfts[min(len(ttfts) - 1,
                          int(0.99 * len(ttfts)))], 4),
            "peak_replicas": peak_replicas,
            "scale_ups": ups,
            "scale_downs": downs,
            "desired_final": (None if ctrl is None
                              else ctrl.desired_replicas),
        }

    cp_auto = ControlPlaneConfig(
        min_replicas=1, max_replicas=n_max, target_util=0.70,
        scale_down_util=0.35, scale_up_cooldown_s=0.05,
        scale_down_cooldown_s=0.15, est_request_tokens=plen + n_new)
    auto = run(n_max, 1, cp_auto, arrivals, prompts)
    fixed = run(n_max, n_max, None, arrivals, prompts)

    # the steady identity pair: constant trough rate, fixed 2-replica
    # fleet, controller attached-but-clamped vs detached
    steady_rate = 0.30 * cap_rps
    k = int(round(0.8 * steady_rate))
    steady_arrivals = [(j + 1) * (0.8 / k) for j in range(k)]
    steady_prompts = [rng.randint(0, cfg.vocab_size, size=plen)
                      .astype(np.int64) for _ in steady_arrivals]
    cp_clamped = ControlPlaneConfig(min_replicas=2, max_replicas=2)
    steady_cp = run(2, 2, cp_clamped, steady_arrivals, steady_prompts)
    steady_plain = run(2, 2, None, steady_arrivals, steady_prompts)
    steady_identical = (
        steady_cp["token_streams"] == steady_plain["token_streams"]
        and steady_cp["steps"] == steady_plain["steps"])
    for r in (auto, fixed, steady_cp, steady_plain):
        del r["token_streams"]    # evidence, not report payload
    return {
        "virtual_dt_s": dt,
        "n_max": n_max,
        "slots": slots,
        "requests": n,
        "phases": [[round(dur, 3), round(r_, 2)] for dur, r_ in phases],
        "slo_ttft_p99_s": slo_cfg.ttft_p99_s,
        "auto": auto,
        "fixed": fixed,
        "replica_seconds_ratio": round(
            auto["replica_seconds"] / fixed["replica_seconds"], 4),
        "flaps": len(auto["scale_ups"]) + len(auto["scale_downs"]),
        "steady": {"requests": k, "controller_on": steady_cp,
                   "controller_off": steady_plain,
                   "identical": steady_identical},
    }


def disagg_trace(model, cfg, *, slots, seed):
    """--disagg-ab (schema v16): disaggregated prefill/decode over the
    fleet KV fabric vs a mixed 2-replica fleet, on DETERMINISTIC
    per-engine virtual clocks. Both arms replay the SAME trace — a
    steady stream of short decode-heavy requests plus a burst of
    LONG prompts sharing one system prefix — through two engines of
    identical capacity. The MIXED arm routes by load, so long
    prefills pack into the same unified steps the shorts are decoding
    through (every packed prefill token inflates that step's cost —
    the interference ITL) and each engine pays its own COLD prefill
    of the shared prefix. The DISAGG arm pins long prompts on a
    prefill specialist (max_new_tokens=1) whose committed pages ship
    to the decode specialist as a REAL fabric transfer frame
    (engine.export_prefix_frame -> import_prefix_frame — the bytes on
    the wire are the bytes in the report), where the continuation
    grafts the pages and decodes; shorts never share a step with a
    long chunk, and the shared prefix goes cold exactly ONCE
    fleet-wide. Virtual time: each engine's clock advances
    dt_base + dt_token * (packed prefill+decode tokens) per step —
    the unified step's own packing counters — and a handoff costs
    rpc + frame_bytes/bandwidth before the continuation becomes
    admissible; the decode replica relays the handed-off first token
    when it ACCEPTS the handoff (client TTFT includes the transfer).
    The script asserts BOTH client-observed TTFT p99 AND inter-token
    p99 improve in the disagg arm, that the arms are bit-token-
    identical per request, and that a warm RESTART (export_prefix_-
    state -> fresh engine import_prefix_state) serves the next turn
    at warm-hit TTFT, far under a cold engine's."""
    from paddle_tpu.serving import SamplingParams, ServingEngine

    # geometry: small pages so a long prompt spans many transferable
    # pages; token_budget == chunk so resident decoders genuinely eat
    # the spare a cold prefill needs (the starvation the mixed arm
    # shows); slots sized so queueing never hides the step economics
    page_size, chunk, budget = 4, 12, 12
    slots = max(int(slots), 16)
    max_len, num_pages = 96, 128
    dt_base, dt_token = 0.002, 0.001     # virtual s per step / token
    rpc_s, wire_bytes_per_s = 0.001, 2.0e7
    n_short, n_long = 8, 6
    short_new, long_new = 12, 6

    rng = np.random.RandomState(seed)
    sys_prefix = rng.randint(0, cfg.vocab_size,
                             size=40).astype(np.int64)
    recs = []
    for j in range(n_short):             # steady decode-heavy floor
        recs.append({
            "kind": "short", "arrival": 0.002 + j * 0.008,
            "prompt": rng.randint(0, cfg.vocab_size,
                                  size=int(rng.randint(2, 4)))
            .astype(np.int64),
            "n_new": short_new})
    for j in range(n_long):              # shared-prefix long stream,
        # spaced so each lands after the previous chain COMMITTED —
        # on the prefill specialist every long after the first is a
        # warm hit; the mixed arm keeps paying cold starved prefills
        tail = rng.randint(0, cfg.vocab_size,
                           size=4).astype(np.int64)
        recs.append({
            "kind": "long", "arrival": 0.040 + j * 0.065,
            "prompt": np.concatenate([sys_prefix, tail]),
            "n_new": long_new})
    recs.sort(key=lambda r: r["arrival"])
    n = len(recs)

    def make_engine(tclv):
        eng = ServingEngine(
            model, num_slots=slots, max_len=max_len,
            page_size=page_size, num_pages=num_pages,
            chunk_len=chunk, token_budget=budget,
            prefix_cache=True, kv_dtype="int8",
            clock=lambda: tclv[0])
        # compile-warm outside the virtual clock (same tiny prompt on
        # every engine, so the arms' trees start identical)
        eng.add_request(np.arange(1, 7, dtype=np.int64),
                        SamplingParams(max_new_tokens=2))
        eng.run()
        return eng

    def run_arm(disagg):
        """One 2-engine virtual-time replay. disagg=False: both
        engines general, route by load. disagg=True: engine 0 is the
        prefill specialist, engine 1 the decode specialist."""
        tcl = [[0.0], [0.0]]
        engines = [make_engine(tcl[0]), make_engine(tcl[1])]
        wall0 = time.monotonic()
        for r in recs:
            r["tokens"], r["times"] = [], []
            r["_seen"], r["t1"] = 0, None
        pending = list(recs)             # already arrival-sorted
        conts = []                       # (ready_t, rec) handoffs
        fab = {"handoffs": 0, "frame_bytes": 0, "frame_pages": 0,
               "grafted_pages": 0}
        steps = 0

        def packed(i):
            m = engines[i].metrics
            return m.packed_prefill_tokens + m.packed_decode_tokens

        live = [[], []]                  # per engine: [rec, req, leg]

        def admit(i, rec, prompt, n_new, t, leg):
            tcl[i][0] = max(tcl[i][0], t)
            req = engines[i].add_request(
                np.asarray(prompt, dtype=np.int64),
                SamplingParams(max_new_tokens=n_new))
            rec["_seen"] = 0
            live[i].append([rec, req, leg])

        inf = float("inf")
        while pending or conts \
                or any(e.has_work for e in engines):
            busy = [i for i in (0, 1) if engines[i].has_work]
            t_step = min((tcl[i][0] for i in busy), default=inf)
            t_arr = pending[0]["arrival"] if pending else inf
            t_cont = min((c[0] for c in conts), default=inf)
            if pending and t_arr <= min(t_step, t_cont):
                rec = pending.pop(0)
                if disagg:
                    if rec["kind"] == "long":
                        # prefill specialist: prompt pages + the
                        # first token, then hand off
                        admit(0, rec, rec["prompt"], 1, t_arr,
                              "prefill")
                    else:
                        admit(1, rec, rec["prompt"], rec["n_new"],
                              t_arr, "full")
                else:
                    i = min((0, 1), key=lambda j: (
                        engines[j].scheduler.queue_depth
                        + len(engines[j].scheduler.running), j))
                    admit(i, rec, rec["prompt"], rec["n_new"],
                          t_arr, "full")
            elif conts and t_cont <= t_step:
                conts.sort(key=lambda c: c[0])
                ready, rec = conts.pop(0)
                # the decode replica relays the handed-off first
                # token on its first scheduler tick after accepting
                # the handoff — the client's stream attaches there,
                # so the transfer rides in TTFT, not as a mid-stream
                # stall
                admit(1, rec,
                      np.concatenate([rec["prompt"],
                                      np.asarray([rec["t1"]],
                                                 dtype=np.int64)]),
                      rec["n_new"] - 1, ready, "cont")
                rec["t1_pending"] = True
            else:
                i = min(busy, key=lambda j: tcl[j][0])
                p0 = packed(i)
                engines[i].step()
                steps += 1
                tcl[i][0] += dt_base + dt_token * (packed(i) - p0)
                now = tcl[i][0]
                for entry in list(live[i]):
                    rec, req, leg = entry
                    if leg == "cont" and rec.get("t1_pending"):
                        rec["tokens"].append(int(rec["t1"]))
                        rec["times"].append(now)
                        rec["t1_pending"] = False
                    if leg != "prefill":
                        while rec["_seen"] < len(req.output_tokens):
                            rec["tokens"].append(
                                int(req.output_tokens[rec["_seen"]]))
                            rec["times"].append(now)
                            rec["_seen"] += 1
                    if req.finish_reason is not None:
                        live[i].remove(entry)
                        if leg == "prefill":
                            rec["t1"] = int(req.output_tokens[0])
                            frame = engines[0].export_prefix_frame(
                                rec["prompt"])
                            xfer = rpc_s
                            if frame is not None:
                                fab["grafted_pages"] += \
                                    engines[1].import_prefix_frame(
                                        frame)
                                fab["frame_bytes"] += len(frame)
                                fab["frame_pages"] += 1
                                xfer += (len(frame)
                                         / wire_bytes_per_s)
                            fab["handoffs"] += 1
                            conts.append((now + xfer, rec))
        for e in engines:
            e.drain()
        ttfts, itls = [], []
        for r in recs:
            ttfts.append(r["times"][0] - r["arrival"])
            itls.extend(b - a for a, b in zip(r["times"],
                                              r["times"][1:]))

        def pct(xs, q):
            xs = sorted(xs)
            return round(xs[min(len(xs) - 1, int(q * len(xs)))], 5)

        vt_end = max(tcl[0][0], tcl[1][0])
        fab["pages_sent"] = \
            engines[0].metrics.snapshot()["fabric"]["pages_sent"]
        fab["bytes_sent"] = \
            engines[0].metrics.snapshot()["fabric"]["bytes_sent"]
        return {
            "completed": sum(1 for r in recs
                             if len(r["tokens"]) == r["n_new"]),
            "steps": steps,
            "virtual_s": round(vt_end, 4),
            "wall_s": round(time.monotonic() - wall0, 4),
            "ttft_p50_s": pct(ttfts, 0.50),
            "ttft_p99_s": pct(ttfts, 0.99),
            "itl_p50_s": pct(itls, 0.50),
            "itl_p99_s": pct(itls, 0.99),
            "tokens_per_virtual_s": round(
                sum(len(r["tokens"]) for r in recs) / vt_end, 4),
            "fabric": fab if disagg else None,
            "token_streams": [list(r["tokens"]) for r in recs],
        }

    mixed = run_arm(disagg=False)
    disagg = run_arm(disagg=True)
    token_identical = (mixed["token_streams"]
                       == disagg["token_streams"])

    # restart warmth: engine C serves turn 1 then snapshots its tree;
    # a FRESH engine D imports the snapshot and serves turn 2 at
    # warm-hit cost; a fresh cold engine E pays the full prefill
    def single(eng, tclv, prompt, n_new):
        req = eng.add_request(np.asarray(prompt, dtype=np.int64),
                              SamplingParams(max_new_tokens=n_new))
        t0, first = tclv[0], None
        while eng.has_work:
            b0 = (eng.metrics.packed_prefill_tokens
                  + eng.metrics.packed_decode_tokens)
            eng.step()
            b1 = (eng.metrics.packed_prefill_tokens
                  + eng.metrics.packed_decode_tokens)
            tclv[0] += dt_base + dt_token * (b1 - b0)
            if first is None and req.output_tokens:
                first = tclv[0]
        return [int(t) for t in req.output_tokens], \
            round(first - t0, 5)

    tail1 = rng.randint(0, cfg.vocab_size, size=5).astype(np.int64)
    tail2 = rng.randint(0, cfg.vocab_size, size=5).astype(np.int64)
    turn1 = np.concatenate([sys_prefix, tail1])
    turn2 = np.concatenate([sys_prefix, tail2])
    tc, td, te = [0.0], [0.0], [0.0]
    eng_c = make_engine(tc)
    single(eng_c, tc, turn1, 6)
    snap = eng_c.export_prefix_state()
    tok_c, ttft_warm = single(eng_c, tc, turn2, 6)
    eng_d = make_engine(td)
    restored = eng_d.import_prefix_state(snap)
    tok_d, ttft_restored = single(eng_d, td, turn2, 6)
    eng_e = make_engine(te)
    tok_e, ttft_cold = single(eng_e, te, turn2, 6)

    for r in (mixed, disagg):
        del r["token_streams"]          # evidence, not payload
    return {
        "requests": n,
        "long_requests": n_long,
        "short_requests": n_short,
        "shared_prefix_tokens": int(sys_prefix.size),
        "slots": slots,
        "page_size": page_size,
        "token_budget": budget,
        "virtual_dt_base_s": dt_base,
        "virtual_dt_token_s": dt_token,
        "transfer_rpc_s": rpc_s,
        "transfer_bytes_per_s": wire_bytes_per_s,
        "mixed": mixed,
        "disagg": disagg,
        "token_identical": token_identical,
        "ttft_p99_ratio": round(
            disagg["ttft_p99_s"] / mixed["ttft_p99_s"], 4),
        "itl_p99_ratio": round(
            disagg["itl_p99_s"] / mixed["itl_p99_s"], 4),
        "restart": {
            "restored_pages": int(restored),
            "warm_ttft_s": ttft_warm,
            "restored_ttft_s": ttft_restored,
            "cold_ttft_s": ttft_cold,
            "token_identical": tok_c == tok_d == tok_e,
        },
    }


def http_trace(model, cfg, *, n_req, rate, max_new, max_len, chunk,
               prompt_lens, slots, page_size, pages, replicas, seed):
    """Same Poisson trace, but through the serving/http front-end over
    loopback: N replicas behind the least-loaded router, half the
    clients SSE-streaming (client-observed TTFT = first token frame),
    half blocking JSON (server-reported TTFT). Returns the `http`
    section of the report."""
    import http.client
    import threading

    from paddle_tpu.serving import Histogram, ServingEngine
    from paddle_tpu.serving.http import serve

    engines = [ServingEngine(model, num_slots=slots, max_len=max_len,
                             page_size=page_size, num_pages=pages,
                             chunk_len=chunk)
               for _ in range(replicas)]
    server = serve(engines, poll_interval_s=0.01)
    host, port = server.server_address[:2]

    def post(body):
        conn = http.client.HTTPConnection(host, port, timeout=300)
        conn.request("POST", "/v1/completions", json.dumps(body),
                     {"Content-Type": "application/json"})
        return conn, conn.getresponse()

    # warm every compiled program ON EVERY replica through the HTTP
    # path (concurrent requests per prompt length spread across the
    # router), then drop warmup from the metrics
    def warm(pl):
        conn, resp = post({"prompt": list(range(1, pl + 1)),
                           "max_tokens": 2})
        resp.read()
        conn.close()

    for pl in sorted(set(prompt_lens)):
        ws = [threading.Thread(target=warm, args=(pl,))
              for _ in range(replicas)]
        for w in ws:
            w.start()
        for w in ws:
            w.join()
    for eng in engines:
        eng.metrics.__init__()

    rng = np.random.RandomState(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n_req))
    prompts = [rng.randint(1, cfg.vocab_size,
                           size=rng.choice(prompt_lens)).tolist()
               for _ in range(n_req)]
    budgets = rng.randint(max(1, max_new // 2), max_new + 1,
                          size=n_req)

    lock = threading.Lock()
    ttft = Histogram()
    done = {"completed": 0, "tokens": 0, "errors": 0}

    def record(ttft_s, n_tokens, ok):
        with lock:
            if ttft_s is not None:
                ttft.record(ttft_s)
            done["tokens"] += n_tokens
            done["completed" if ok else "errors"] += 1

    def stream_client(i):
        sent = time.monotonic()
        conn, resp = post({"prompt": prompts[i], "stream": True,
                           "max_tokens": int(budgets[i])})
        first, n, fin = None, 0, None
        while True:
            line = resp.readline()
            if not line or line.strip() == b"data: [DONE]":
                break
            if not line.startswith(b"data: "):
                continue
            choice = json.loads(line[6:])["choices"][0]
            if choice["token"] is not None:
                n += 1
                if first is None:
                    first = time.monotonic() - sent
            if choice["finish_reason"]:
                fin = choice["finish_reason"]
        conn.close()
        record(first, n, fin in ("stop", "length"))

    def blocking_client(i):
        conn, resp = post({"prompt": prompts[i],
                           "max_tokens": int(budgets[i])})
        body = json.loads(resp.read())
        conn.close()
        if resp.status != 200:
            record(None, 0, False)
            return
        choice = body["choices"][0]
        record(body["timing"]["ttft_s"], len(choice["token_ids"]),
               choice["finish_reason"] in ("stop", "length"))

    t0 = time.monotonic()
    threads = []
    for i in range(n_req):
        wait = arrivals[i] - (time.monotonic() - t0)
        if wait > 0:
            time.sleep(wait)
        fn = stream_client if i % 2 == 0 else blocking_client
        threads.append(threading.Thread(target=fn, args=(i,)))
        threads[-1].start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    server.drain()

    snaps = [e.metrics.snapshot() for e in engines]
    return {
        "replicas": replicas,
        "requests": n_req,
        "stream_fraction": 0.5,
        "wall_s": round(wall, 4),
        "completed": done["completed"],
        "errors": done["errors"],
        "tokens_received": done["tokens"],
        "tokens_per_sec": (done["tokens"] / wall) if wall > 0 else None,
        "ttft_p50_s": ttft.percentile(50),
        "ttft_p99_s": ttft.percentile(99),
        "engine_decode_steps": sum(s["decode_steps"] for s in snaps),
        "engine_tokens_generated": sum(s["tokens_generated"]
                                       for s in snaps),
    }


def chaos_trace(model, cfg, *, n_req, rate, max_new, max_len, chunk,
                prompt_lens, slots, page_size, pages, seed):
    """--chaos: the SAME Poisson trace twice through a 2-replica HTTP
    front-end — once fault-free, once with the FaultInjector killing
    replica-0 after its first token has streamed. Every client is an
    SSE stream that records its tokens, worst inter-token gap, and the
    final frame's usage. Greedy + no EOS means every request must
    finish "length" with EXACTLY its budget of tokens — so
    `len(tokens) != budget` catches truncation AND duplication; the
    caller asserts truncated_streams == 0. recovery_p99_s is the p99
    of the migrated streams' worst client-observed inter-token gap
    (the latency blip a migration costs); goodput_ratio compares
    chaos-run token throughput against the fault-free run."""
    import threading
    import http.client

    from paddle_tpu.serving import (FaultInjector, Histogram,
                                    ServingEngine)
    from paddle_tpu.serving.http import serve

    rng = np.random.RandomState(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n_req))
    prompts = [rng.randint(1, cfg.vocab_size,
                           size=rng.choice(prompt_lens)).tolist()
               for _ in range(n_req)]
    budgets = rng.randint(max(2, max_new // 2), max_new + 1,
                          size=n_req)

    def run(inject: bool):
        engines = [ServingEngine(model, num_slots=slots,
                                 max_len=max_len, page_size=page_size,
                                 num_pages=pages, chunk_len=chunk)
                   for _ in range(2)]
        inj = FaultInjector(seed=seed) if inject else None
        server = serve(engines, poll_interval_s=0.01, faults=inj,
                       watchdog_timeout_s=10.0)
        host, port = server.server_address[:2]

        def post(body):
            conn = http.client.HTTPConnection(host, port, timeout=300)
            conn.request("POST", "/v1/completions", json.dumps(body),
                         {"Content-Type": "application/json"})
            return conn, conn.getresponse()

        # compile-warm both replicas before any fault can fire (a
        # first-use XLA compile inside the trace would read as a hang)
        for pl in sorted(set(len(p) for p in prompts)):
            ws = []
            for _ in range(2):
                def warm(pl=pl):
                    conn, resp = post({"prompt": list(range(1, pl + 1)),
                                       "max_tokens": 2})
                    resp.read()
                    conn.close()
                ws.append(threading.Thread(target=warm))
            for w in ws:
                w.start()
            for w in ws:
                w.join()
        for eng in engines:
            eng.metrics.__init__()

        lock = threading.Lock()
        rows = []

        def stream_client(i):
            conn, resp = post({"prompt": prompts[i], "stream": True,
                               "max_tokens": int(budgets[i])})
            toks, fin, usage = [], None, {}
            worst_gap, last_t = 0.0, time.monotonic()
            while True:
                line = resp.readline()
                if not line or line.strip() == b"data: [DONE]":
                    break
                if not line.startswith(b"data: "):
                    continue
                frame = json.loads(line[6:])
                if "error" in frame:
                    fin = "error"
                    continue
                choice = frame["choices"][0]
                if choice["token"] is not None:
                    now = time.monotonic()
                    worst_gap = max(worst_gap, now - last_t)
                    last_t = now
                    toks.append(choice["token"])
                if choice["finish_reason"]:
                    fin = choice["finish_reason"]
                    usage = frame.get("usage") or {}
            conn.close()
            with lock:
                rows.append({"i": i, "tokens": toks, "fin": fin,
                             "worst_gap_s": worst_gap,
                             "migrations": usage.get("migrations", 0)})

        killer_done = threading.Event()

        def killer():
            # kill replica-0 once it has STARTED streaming (>= 1
            # emitted token) — the mid-stream shape migration exists
            # for; deterministic trigger, injected raise
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if engines[0].metrics.tokens_generated >= 1:
                    inj.kill_at_step("replica-0", 0)
                    break
                time.sleep(0.002)
            killer_done.set()

        t0 = time.monotonic()
        kt = None
        if inject:
            kt = threading.Thread(target=killer)
            kt.start()
        threads = []
        for i in range(n_req):
            wait = arrivals[i] - (time.monotonic() - t0)
            if wait > 0:
                time.sleep(wait)
            threads.append(threading.Thread(target=stream_client,
                                            args=(i,)))
            threads[-1].start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        if kt is not None:
            kt.join()
        server.drain()
        total_tokens = sum(len(r["tokens"]) for r in rows)
        truncated = sum(
            1 for r in rows
            if r["fin"] != "length"
            or len(r["tokens"]) != int(budgets[r["i"]]))
        migrated = [r for r in rows if r["migrations"] > 0]
        rec = Histogram()
        for r in migrated:
            rec.record(r["worst_gap_s"])
        return {
            "wall_s": round(wall, 4),
            "completed": sum(1 for r in rows if r["fin"] == "length"),
            "truncated_streams": truncated,
            "migrated_streams": len(migrated),
            "tokens_received": total_tokens,
            "tokens_per_sec": (total_tokens / wall) if wall else None,
            "recovery_p99_s": rec.percentile(99),
            "kills_fired": inj.kills_fired if inj else 0,
        }

    base = run(inject=False)
    chaos = run(inject=True)
    ratio = (None if not base["tokens_per_sec"]
             else (chaos["tokens_per_sec"] or 0.0)
             / base["tokens_per_sec"])
    return {
        "replicas": 2,
        "requests": n_req,
        "killed_replica": "replica-0",
        "kills_fired": chaos["kills_fired"],
        "completed": chaos["completed"],
        "truncated_streams": chaos["truncated_streams"],
        "migrated_streams": chaos["migrated_streams"],
        "recovery_p99_s": chaos["recovery_p99_s"],
        "goodput_tokens_per_sec": chaos["tokens_per_sec"],
        "fault_free_tokens_per_sec": base["tokens_per_sec"],
        "goodput_ratio": ratio,
        "fault_free": base,
    }


if __name__ == "__main__":
    main()
