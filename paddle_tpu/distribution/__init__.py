"""paddle.distribution parity: probability distributions over Tensors.

Reference: python/paddle/distribution/ (distribution.py Distribution
base; normal/uniform/categorical/beta/dirichlet/multinomial/laplace/
lognormal/gumbel.py; independent.py, transformed_distribution.py,
transform.py, kl.py kl_divergence/register_kl). All densities are
written with framework ops, so log_prob/entropy are differentiable on
the eager tape and traceable under jit.to_static.
"""
from __future__ import annotations

import math
import numbers

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import register_op
from ..core import random as random_mod
from ..ops._helpers import apply_op
from ..ops import creation, math as ops_math, manipulation

__all__ = ["Distribution", "Normal", "Uniform", "Categorical",
           "Bernoulli", "Beta", "Dirichlet", "Multinomial", "Laplace",
           "LogNormal", "Gumbel", "Independent",
           "TransformedDistribution", "ExponentialFamily",
           "kl_divergence", "register_kl", "Transform",
           "AffineTransform", "ExpTransform", "SigmoidTransform",
           "AbsTransform", "ChainTransform", "IndependentTransform",
           "PowerTransform", "ReshapeTransform", "SoftmaxTransform",
           "StackTransform", "StickBreakingTransform", "TanhTransform"]


def _as_tensor(x, dtype="float32"):
    if isinstance(x, Tensor):
        return x
    if isinstance(x, numbers.Number):
        return creation.to_tensor(np.asarray(x, dtype))
    return creation.to_tensor(np.asarray(x, dtype))


register_op("dist_standard_gamma",
            lambda key, alpha: jax.random.gamma(key, alpha))


def _standard_gamma(alpha: Tensor) -> Tensor:
    key = Tensor(random_mod.next_key())
    return apply_op("dist_standard_gamma", key, alpha)


class Distribution:
    """Base class (reference: distribution/distribution.py)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return ops_math.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)

    def _extend_shape(self, sample_shape):
        return tuple(sample_shape) + self._batch_shape + \
            self._event_shape


class Normal(Distribution):
    """reference: distribution/normal.py."""

    def __init__(self, loc, scale, name=None):
        self.loc = _as_tensor(loc)
        self.scale = _as_tensor(scale)
        super().__init__(tuple(np.broadcast_shapes(
            tuple(self.loc.shape), tuple(self.scale.shape))))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return self.scale * self.scale

    @property
    def stddev(self):
        return self.scale

    def sample(self, shape=()):
        from ..core.tensor import no_grad
        with no_grad():
            return self.rsample(shape)

    def rsample(self, shape=()):
        out_shape = self._extend_shape(shape)
        eps = creation.randn(list(out_shape))
        return self.loc + self.scale * eps

    def log_prob(self, value):
        value = _as_tensor(value)
        var = self.scale * self.scale
        return (-((value - self.loc) ** 2) / (2.0 * var)
                - ops_math.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return 0.5 + 0.5 * math.log(2 * math.pi) + \
            ops_math.log(self.scale)


class Uniform(Distribution):
    """reference: distribution/uniform.py."""

    def __init__(self, low, high, name=None):
        self.low = _as_tensor(low)
        self.high = _as_tensor(high)
        super().__init__(tuple(np.broadcast_shapes(
            tuple(self.low.shape), tuple(self.high.shape))))

    @property
    def mean(self):
        return (self.low + self.high) / 2.0

    @property
    def variance(self):
        return (self.high - self.low) ** 2 / 12.0

    def sample(self, shape=()):
        out_shape = self._extend_shape(shape)
        u = creation.rand(list(out_shape))
        return self.low + (self.high - self.low) * u

    rsample = sample

    def log_prob(self, value):
        value = _as_tensor(value)
        from ..ops import comparison
        inside = ops_math.logical_and(
            comparison.greater_equal(value, self.low),
            comparison.less_than(value, self.high))
        lp = -ops_math.log(self.high - self.low)
        neg_inf = creation.full_like(value, -np.inf)
        from ..ops.manipulation import where
        return where(inside, lp + value * 0.0, neg_inf)

    def entropy(self):
        return ops_math.log(self.high - self.low)


class Categorical(Distribution):
    """reference: distribution/categorical.py (logits parameterized)."""

    def __init__(self, logits, name=None):
        self.logits = _as_tensor(logits)
        super().__init__(tuple(self.logits.shape[:-1]))
        self._n = self.logits.shape[-1]

    def _log_pmf(self):
        from ..nn.functional import log_softmax
        return log_softmax(self.logits, axis=-1)

    @property
    def probs(self):
        from ..nn.functional import softmax
        return softmax(self.logits, axis=-1)

    def sample(self, shape=()):
        n = int(np.prod(shape)) if shape else 1
        out = creation.multinomial(self.probs, num_samples=n,
                                   replacement=True)   # [..., n]
        if not shape:
            return manipulation.squeeze(out, axis=-1)
        # paddle convention: sample dims lead
        perm = [out.ndim - 1] + list(range(out.ndim - 1))
        out = manipulation.transpose(out, perm)
        return manipulation.reshape(
            out, list(shape) + list(self._batch_shape))

    def log_prob(self, value):
        value = _as_tensor(value).astype("int64")
        lp = self._log_pmf()
        from ..ops.manipulation import take_along_axis
        if value.ndim > lp.ndim - 1:
            # values carry sample dims beyond the batch: broadcast the
            # pmf alongside them
            lp = manipulation.broadcast_to(
                lp, list(value.shape) + [self._n])
        idx = manipulation.unsqueeze(value, axis=-1)
        out = take_along_axis(lp, idx, axis=-1, broadcast=False)
        return manipulation.squeeze(out, axis=-1)

    def probabilities(self):
        return self.probs

    def entropy(self):
        lp = self._log_pmf()
        return -ops_math.multiply(self.probs, lp).sum(axis=-1)


class Bernoulli(Distribution):
    """reference: distribution/bernoulli.py (probs parameterized)."""

    def __init__(self, probs, name=None):
        self.probs = _as_tensor(probs)
        super().__init__(tuple(self.probs.shape))

    @property
    def mean(self):
        return self.probs

    @property
    def variance(self):
        return self.probs * (1.0 - self.probs)

    def sample(self, shape=()):
        out_shape = self._extend_shape(shape)
        u = creation.rand(list(out_shape))
        from ..ops.comparison import less_than
        return less_than(u, self.probs).astype("float32")

    def log_prob(self, value):
        value = _as_tensor(value)
        eps = 1e-8
        p = self.probs
        return value * ops_math.log(p + eps) + \
            (1.0 - value) * ops_math.log(1.0 - p + eps)

    def entropy(self):
        eps = 1e-8
        p = self.probs
        return -(p * ops_math.log(p + eps)
                 + (1.0 - p) * ops_math.log(1.0 - p + eps))


class Beta(Distribution):
    """reference: distribution/beta.py — built on Dirichlet's gamma
    sampler."""

    def __init__(self, alpha, beta):
        self.alpha = _as_tensor(alpha)
        self.beta = _as_tensor(beta)
        super().__init__(tuple(np.broadcast_shapes(
            tuple(self.alpha.shape), tuple(self.beta.shape))))

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)

    @property
    def variance(self):
        s = self.alpha + self.beta
        return self.alpha * self.beta / (s * s * (s + 1.0))

    def sample(self, shape=()):
        ga = _standard_gamma(manipulation.broadcast_to(
            self.alpha, list(self._extend_shape(shape))))
        gb = _standard_gamma(manipulation.broadcast_to(
            self.beta, list(self._extend_shape(shape))))
        return ga / (ga + gb)

    rsample = sample

    def log_prob(self, value):
        value = _as_tensor(value)
        return ((self.alpha - 1.0) * ops_math.log(value)
                + (self.beta - 1.0) * ops_math.log(1.0 - value)
                - _lbeta(self.alpha, self.beta))

    def entropy(self):
        a, b = self.alpha, self.beta
        s = a + b
        return (_lbeta(a, b) - (a - 1.0) * ops_math.digamma(a)
                - (b - 1.0) * ops_math.digamma(b)
                + (s - 2.0) * ops_math.digamma(s))


def _lbeta(a, b):
    return ops_math.lgamma(a) + ops_math.lgamma(b) - \
        ops_math.lgamma(a + b)


class Dirichlet(Distribution):
    """reference: distribution/dirichlet.py."""

    def __init__(self, concentration):
        self.concentration = _as_tensor(concentration)
        super().__init__(tuple(self.concentration.shape[:-1]),
                         tuple(self.concentration.shape[-1:]))

    @property
    def mean(self):
        return self.concentration / self.concentration.sum(
            axis=-1, keepdim=True)

    @property
    def variance(self):
        c = self.concentration
        c0 = c.sum(axis=-1, keepdim=True)
        m = c / c0
        return m * (1.0 - m) / (c0 + 1.0)

    def sample(self, shape=()):
        g = _standard_gamma(manipulation.broadcast_to(
            self.concentration, list(self._extend_shape(shape))))
        return g / g.sum(axis=-1, keepdim=True)

    rsample = sample

    def log_prob(self, value):
        value = _as_tensor(value)
        c = self.concentration
        return (((c - 1.0) * ops_math.log(value)).sum(axis=-1)
                + ops_math.lgamma(c.sum(axis=-1))
                - ops_math.lgamma(c).sum(axis=-1))

    def entropy(self):
        c = self.concentration
        c0 = c.sum(axis=-1)
        k = c.shape[-1]
        return (ops_math.lgamma(c).sum(axis=-1)
                - ops_math.lgamma(c0)
                + (c0 - float(k)) * ops_math.digamma(c0)
                - ((c - 1.0) * ops_math.digamma(c)).sum(axis=-1))


class Multinomial(Distribution):
    """reference: distribution/multinomial.py."""

    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs = _as_tensor(probs)
        norm = self.probs.sum(axis=-1, keepdim=True)
        self.probs = self.probs / norm
        super().__init__(tuple(self.probs.shape[:-1]),
                         tuple(self.probs.shape[-1:]))

    @property
    def mean(self):
        return self.probs * float(self.total_count)

    @property
    def variance(self):
        return float(self.total_count) * self.probs * (1.0 - self.probs)

    def sample(self, shape=()):
        if shape:
            raise NotImplementedError(
                "Multinomial.sample(shape) beyond () — draw in a loop")
        draws = creation.multinomial(self.probs,
                                     num_samples=self.total_count,
                                     replacement=True)    # [..., N]
        k = self.probs.shape[-1]
        from ..nn.functional import one_hot
        oh = one_hot(draws.astype("int64"), num_classes=k)
        return oh.sum(axis=-2)

    def log_prob(self, value):
        value = _as_tensor(value)
        logits = ops_math.log(self.probs)
        return (ops_math.lgamma(
                    _as_tensor(float(self.total_count + 1)))
                - ops_math.lgamma(value + 1.0).sum(axis=-1)
                + (value * logits).sum(axis=-1))

    def entropy(self):
        raise NotImplementedError


class Laplace(Distribution):
    """reference: distribution/laplace.py."""

    def __init__(self, loc, scale):
        self.loc = _as_tensor(loc)
        self.scale = _as_tensor(scale)
        super().__init__(tuple(np.broadcast_shapes(
            tuple(self.loc.shape), tuple(self.scale.shape))))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return 2.0 * self.scale * self.scale

    @property
    def stddev(self):
        return math.sqrt(2.0) * self.scale

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        out_shape = self._extend_shape(shape)
        # epsilon guard: u = -0.5 exactly would give log(0) = -inf
        u = creation.rand(list(out_shape)) * (1 - 1e-7) - 0.5 + 1e-10
        sgn = ops_math.sign(u)
        return self.loc - self.scale * sgn * ops_math.log(
            1.0 - 2.0 * ops_math.abs(u))

    def log_prob(self, value):
        value = _as_tensor(value)
        return -ops_math.log(2.0 * self.scale) - \
            ops_math.abs(value - self.loc) / self.scale

    def entropy(self):
        return 1.0 + ops_math.log(2.0 * self.scale)


class LogNormal(Distribution):
    """reference: distribution/lognormal.py."""

    def __init__(self, loc, scale):
        self._normal = Normal(loc, scale)
        self.loc = self._normal.loc
        self.scale = self._normal.scale
        super().__init__(self._normal.batch_shape)

    @property
    def mean(self):
        return ops_math.exp(self.loc + self.scale * self.scale / 2.0)

    @property
    def variance(self):
        s2 = self.scale * self.scale
        return (ops_math.exp(s2) - 1.0) * ops_math.exp(
            2.0 * self.loc + s2)

    def sample(self, shape=()):
        return ops_math.exp(self._normal.sample(shape))

    def rsample(self, shape=()):
        return ops_math.exp(self._normal.rsample(shape))

    def log_prob(self, value):
        value = _as_tensor(value)
        lv = ops_math.log(value)
        return self._normal.log_prob(lv) - lv

    def entropy(self):
        return self._normal.entropy() + self.loc


class Gumbel(Distribution):
    """reference: distribution/gumbel.py."""

    _EULER = 0.57721566490153286060

    def __init__(self, loc, scale):
        self.loc = _as_tensor(loc)
        self.scale = _as_tensor(scale)
        super().__init__(tuple(np.broadcast_shapes(
            tuple(self.loc.shape), tuple(self.scale.shape))))

    @property
    def mean(self):
        return self.loc + self.scale * self._EULER

    @property
    def variance(self):
        return (math.pi ** 2 / 6.0) * self.scale * self.scale

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        out_shape = self._extend_shape(shape)
        u = creation.rand(list(out_shape)) * (1 - 1e-7) + 1e-10
        return self.loc - self.scale * ops_math.log(-ops_math.log(u))

    def log_prob(self, value):
        value = _as_tensor(value)
        z = (value - self.loc) / self.scale
        return -(z + ops_math.exp(-z)) - ops_math.log(self.scale)

    def entropy(self):
        return ops_math.log(self.scale) + 1.0 + self._EULER


class Independent(Distribution):
    """reference: distribution/independent.py — reinterprets batch dims
    as event dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self._r = int(reinterpreted_batch_rank)
        b = base.batch_shape
        super().__init__(b[:len(b) - self._r],
                         b[len(b) - self._r:] + base.event_shape)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        for _ in range(self._r):
            lp = lp.sum(axis=-1)
        return lp

    def entropy(self):
        e = self.base.entropy()
        for _ in range(self._r):
            e = e.sum(axis=-1)
        return e


class Transform:
    """reference: distribution/transform.py."""

    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        return -self.forward_log_det_jacobian(self.inverse(y))

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _as_tensor(loc)
        self.scale = _as_tensor(scale)

    def forward(self, x):
        return self.loc + self.scale * x

    def inverse(self, y):
        return (y - self.loc) / self.scale

    def forward_log_det_jacobian(self, x):
        return ops_math.log(ops_math.abs(self.scale)) + x * 0.0


class ExpTransform(Transform):
    def forward(self, x):
        return ops_math.exp(x)

    def inverse(self, y):
        return ops_math.log(y)

    def forward_log_det_jacobian(self, x):
        return x


class SigmoidTransform(Transform):
    def forward(self, x):
        from ..nn.functional import sigmoid
        return sigmoid(x)

    def inverse(self, y):
        return ops_math.log(y) - ops_math.log(1.0 - y)

    def forward_log_det_jacobian(self, x):
        from ..nn.functional import softplus
        return -softplus(-x) - softplus(x)


class AbsTransform(Transform):
    def forward(self, x):
        return ops_math.abs(x)

    def inverse(self, y):
        return y


class ChainTransform(Transform):
    """Composition t_n(...t_1(x)) (reference: transform.py:496)."""

    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t.forward_log_det_jacobian(x)
            x = t.forward(x)
        return total


class IndependentTransform(Transform):
    """Reinterprets batch dims as event dims: the log-det sums over the
    reinterpreted trailing dims (reference: transform.py:670)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)

    def forward(self, x):
        return self.base.forward(x)

    def inverse(self, y):
        return self.base.inverse(y)

    def forward_log_det_jacobian(self, x):
        from ..ops import reduction
        ld = self.base.forward_log_det_jacobian(x)
        axes = list(range(ld.ndim - self.reinterpreted_batch_rank,
                          ld.ndim))
        return reduction.sum(ld, axis=axes)


class PowerTransform(Transform):
    """y = x**p on the positive half-line (reference: transform.py:765)."""

    def __init__(self, power):
        self.power = _as_tensor(power)

    def forward(self, x):
        return x ** self.power

    def inverse(self, y):
        return y ** (1.0 / self.power)

    def forward_log_det_jacobian(self, x):
        return ops_math.log(ops_math.abs(
            self.power * x ** (self.power - 1.0)))


class ReshapeTransform(Transform):
    """Event-shape reshape; volume-preserving (reference:
    transform.py:829)."""

    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(int(d) for d in in_event_shape)
        self.out_event_shape = tuple(int(d) for d in out_event_shape)
        if int(np.prod(self.in_event_shape)) != \
                int(np.prod(self.out_event_shape)):
            raise ValueError("in/out event shapes must have equal size")

    def forward(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return manipulation.reshape(x, list(batch) +
                                    list(self.out_event_shape))

    def inverse(self, y):
        batch = y.shape[:y.ndim - len(self.out_event_shape)]
        return manipulation.reshape(y, list(batch) +
                                    list(self.in_event_shape))

    def forward_log_det_jacobian(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return creation.zeros(list(batch) or [1], "float32")


class SoftmaxTransform(Transform):
    """x -> softmax(x) over the last axis; not bijective — inverse maps
    to one representative preimage (reference: transform.py:996)."""

    def forward(self, x):
        from ..nn.functional import softmax
        return softmax(x, axis=-1)

    def inverse(self, y):
        return ops_math.log(y)


class StackTransform(Transform):
    """Applies transforms[i] to slice i along `axis` (reference:
    transform.py:1052)."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _map(self, fn_name, v):
        parts = manipulation.unstack(v, axis=self.axis)
        outs = [getattr(t, fn_name)(p)
                for t, p in zip(self.transforms, parts)]
        return manipulation.stack(outs, axis=self.axis)

    def forward(self, x):
        return self._map("forward", x)

    def inverse(self, y):
        return self._map("inverse", y)

    def forward_log_det_jacobian(self, x):
        return self._map("forward_log_det_jacobian", x)


class StickBreakingTransform(Transform):
    """R^K -> interior of the (K+1)-simplex via stick breaking
    (reference: transform.py:1172). With xo_i = x_i - log(K - i) and
    z_i = sigmoid(xo_i): y_i = z_i * prod_{j<i}(1 - z_j), and the final
    coordinate takes the remaining stick."""

    def _offsets(self, k):
        return creation.to_tensor(np.arange(k, 0, -1, dtype=np.float32))

    def forward(self, x):
        from ..nn.functional import sigmoid
        k = x.shape[-1]
        z = sigmoid(x - ops_math.log(self._offsets(k)))
        one = creation.ones(list(z.shape[:-1]) + [1], "float32")
        # cum[..., i] = prod_{j<=i}(1 - z_j); remaining stick before i
        # is [1, cum[..., :-1]]
        from ..ops import reduction as ops_red
        cum = ops_red.cumprod(1.0 - z, dim=-1)
        rem = manipulation.concat([one, cum[..., :-1]], axis=-1)
        return manipulation.concat([z * rem, cum[..., -1:]], axis=-1)

    def inverse(self, y):
        k = y.shape[-1] - 1
        from ..ops import reduction as ops_red
        cumsum = ops_red.cumsum(y, axis=-1)
        rem = 1.0 - manipulation.concat(
            [creation.zeros(list(y.shape[:-1]) + [1], "float32"),
             cumsum[..., :-2]], axis=-1)
        z = y[..., :-1] / rem
        return ops_math.log(z / (1.0 - z)) + \
            ops_math.log(self._offsets(k))

    def forward_log_det_jacobian(self, x):
        # lower-triangular J: log|det| = sum_i log z_i(1-z_i)rem_i
        #                              = sum_i log y_i + log sigmoid(-xo_i)
        from ..nn.functional import log_sigmoid
        from ..ops import reduction
        k = x.shape[-1]
        xo = x - ops_math.log(self._offsets(k))
        y = self.forward(x)
        return reduction.sum(ops_math.log(y[..., :-1]) +
                             log_sigmoid(-xo), axis=-1)


class TanhTransform(Transform):
    """y = tanh(x) (reference: transform.py:1238)."""

    def forward(self, x):
        return ops_math.tanh(x)

    def inverse(self, y):
        return ops_math.atanh(y)

    def forward_log_det_jacobian(self, x):
        from ..nn.functional import softplus
        # log(1 - tanh(x)^2) = 2*(log2 - x - softplus(-2x))
        return 2.0 * (float(np.log(2.0)) - x - softplus(-2.0 * x))


class TransformedDistribution(Distribution):
    """reference: distribution/transformed_distribution.py."""

    def __init__(self, base, transforms):
        self.base = base
        self.transforms = list(transforms)
        super().__init__(base.batch_shape, base.event_shape)

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        lp = 0.0
        y = value
        for t in reversed(self.transforms):
            x = t.inverse(y)
            lp = lp - t.forward_log_det_jacobian(x)
            y = x
        return self.base.log_prob(y) + lp


class ExponentialFamily(Distribution):
    """reference: distribution/exponential_family.py shell."""
    pass


# -- KL divergence -----------------------------------------------------------

_KL_REGISTRY: dict = {}


def register_kl(cls_p, cls_q):
    """reference: distribution/kl.py register_kl decorator."""
    def deco(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn
    return deco


def kl_divergence(p, q):
    for (cp, cq), fn in _KL_REGISTRY.items():
        if isinstance(p, cp) and isinstance(q, cq):
            return fn(p, q)
    raise NotImplementedError(
        f"no KL registered for ({type(p).__name__}, {type(q).__name__})")


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return 0.5 * (var_ratio + t1 - 1.0 - ops_math.log(var_ratio))


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    # +inf when p's support is not contained in q's (density ratio is
    # unbounded there); a finite/negative value would silently corrupt
    # variational objectives
    from ..ops import comparison
    from ..ops.manipulation import where
    ok = ops_math.logical_and(
        comparison.less_equal(q.low, p.low),
        comparison.greater_equal(q.high, p.high))
    val = ops_math.log((q.high - q.low) / (p.high - p.low))
    return where(ok, val, creation.full_like(val, np.inf))


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    lp, lq = p._log_pmf(), q._log_pmf()
    return (p.probs * (lp - lq)).sum(axis=-1)


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
    s1 = a1 + b1
    return (_lbeta(a2, b2) - _lbeta(a1, b1)
            + (a1 - a2) * ops_math.digamma(a1)
            + (b1 - b2) * ops_math.digamma(b1)
            + (a2 - a1 + b2 - b1) * ops_math.digamma(s1))


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    cp, cq = p.concentration, q.concentration
    sp = cp.sum(axis=-1)
    return (ops_math.lgamma(sp)
            - ops_math.lgamma(cq.sum(axis=-1))
            - ops_math.lgamma(cp).sum(axis=-1)
            + ops_math.lgamma(cq).sum(axis=-1)
            + ((cp - cq) * (ops_math.digamma(cp)
                            - manipulation.unsqueeze(
                                ops_math.digamma(sp), axis=-1))
               ).sum(axis=-1))
