"""Multi-chip tensor-parallel serving replica (serving/tp.py).

The load-bearing property (ISSUE 13 acceptance): an engine spanning a
(dp, mp) mesh of the conftest's 8 virtual CPU devices emits tokens
BIT-IDENTICAL to the single-device (mp=1) oracle — through prefix
cache on/off, int8/fp8 pools, grouped attention, COW, preemption swap
and speculative decoding — while compiling ONE unified trace whose
only collectives are bit-exact output all-gathers (one per layer,
ZERO all-reduces: fp math is never reassociated, which is why the
identity is provable rather than pinned-drift).

Non-slow tests stay lean (a handful of tiny-model engine compiles,
mp=2); the mp=4 x {int8, fp8, prefix, spec, preempt} matrix rides the
`slow` marker.
"""
import os
import re

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp import (GPTConfig, GPTForCausalLM, LlamaConfig,
                            LlamaForCausalLM)
from paddle_tpu.ops.pallas.paged_attention import \
    count_page_block_reads
from paddle_tpu.serving import (SamplingParams, ServingEngine,
                                ServingTP, collective_counts,
                                parse_mesh_spec, prometheus_render,
                                resolve_serving_mesh,
                                shared_prefix_groups)

_MODELS = {}   # engines never mutate the model: share per module


def tiny_llama():
    m = _MODELS.get("llama")
    if m is None:
        paddle.seed(11)
        cfg = LlamaConfig(vocab_size=89, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2, intermediate_size=48,
                          max_position_embeddings=128)
        m = _MODELS["llama"] = LlamaForCausalLM(cfg)
        m.eval()
    return m


def tiny_gpt():
    m = _MODELS.get("gpt")
    if m is None:
        paddle.seed(7)
        cfg = GPTConfig(vocab_size=97, hidden_size=32,
                        num_hidden_layers=2, num_attention_heads=4,
                        intermediate_size=64,
                        max_position_embeddings=128,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
        m = _MODELS["gpt"] = GPTForCausalLM(cfg)
        m.eval()
    return m


def _prompts(vocab, sizes, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, vocab, size=n).astype(np.int64)
            for n in sizes]


def _serve(eng, prompts, max_new=8, **sp):
    outs = eng.generate(
        prompts, [SamplingParams(max_new_tokens=max_new, **sp)
                  for _ in prompts])
    return [list(o.token_ids) for o in outs]


def _engine(model, mesh=None, **kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("chunk_len", 8)
    return ServingEngine(model, mesh=mesh, **kw)


# module-scoped engine pair: most non-slow tests drive traffic through
# these two (requests retire cleanly, so reuse is free — and reuse is
# itself a retrace check: the one trace must survive every batch)
@pytest.fixture(scope="module")
def mp1_eng():
    return _engine(tiny_llama())


@pytest.fixture(scope="module")
def mp2_eng():
    return _engine(tiny_llama(), mesh="dp1mp2")


class TestMeshResolution:
    def test_parse_specs(self):
        assert parse_mesh_spec("dp2mp4") == (2, 4)
        assert parse_mesh_spec("dp1xmp2") == (1, 2)
        assert parse_mesh_spec(" DP2MP2 ") == (2, 2)
        for bad in ("mp2", "dp2", "dp0mp2", "2x4", "dp2mp"):
            with pytest.raises(ValueError):
                parse_mesh_spec(bad)

    def test_env_gate(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_MESH", raising=False)
        assert resolve_serving_mesh(None) is None       # default off
        monkeypatch.setenv("PADDLE_TPU_MESH", "off")
        assert resolve_serving_mesh(None) is None
        monkeypatch.setenv("PADDLE_TPU_MESH", "dp1mp2")
        tp = resolve_serving_mesh(None)
        assert tp.shape == "dp1xmp2" and (tp.dp, tp.mp) == (1, 2)
        # an explicit False wins over the env (the oracle arm's knob)
        assert resolve_serving_mesh(False) is None
        monkeypatch.setenv("PADDLE_TPU_MESH", "nonsense")
        with pytest.raises(ValueError, match="dp2mp4"):
            resolve_serving_mesh(None)

    def test_overrides(self):
        assert resolve_serving_mesh((2, 2)).shape == "dp2xmp2"
        tp = ServingTP(1, 2)
        assert resolve_serving_mesh(tp) is tp
        # a jax Mesh / ProcessMesh with dp+mp axes passes through
        from paddle_tpu.distributed.mesh import ProcessMesh
        pm = ProcessMesh(shape=[2, 2], dim_names=["dp", "mp"])
        got = resolve_serving_mesh(pm)
        assert (got.dp, got.mp) == (2, 2)
        with pytest.raises(ValueError, match="mp"):
            resolve_serving_mesh(
                ProcessMesh(shape=[2], dim_names=["dp"]))
        with pytest.raises(ValueError, match="tuple"):
            resolve_serving_mesh(3.5)

    def test_too_many_devices(self):
        with pytest.raises(ValueError, match="devices"):
            ServingTP(4, 4)    # 16 > the conftest's 8


class TestGeometryValidation:
    def test_kv_head_mismatch_names_dims_and_legal_values(self):
        # llama tiny: H_kv=2, H=4, hidden=32 — mp=4 cannot split the
        # kv heads; the error must name the dims and the legal mps
        with pytest.raises(ValueError) as ei:
            _engine(tiny_llama(), mesh="dp1mp4")
        msg = str(ei.value)
        assert "H_kv=2" in msg and "mp=4" in msg
        assert "H=4" in msg and "hidden=32" in msg
        assert "Legal mp values" in msg and "[1, 2]" in msg

    def test_validation_happens_at_construction(self):
        # no engine state, no compiled program, no sharded array —
        # the raise precedes all of it (no silent mis-shard)
        try:
            _engine(tiny_llama(), mesh="dp2mp4")
        except ValueError as exc:
            assert "H_kv=2" in str(exc)
        else:
            pytest.fail("geometry error not raised")

    def test_legal_mp_passes(self):
        eng = _engine(tiny_llama(), mesh="dp1mp2")
        assert (eng.mp, eng.dp) == (2, 1)
        assert eng.tp.shape == "dp1xmp2"
        # per-chip page cost is 1/mp of the full page
        assert eng.page_bytes_per_chip * 2 == eng.page_bytes


class TestTokenIdentity:
    """mp>1 must be BIT-token-identical to the mp=1 oracle."""

    def test_mp2_matches_mp1_and_solo_oracle(self, mp1_eng, mp2_eng):
        m = tiny_llama()
        prompts = _prompts(89, (5, 9, 17, 3, 12, 7), seed=1)
        t1 = _serve(mp1_eng, prompts)
        t2 = _serve(mp2_eng, prompts)
        assert t1 == t2
        # one solo CompiledGenerator cross-check anchors the pair to
        # the offline oracle (same-length prompts share one compile)
        solo = m.generate(paddle.to_tensor(prompts[0][None]),
                          max_new_tokens=8).numpy()[0, prompts[0].size:]
        assert t2[0] == list(solo)

    @pytest.mark.slow
    def test_mp2_dp2_full_mesh(self, mp1_eng):
        # dp replicates (control and data plane): a dp2xmp2 mesh must
        # still be bit-token-identical to the single-device oracle
        prompts = _prompts(89, (4, 11, 6), seed=2)
        eng = _engine(tiny_llama(), mesh=(2, 2))
        assert _serve(eng, prompts) == _serve(mp1_eng, prompts)

    def test_mp2_prefix_cache_off(self, mp1_eng):
        # the mp1 arm rides the module fixture (prefix ON): cache
        # on/off is token-identical by PR 5's proven gate, so the
        # sharded prefix-OFF engine must match it bit-for-bit too
        prompts = _prompts(89, (6, 13, 8), seed=3)
        e2 = _engine(tiny_llama(), mesh="dp1mp2", prefix_cache=False)
        assert _serve(e2, prompts) == _serve(mp1_eng, prompts)

    def test_mp2_int8_pool(self):
        # int8 is lossy vs fp but DETERMINISTIC: the sharded int8
        # engine must match the single-device int8 engine bit-for-bit
        # (quantize-on-write and fused dequant both ride the sharded
        # head axis; scales shard alongside their codes)
        prompts = _prompts(89, (5, 14, 9, 3), seed=4)
        e1 = _engine(tiny_llama(), kv_dtype="int8")
        e2 = _engine(tiny_llama(), mesh="dp1mp2", kv_dtype="int8")
        assert _serve(e1, prompts) == _serve(e2, prompts)


class TestOneTrace:
    """The mesh must not cost a single extra trace: ONE unified
    program, one-trace COW and swap programs."""

    def test_retrace_probe(self, mp2_eng):
        # the fixture already served several batches with different
        # membership/page mixes across tests; serve one more and
        # assert the ONE-trace discipline held throughout
        prompts = _prompts(89, (7, 15, 4), seed=5)
        _serve(mp2_eng, prompts)
        assert mp2_eng._unified_fn is not None
        assert mp2_eng._unified_fn._cache_size() == 1
        assert mp2_eng._prefill_fns == {}     # legacy families never built
        assert mp2_eng._decode_fn is None

    def test_cow_and_swap_one_trace_on_sharded_pool(self):
        m = tiny_llama()
        # COW: finish a request mid-page, then two follow-ups sharing
        # the partial page force two copy-on-writes over different
        # (src, dst) pairs — ONE compiled copy program serves both,
        # moving every shard's page slice together
        eng = _engine(m, mesh="dp1mp2", num_slots=2, num_pages=17)
        base = _prompts(89, (13,), seed=6)[0]
        _serve(eng, [base], max_new=3)
        for seed in (7, 8):
            tail = _prompts(89, (5,), seed=seed)[0]
            _serve(eng, [np.concatenate([base[:13], tail])], max_new=3)
        assert eng._copy_page_fn is not None
        assert eng._copy_page_fn._cache_size() == 1
        # preemption swap: fill the pool with low-priority residents,
        # admit a high-priority head — the victim's pages swap out
        # whole-page (codes+slices of every shard together) and later
        # restore, each through ONE compiled program
        lo = [eng.add_request(p, SamplingParams(max_new_tokens=10,
                                                priority=5))
              for p in _prompts(89, (9, 12), seed=9)]
        for _ in range(4):
            eng.step()
        hi = eng.add_request(_prompts(89, (8,), seed=10)[0],
                             SamplingParams(max_new_tokens=6,
                                            priority=0))
        eng.run()
        assert all(r.finished for r in [*lo, hi])
        assert sum(r.preemptions for r in [*lo, hi]) >= 1
        assert eng._swap_out_fn._cache_size() == 1
        assert eng._swap_in_fn._cache_size() == 1
        assert eng._unified_fn._cache_size() == 1


class TestCollectives:
    """The sharded step's collective contract: zero all-reduces
    (never reassociate fp math), exactly ONE output all-gather per
    layer per step."""

    def test_compiled_hlo_census(self, mp2_eng):
        prompts = _prompts(89, (5, 8), seed=11)
        _serve(mp2_eng, prompts)
        counts = mp2_eng.collective_counts()
        assert counts["all_reduce"] == 0
        assert counts["reduce_scatter"] == 0
        assert counts["all_gather"] == mp2_eng.n_layers
        # helper sanity: the census comes from real HLO text
        assert collective_counts("x = all-gather(y)\n"
                                 "z = all-reduce(w)") == {
            "all_reduce": 1, "all_gather": 1, "reduce_scatter": 0,
            "all_to_all": 0, "collective_permute": 0}

    def test_collective_counts_needs_mesh_and_a_step(self, mp1_eng):
        with pytest.raises(ValueError, match="mesh"):
            mp1_eng.collective_counts()
        fresh = _engine(tiny_llama(), mesh="dp1mp2")
        with pytest.raises(ValueError, match="no unified step"):
            fresh.collective_counts()

    def test_flight_record_carries_per_step_collectives(self, mp2_eng,
                                                        mp1_eng):
        _serve(mp2_eng, _prompts(89, (6,), seed=12))
        rec = mp2_eng.obs.flight.snapshot()["steps"][-1]
        # the modeled per-step count: one output all-gather per layer
        assert rec["collectives"] == mp2_eng.n_layers
        _serve(mp1_eng, _prompts(89, (6,), seed=12))
        rec1 = mp1_eng.obs.flight.snapshot()["steps"][-1]
        assert rec1["collectives"] == 0


class TestGroupedShardingInterplay:
    """Grouped attention x sharding: the group operands are
    replicated scalars, the grouped walk on a SHARDED pool stays
    token-identical to flat, and the DMA model counts per-shard."""

    def test_grouped_walk_on_sharded_pool_token_identical(
            self, mp1_eng, mp2_eng):
        # both fixtures run the grouped walk (default on); a
        # shared-prefix trace forms real groups over the SHARDED pool
        # and the tokens must still match the single-device engine
        # bit-for-bit (PR 11 proved grouped==flat on one device, so
        # this chains to flat). Zero extra engine compiles.
        sysp = _prompts(89, (21,), seed=30)[0]
        prompts = [np.concatenate([sysp, t])
                   for t in _prompts(89, (3, 5, 2), seed=31)]
        before = mp2_eng.metrics.snapshot(
        )["shared_page_reads_saved_total"]
        t1 = _serve(mp1_eng, [sysp], max_new=2)
        t2 = _serve(mp2_eng, [sysp], max_new=2)
        assert t1 == t2
        assert _serve(mp1_eng, prompts, max_new=6) == \
            _serve(mp2_eng, prompts, max_new=6)
        after = mp2_eng.metrics.snapshot(
        )["shared_page_reads_saved_total"]
        assert after > before        # groups really formed + saved

    @pytest.mark.slow
    def test_grouped_vs_flat_on_sharded_pool(self):
        m = tiny_llama()
        sysp = _prompts(89, (21,), seed=13)[0]
        prompts = [np.concatenate([sysp, t])
                   for t in _prompts(89, (3, 5, 2, 9), seed=14)]
        runs = {}
        for grouped in (True, False):
            eng = _engine(m, mesh="dp1mp2", grouped=grouped)
            _serve(eng, [sysp], max_new=2)     # warm the radix tree
            runs[grouped] = (_serve(eng, prompts, max_new=6), eng)
        assert runs[True][0] == runs[False][0]
        # groups really formed on the sharded pool (reads saved > 0)
        snap = runs[True][1].metrics.snapshot()
        assert snap["shared_page_reads_saved_total"] > 0
        assert runs[True][1]._unified_fn._cache_size() == 1

    def test_group_operands_ride_replicated(self):
        # the grouped-walk operands are [S] host scalars; on the mesh
        # they enter the step fully replicated — operand data, never
        # sharded state
        pt = np.array([[1, 2, 0], [1, 2, 0], [3, 0, 0]], np.int32)
        gid, gld, gcn = shared_prefix_groups(pt, np.array([1, 1, 1]))
        tp = ServingTP(1, 2)
        for arr in (gid, gld, gcn):
            dev = tp.replicate(np.asarray(arr))
            assert dev.sharding.is_fully_replicated

    def test_per_shard_read_model_scales_with_mp(self):
        # one shared span of 2 pages across 3 rows + a private tail
        pt = np.array([[1, 2, 4, 0], [1, 2, 5, 0], [1, 2, 6, 7]],
                      np.int32)
        pos = np.array([20, 20, 28])
        q_len = np.array([1, 1, 1])
        gid, gld, gcn = shared_prefix_groups(pt, q_len)
        base_flat, base_grp, sizes = count_page_block_reads(
            pt, pos, q_len, gid, gcn, page_size=8)
        assert base_grp < base_flat and sizes == [3]
        # n_kv=4: per-chip reads drop with mp (each chip walks
        # n_kv/mp local heads over 1/mp page slices)
        per_chip = {}
        for mp in (1, 2, 4):
            f, g, _ = count_page_block_reads(
                pt, pos, q_len, gid, gcn, page_size=8, n_kv=4, mp=mp)
            per_chip[mp] = (f, g)
        assert per_chip[1] == (4 * base_flat, 4 * base_grp)
        assert per_chip[2] == (2 * base_flat, 2 * base_grp)
        assert per_chip[4] == (base_flat, base_grp)
        # per-chip reads SAVED by grouping scale the same way
        saved = {mp: f - g for mp, (f, g) in per_chip.items()}
        assert saved[1] == 2 * saved[2] == 4 * saved[4] > 0


class TestObservability:
    def test_metrics_and_debug_state_tags(self, mp2_eng):
        snap = mp2_eng.metrics.snapshot()
        assert snap["mesh"] == "dp1xmp2"
        assert (snap["mp"], snap["dp"]) == (2, 1)
        assert snap["pool"]["shard_bytes_per_page"] * 2 == \
            snap["pool"]["bytes_per_page"]
        st = mp2_eng.debug_state()
        assert st["config"]["mesh"] == "dp1xmp2"
        assert (st["config"]["mp"], st["config"]["dp"]) == (2, 1)

    def test_prometheus_render_mesh_labels_valid(self, mp2_eng):
        text = prometheus_render({"r0": mp2_eng.metrics.snapshot()})
        info = [ln for ln in text.splitlines()
                if ln.startswith("paddle_serving_engine_info")]
        assert len(info) == 1
        assert 'mesh="dp1xmp2"' in info[0]
        assert 'mp="2"' in info[0] and 'dp="1"' in info[0]
        shard = [ln for ln in text.splitlines()
                 if ln.startswith("paddle_serving_pool_shard_bytes_per_page")]
        assert len(shard) == 1 and shard[0].split()[-1] != "0"
        # every line is exposition-shaped (the strict cross-field
        # checks live in test_serving_obs's format suite)
        rx = re.compile(
            r'^[A-Za-z_:][A-Za-z0-9_:]*'
            r'(\{[A-Za-z0-9_]+="[^"]*"(,[A-Za-z0-9_]+="[^"]*")*\})?'
            r' -?[0-9.eE+\-]+(inf|nan)?$')
        for ln in text.splitlines():
            if not ln or ln.startswith("#"):
                continue
            assert rx.match(ln), ln


@pytest.mark.slow
class TestMp4Matrix:
    """The deep matrix on the full 8-device budget: GPT (H_kv=4)
    shards at mp=4; every serving feature stays bit-token-identical
    to its single-device twin."""

    def _pair(self, **kw):
        m = tiny_gpt()
        prompts = _prompts(97, (5, 9, 17, 3, 12, 7), seed=20)
        e1 = _engine(m, **kw)
        e2 = _engine(m, mesh="dp1mp4", **kw)
        return _serve(e1, prompts), _serve(e2, prompts), e2

    def test_mp4_fp(self):
        t1, t2, eng = self._pair()
        assert t1 == t2
        counts = eng.collective_counts()
        assert counts["all_reduce"] == 0
        assert counts["all_gather"] == eng.n_layers

    def test_mp4_int8(self):
        t1, t2, _ = self._pair(kv_dtype="int8")
        assert t1 == t2

    def test_mp4_fp8(self):
        t1, t2, _ = self._pair(kv_dtype="fp8")
        assert t1 == t2

    def test_mp4_prefix_off(self):
        t1, t2, _ = self._pair(prefix_cache=False)
        assert t1 == t2

    def test_mp4_spec(self):
        t1, t2, _ = self._pair(spec="ngram:3")
        assert t1 == t2

    def test_mp4_preempt_swap(self):
        m = tiny_gpt()
        outs = {}
        for mesh in (None, "dp2mp4"):          # all 8 devices
            eng = _engine(m, mesh=mesh, num_slots=2, num_pages=17)
            lo = [eng.add_request(p, SamplingParams(
                max_new_tokens=10, priority=5))
                for p in _prompts(97, (9, 12), seed=21)]
            for _ in range(4):
                eng.step()
            hi = eng.add_request(
                _prompts(97, (8,), seed=22)[0],
                SamplingParams(max_new_tokens=6, priority=0))
            eng.run()
            assert sum(r.preemptions for r in [*lo, hi]) >= 1
            outs[mesh] = [list(r.output_tokens) for r in [*lo, hi]]
        assert outs[None] == outs["dp2mp4"]


@pytest.mark.slow
def test_serving_bench_tp_ab_smoke(tmp_path, monkeypatch):
    """The --tp-ab bench end to end: schema v12, token identity,
    residents-per-chip win and the pinned collective census all
    asserted by the script itself."""
    import importlib.util
    import json
    import sys

    spec = importlib.util.spec_from_file_location(
        "serving_bench", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts", "serving_bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = str(tmp_path / "BENCH_serving.json")
    monkeypatch.setattr(sys, "argv",
                        ["serving_bench.py", "--smoke", "--requests",
                         "3", "--tp-ab", "--out", out])
    mod.main()
    with open(out) as f:
        report = json.load(f)
    assert report["schema_version"] == 19
    tp = report["tp"]
    assert tp["token_identical"] is True
    assert tp["residents_ratio"] >= 1.5
    assert tp["collectives"]["all_reduce"] == 0
    assert tp["output_collectives_per_layer_step"] == 1.0
    assert tp["mp2"]["page_bytes_per_chip"] * 2 == \
        tp["mp2"]["page_bytes"]
