"""paddle.text.datasets parity: Imdb, Imikolov, Movielens, UCIHousing,
Conll05st, WMT14, WMT16.

Reference: /root/reference/python/paddle/text/datasets/{imdb,imikolov,
movielens,uci_housing,conll05,wmt14,wmt16}.py. Each class parses the
SAME archive formats as the reference (aclImdb tar, PTB simple-examples
tar, ml-1m zip, conll05st-release tar, wmt tars) from a local
`data_file` path. Automatic download is unavailable in this build (no
network egress): constructing without `data_file` raises with
instructions, matching paddle_tpu.vision.datasets' policy.
"""
from __future__ import annotations

import collections
import gzip
import re
import string
import tarfile
import zipfile

import numpy as np

from ...io import Dataset

__all__ = ["Imdb", "Imikolov", "Movielens", "UCIHousing", "Conll05st",
           "WMT14", "WMT16", "ViterbiDecoder", "viterbi_decode"]

# re-export the decoding utilities living in text/
from ..tokenizer import __name__ as _  # noqa: F401  (package anchor)
try:
    from .. import viterbi_decode, ViterbiDecoder  # noqa: F401
except ImportError:  # pragma: no cover
    pass


def _no_download(name):
    raise RuntimeError(
        f"{name}: automatic download is unavailable (no network egress). "
        f"Pass data_file= pointing at the dataset archive in the "
        f"reference format.")


class Imdb(Dataset):
    """IMDB sentiment (reference: text/datasets/imdb.py — aclImdb tar;
    samples are (word-id array, [label]) with label 0=pos, 1=neg)."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        assert mode.lower() in ("train", "test")
        self.mode = mode.lower()
        if data_file is None:
            _no_download(type(self).__name__)
        self.data_file = data_file
        self.word_idx = self._build_work_dict(cutoff)
        self._load_anno()

    def _tokenize(self, pattern):
        data = []
        with tarfile.open(self.data_file) as tarf:
            tf = tarf.next()
            while tf is not None:
                if bool(pattern.match(tf.name)):
                    data.append(
                        tarf.extractfile(tf).read().rstrip(b"\n\r")
                        .translate(None,
                                   string.punctuation.encode("latin-1"))
                        .lower().split())
                tf = tarf.next()
        return data

    def _build_work_dict(self, cutoff):
        word_freq = collections.defaultdict(int)
        pattern = re.compile(
            r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$")
        for doc in self._tokenize(pattern):
            for word in doc:
                word_freq[word] += 1
        word_freq = [x for x in word_freq.items() if x[1] > cutoff]
        dictionary = sorted(word_freq, key=lambda x: (-x[1], x[0]))
        words = [w for w, _ in dictionary]
        word_idx = dict(zip(words, range(len(words))))
        word_idx[b"<unk>"] = len(words)
        return word_idx

    def _load_anno(self):
        pos = re.compile(rf"aclImdb/{self.mode}/pos/.*\.txt$")
        neg = re.compile(rf"aclImdb/{self.mode}/neg/.*\.txt$")
        unk = self.word_idx[b"<unk>"]
        self.docs, self.labels = [], []
        for pattern, label in ((pos, 0), (neg, 1)):
            for doc in self._tokenize(pattern):
                self.docs.append([self.word_idx.get(w, unk)
                                  for w in doc])
                self.labels.append(label)

    def __getitem__(self, idx):
        return np.array(self.docs[idx]), np.array([self.labels[idx]])

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB language-model corpus (reference: text/datasets/imikolov.py —
    simple-examples tar; NGRAM windows or SEQ (src, trg) pairs)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=True):
        assert data_type.upper() in ("NGRAM", "SEQ")
        assert mode.lower() in ("train", "valid")
        self.data_type = data_type.upper()
        self.window_size = window_size
        self.mode = mode.lower()
        self.min_word_freq = min_word_freq
        if data_file is None:
            _no_download(type(self).__name__)
        self.data_file = data_file
        self.word_idx = self._build_work_dict(min_word_freq)
        self._load_anno()

    @staticmethod
    def word_count(f, word_freq=None):
        if word_freq is None:
            word_freq = collections.defaultdict(int)
        for line in f:
            for w in line.strip().split():
                word_freq[w] += 1
            word_freq[b"<s>"] += 1
            word_freq[b"<e>"] += 1
        return word_freq

    def _build_work_dict(self, cutoff):
        with tarfile.open(self.data_file) as tf:
            trainf = tf.extractfile(
                "./simple-examples/data/ptb.train.txt")
            testf = tf.extractfile(
                "./simple-examples/data/ptb.valid.txt")
            word_freq = self.word_count(testf, self.word_count(trainf))
            word_freq.pop(b"<unk>", None)
            word_freq = [x for x in word_freq.items() if x[1] > cutoff]
            words = [w for w, _ in sorted(word_freq,
                                          key=lambda x: (-x[1], x[0]))]
            word_idx = dict(zip(words, range(len(words))))
            word_idx[b"<unk>"] = len(words)
        return word_idx

    def _load_anno(self):
        self.data = []
        with tarfile.open(self.data_file) as tf:
            f = tf.extractfile(
                f"./simple-examples/data/ptb.{self.mode}.txt")
            unk = self.word_idx[b"<unk>"]
            for line in f:
                if self.data_type == "NGRAM":
                    assert self.window_size > -1, "Invalid gram length"
                    toks = [b"<s>"] + line.strip().split() + [b"<e>"]
                    if len(toks) >= self.window_size:
                        ids = [self.word_idx.get(w, unk) for w in toks]
                        for i in range(self.window_size, len(ids) + 1):
                            self.data.append(
                                tuple(ids[i - self.window_size:i]))
                else:
                    toks = line.strip().split()
                    ids = [self.word_idx.get(w, unk) for w in toks]
                    src = [self.word_idx[b"<s>"]] + ids
                    trg = ids + [self.word_idx[b"<e>"]]
                    if self.window_size > 0 and \
                            len(src) > self.window_size:
                        continue
                    self.data.append((src, trg))

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, movie_title_dict):
        return [[self.index],
                [categories_dict[c] for c in self.categories],
                [movie_title_dict[w.lower()]
                 for w in self.title.split()]]


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = int(age)
        self.job_id = int(job_id)

    def value(self):
        return [[self.index], [0 if self.is_male else 1], [self.age],
                [self.job_id]]


class Movielens(Dataset):
    """ml-1m ratings (reference: text/datasets/movielens.py — zip with
    movies.dat/users.dat/ratings.dat '::'-separated latin records)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        assert mode.lower() in ("train", "test")
        self.mode = mode.lower()
        if data_file is None:
            _no_download(type(self).__name__)
        self.data_file = data_file
        self.test_ratio = test_ratio
        np.random.seed(rand_seed)
        self._load_meta_info()
        self._load_data()

    def _load_meta_info(self):
        pattern = re.compile(r"^(.*)\((\d+)\)$")
        self.movie_info, self.user_info = {}, {}
        self.movie_title_dict, self.categories_dict = {}, {}
        title_words, categories = set(), set()
        with zipfile.ZipFile(self.data_file) as package:
            with package.open("ml-1m/movies.dat") as f:
                for line in f:
                    line = line.decode("latin")
                    movie_id, title, cats = line.strip().split("::")
                    cats = cats.split("|")
                    categories.update(cats)
                    title = pattern.match(title).group(1)
                    self.movie_info[int(movie_id)] = MovieInfo(
                        movie_id, cats, title)
                    for w in title.split():
                        title_words.add(w.lower())
            for i, w in enumerate(sorted(title_words)):
                self.movie_title_dict[w] = i
            for i, c in enumerate(sorted(categories)):
                self.categories_dict[c] = i
            with package.open("ml-1m/users.dat") as f:
                for line in f:
                    line = line.decode("latin")
                    uid, gender, age, job, _ = line.strip().split("::")
                    self.user_info[int(uid)] = UserInfo(uid, gender,
                                                        age, job)

    def _load_data(self):
        self.data = []
        is_test = self.mode == "test"
        with zipfile.ZipFile(self.data_file) as package:
            with package.open("ml-1m/ratings.dat") as f:
                for line in f:
                    line = line.decode("latin")
                    if (np.random.random() < self.test_ratio) != is_test:
                        continue
                    uid, mov_id, rating, _ = line.strip().split("::")
                    rating = float(rating) * 2 - 5.0
                    mov = self.movie_info[int(mov_id)]
                    usr = self.user_info[int(uid)]
                    self.data.append(
                        usr.value() +
                        mov.value(self.categories_dict,
                                  self.movie_title_dict) + [[rating]])

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


class UCIHousing(Dataset):
    """Boston housing regression (reference:
    text/datasets/uci_housing.py — whitespace floats, 14 columns,
    80/20 split, feature normalization over the WHOLE file)."""

    def __init__(self, data_file=None, mode="train", download=True):
        assert mode.lower() in ("train", "test")
        self.mode = mode.lower()
        if data_file is None:
            _no_download(type(self).__name__)
        self.data_file = data_file
        self._load_data()
        from ...core import dtype as dtypes
        self.dtype = dtypes.get_default_dtype().np_dtype

    def _load_data(self, feature_num=14, ratio=0.8):
        data = np.fromfile(self.data_file, sep=" ")
        data = data.reshape(data.shape[0] // feature_num, feature_num)
        maximums = data.max(axis=0)
        minimums = data.min(axis=0)
        avgs = data.sum(axis=0) / data.shape[0]
        for i in range(feature_num - 1):
            data[:, i] = (data[:, i] - avgs[i]) / \
                (maximums[i] - minimums[i])
        offset = int(data.shape[0] * ratio)
        self.data = data[:offset] if self.mode == "train" else \
            data[offset:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return (np.array(row[:-1]).astype(self.dtype),
                np.array(row[-1:]).astype(self.dtype))

    def __len__(self):
        return len(self.data)


_UNK_IDX = 0


class Conll05st(Dataset):
    """CoNLL-2005 SRL test split (reference: text/datasets/conll05.py —
    tar with gzipped words/props columns; 9-field samples with verb
    context windows and B/I/O label ids)."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None,
                 emb_file=None, download=True):
        for arg, name in ((data_file, "data_file"),
                          (word_dict_file, "word_dict_file"),
                          (verb_dict_file, "verb_dict_file"),
                          (target_dict_file, "target_dict_file")):
            if arg is None:
                _no_download(f"{type(self).__name__} ({name})")
        self.data_file = data_file
        self.word_dict = self._load_dict(word_dict_file)
        self.predicate_dict = self._load_dict(verb_dict_file)
        self.label_dict = self._load_label_dict(target_dict_file)
        self._load_anno()

    @staticmethod
    def _load_dict(filename):
        with open(filename) as f:
            return {line.strip(): i for i, line in enumerate(f)}

    @staticmethod
    def _load_label_dict(filename):
        d = {}
        tags = set()
        with open(filename) as f:
            for line in f:
                line = line.strip()
                if line.startswith("B-") or line.startswith("I-"):
                    tags.add(line[2:])
        index = 0
        for tag in sorted(tags):
            d["B-" + tag] = index
            index += 1
            d["I-" + tag] = index
            index += 1
        d["O"] = index
        return d

    def _load_anno(self):
        self.sentences, self.predicates, self.labels = [], [], []
        with tarfile.open(self.data_file) as tf:
            wf = tf.extractfile(
                "conll05st-release/test.wsj/words/test.wsj.words.gz")
            pf = tf.extractfile(
                "conll05st-release/test.wsj/props/test.wsj.props.gz")
            with gzip.GzipFile(fileobj=wf) as words_file, \
                    gzip.GzipFile(fileobj=pf) as props_file:
                sentences, labels, one_seg = [], [], []
                for word, label in zip(words_file, props_file):
                    word = word.strip().decode()
                    label = label.strip().decode().split()
                    if label:
                        sentences.append(word)
                        one_seg.append(label)
                        continue
                    # end of sentence: transpose prop columns
                    for i in range(len(one_seg[0]) if one_seg else 0):
                        labels.append([x[i] for x in one_seg])
                    if labels:
                        verb_list = [x for x in labels[0] if x != "-"]
                        for i, lbl in enumerate(labels[1:]):
                            self.sentences.append(list(sentences))
                            self.predicates.append(verb_list[i])
                            self.labels.append(self._to_bio(lbl))
                    sentences, labels, one_seg = [], [], []

    @staticmethod
    def _to_bio(lbl):
        cur_tag, in_bracket, seq = "O", False, []
        for l in lbl:
            if l == "*" and not in_bracket:
                seq.append("O")
            elif l == "*" and in_bracket:
                seq.append("I-" + cur_tag)
            elif l == "*)":
                seq.append("I-" + cur_tag)
                in_bracket = False
            elif "(" in l and ")" in l:
                cur_tag = l[1:l.find("*")]
                seq.append("B-" + cur_tag)
                in_bracket = False
            elif "(" in l:
                cur_tag = l[1:l.find("*")]
                seq.append("B-" + cur_tag)
                in_bracket = True
            else:
                raise RuntimeError(f"Unexpected label: {l}")
        return seq

    def get_dict(self):
        return self.word_dict, self.predicate_dict, self.label_dict

    def __getitem__(self, idx):
        sentence = self.sentences[idx]
        labels = self.labels[idx]
        sen_len = len(sentence)
        verb_index = labels.index("B-V")
        mark = [0] * len(labels)
        ctx = {}
        for off, name, pad in ((-2, "n2", "bos"), (-1, "n1", "bos"),
                               (0, "0", None), (1, "p1", "eos"),
                               (2, "p2", "eos")):
            j = verb_index + off
            if 0 <= j < len(labels):
                mark[j] = 1
                ctx[name] = sentence[j]
            else:
                ctx[name] = pad
        word_idx = [self.word_dict.get(w, _UNK_IDX) for w in sentence]
        outs = [np.array(word_idx)]
        for name in ("n2", "n1", "0", "p1", "p2"):
            outs.append(np.array(
                [self.word_dict.get(ctx[name], _UNK_IDX)] * sen_len))
        outs.append(np.array(
            [self.predicate_dict.get(self.predicates[idx])] * sen_len))
        outs.append(np.array(mark))
        outs.append(np.array([self.label_dict.get(w) for w in labels]))
        return tuple(outs)

    def __len__(self):
        return len(self.sentences)


class WMT14(Dataset):
    """WMT14 en-fr subset (reference: text/datasets/wmt14.py — tar with
    src.dict/trg.dict and {mode}/{mode} tab-separated pairs; samples are
    (src_ids, trg_ids, trg_ids_next))."""

    START = "<s>"
    END = "<e>"
    UNK = "<unk>"
    UNK_IDX = 2  # reference wmt14.py:37 — dicts start <s>,<e>,<unk>

    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 download=True):
        assert mode.lower() in ("train", "test", "gen")
        self.mode = mode.lower()
        if data_file is None:
            _no_download(type(self).__name__)
        self.data_file = data_file
        assert dict_size > 0, "dict_size should be set as positive number"
        self.dict_size = dict_size
        self._load_data()

    def _load_data(self):
        def to_dict(fd, size):
            out = {}
            for i, line in enumerate(fd):
                if i >= size:
                    break
                out[line.strip().decode()] = i
            return out

        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self.data_file) as f:
            names = [m.name for m in f if m.name.endswith("src.dict")]
            assert len(names) == 1
            self.src_dict = to_dict(f.extractfile(names[0]),
                                    self.dict_size)
            names = [m.name for m in f if m.name.endswith("trg.dict")]
            assert len(names) == 1
            self.trg_dict = to_dict(f.extractfile(names[0]),
                                    self.dict_size)
            file_name = f"{self.mode}/{self.mode}"
            names = [m.name for m in f if m.name.endswith(file_name)]
            for name in names:
                for line in f.extractfile(name):
                    parts = line.decode().strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src_words = parts[0].split()
                    src_ids = [self.src_dict.get(w, self.UNK_IDX)
                               for w in [self.START] + src_words +
                               [self.END]]
                    trg_words = parts[1].split()
                    trg_ids = [self.trg_dict.get(w, self.UNK_IDX)
                               for w in trg_words]
                    if len(src_ids) > 80 or len(trg_ids) > 80:
                        continue
                    self.src_ids.append(src_ids)
                    self.trg_ids_next.append(
                        trg_ids + [self.trg_dict[self.END]])
                    self.trg_ids.append(
                        [self.trg_dict[self.START]] + trg_ids)

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]),
                np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)


class WMT16(Dataset):
    """WMT16 en-de subset (reference: text/datasets/wmt16.py — tar with
    wmt16/{train,test,val} tab-separated pairs; dictionaries built from
    the train split on first use)."""

    START = "<s>"
    END = "<e>"
    UNK = "<unk>"

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", download=True):
        assert mode.lower() in ("train", "test", "val")
        self.mode = mode.lower()
        if data_file is None:
            _no_download(type(self).__name__)
        self.data_file = data_file
        self.lang = lang
        assert src_dict_size > 0 and trg_dict_size > 0
        self.src_dict_size = min(src_dict_size, 30000)
        self.trg_dict_size = min(trg_dict_size, 30000)
        self.src_dict = self._build_dict(self.src_dict_size, lang)
        self.trg_dict = self._build_dict(
            self.trg_dict_size, "de" if lang == "en" else "en")
        self._load_data()

    def _build_dict(self, dict_size, lang):
        word_freq = collections.defaultdict(int)
        src_col = 0 if self.lang == "en" else 1
        col = src_col if lang == self.lang else 1 - src_col
        with tarfile.open(self.data_file) as f:
            for line in f.extractfile("wmt16/train"):
                parts = line.decode().strip().split("\t")
                if len(parts) != 2:
                    continue
                for w in parts[col].split():
                    word_freq[w] += 1
        words = [self.START, self.END, self.UNK]
        for w, _ in sorted(word_freq.items(), key=lambda x: x[1],
                           reverse=True):
            if len(words) == dict_size:
                break
            words.append(w)
        return {w: i for i, w in enumerate(words)}

    def _load_data(self):
        start_id = self.src_dict[self.START]
        end_id = self.src_dict[self.END]
        unk_id = self.src_dict[self.UNK]
        src_col = 0 if self.lang == "en" else 1
        trg_col = 1 - src_col
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self.data_file) as f:
            for line in f.extractfile(f"wmt16/{self.mode}"):
                parts = line.decode().strip().split("\t")
                if len(parts) != 2:
                    continue
                src_ids = [start_id] + \
                    [self.src_dict.get(w, unk_id)
                     for w in parts[src_col].split()] + [end_id]
                trg_ids = [self.trg_dict.get(w, unk_id)
                           for w in parts[trg_col].split()]
                self.src_ids.append(src_ids)
                self.trg_ids_next.append(trg_ids + [end_id])
                self.trg_ids.append([start_id] + trg_ids)

    def get_dict(self, lang, reverse=False):
        d = self.src_dict if lang == self.lang else self.trg_dict
        return {v: k for k, v in d.items()} if reverse else dict(d)

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]),
                np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)
