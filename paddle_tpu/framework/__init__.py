"""Framework-level helpers (reference: python/paddle/framework/)."""
from .io import save, load  # noqa: F401
from ..core.random import seed, get_rng_state, set_rng_state  # noqa: F401
from ..core.dtype import set_default_dtype, get_default_dtype  # noqa: F401
