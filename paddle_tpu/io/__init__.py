"""Data pipeline.

TPU-native replacement for Paddle's DataLoader stack (reference:
python/paddle/fluid/reader.py:312 DataLoader, fluid/dataloader/ —
multiprocess shm workers + C++ blocking queue / buffered_reader double
buffering). Here the loader is a thread-pool prefetcher with an async
host→device staging stage: JAX device_put is non-blocking, so N prefetch
slots give the same overlap the reference gets from buffered_reader
without shared-memory plumbing (no CUDA-IPC analogue is needed on TPU).
"""
from __future__ import annotations

import itertools
import math
import queue
import threading

import numpy as np

from ..core.tensor import Tensor, to_tensor

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "ConcatDataset", "Subset", "random_split",
           "Sampler", "SequenceSampler", "RandomSampler",
           "WeightedRandomSampler", "BatchSampler",
           "DistributedBatchSampler", "DataLoader", "default_collate_fn",
           "get_worker_info"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        lens = {t.shape[0] for t in tensors}
        if len(lens) != 1:
            raise ValueError("tensors must share dim 0")
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, (list, tuple)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cum[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds = np.searchsorted(self.cum, idx, side="right")
        prev = 0 if ds == 0 else self.cum[ds - 1]
        return self.datasets[ds][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths must equal dataset size")
    from ..core import random as random_mod
    import jax
    key = (generator.next_key() if generator is not None
           else random_mod.next_key())
    perm = np.asarray(jax.random.permutation(key, len(dataset)))
    out, offset = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[offset:offset + n].tolist()))
        offset += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        from ..core import random as random_mod
        import jax
        n = len(self.data_source)
        key = (self.generator.next_key() if self.generator is not None
               else random_mod.next_key())
        if self.replacement:
            idx = np.asarray(jax.random.randint(
                key, (self.num_samples,), 0, n))
        else:
            idx = np.asarray(jax.random.permutation(key, n))[:self.num_samples]
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """reference: python/paddle/fluid/dataloader/batch_sampler.py."""

    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        if sampler is None:
            sampler = (RandomSampler(dataset) if shuffle
                       else SequenceSampler(dataset))
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.shuffle = shuffle

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards sample indices across data-parallel ranks (reference:
    fluid/dataloader/batch_sampler.py DistributedBatchSampler). On the TPU
    build "rank" is a position on the mesh's data axis."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import env as dist_env
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = (num_replicas if num_replicas is not None
                       else dist_env.get_world_size())
        self.local_rank = rank if rank is not None else dist_env.get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = list(range(n))
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices += indices[:(self.total_size - n)]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


class _WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


def _collate_with(batch, stack_tensors, stack_arrays, recurse):
    """Shared recursion of the two collates: leaf conversion differs
    (device tensors for the in-process path, host numpy in workers)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return stack_tensors(batch)
    if isinstance(sample, np.ndarray):
        return stack_arrays(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return stack_arrays(np.asarray(batch))
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: recurse([s[k] for s in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        return type(sample)(recurse(list(items))
                            for items in zip(*batch))
    return list(batch)


def default_collate_fn(batch):
    """Stack samples into batch arrays (reference:
    fluid/dataloader/collate.py default_collate_fn)."""
    import jax.numpy as jnp
    return _collate_with(
        batch,
        lambda b: to_tensor(jnp.stack([s._value for s in b])),
        to_tensor, default_collate_fn)


def default_convert_fn(batch):
    if isinstance(batch, (Tensor, np.ndarray)):
        return to_tensor(batch)
    if isinstance(batch, (list, tuple)):
        return type(batch)(default_convert_fn(b) for b in batch)
    return batch


def _np_collate(batch):
    """default_collate_fn producing host numpy (worker-process side —
    Tensor leaves don't reach workers: _dataset_is_fork_safe routes
    Tensor-yielding datasets to the thread pool)."""
    return _collate_with(
        batch,
        lambda b: np.stack([np.asarray(s._value) for s in b]),
        lambda a: a, _np_collate)


_SHM_MIN_BYTES = 1 << 16  # inline-pickle small arrays; shm the big ones


def _pack_payload(obj, use_shm, shm_names):
    """Structure -> picklable spec with ndarray leaves moved to POSIX
    shared memory (the TPU-side analogue of the reference's
    core.LoDTensor._share_memory worker protocol,
    fluid/dataloader/worker.py)."""
    if isinstance(obj, np.ndarray):
        if use_shm and obj.nbytes >= _SHM_MIN_BYTES:
            from multiprocessing import shared_memory
            shm = shared_memory.SharedMemory(create=True,
                                             size=obj.nbytes)
            np.ndarray(obj.shape, obj.dtype, buffer=shm.buf)[...] = obj
            name = shm.name
            shm.close()
            shm_names.append(name)
            return ("shm", name, obj.shape, obj.dtype.str)
        return ("raw", obj)
    if isinstance(obj, dict):
        return ("dict", {k: _pack_payload(v, use_shm, shm_names)
                         for k, v in obj.items()})
    if isinstance(obj, (list, tuple)):
        return ("seq", type(obj).__name__,
                [_pack_payload(v, use_shm, shm_names) for v in obj])
    return ("obj", obj)


def _unpack_payload(spec, to_device):
    tag = spec[0]
    if tag == "shm":
        from multiprocessing import shared_memory
        _, name, shape, dtype = spec
        shm = shared_memory.SharedMemory(name=name)
        try:
            arr = np.ndarray(shape, np.dtype(dtype),
                             buffer=shm.buf).copy()
        finally:
            shm.close()
            shm.unlink()
        return to_tensor(arr) if to_device else arr
    if tag == "raw":
        return to_tensor(spec[1]) if to_device else spec[1]
    if tag == "dict":
        return {k: _unpack_payload(v, to_device)
                for k, v in spec[1].items()}
    if tag == "seq":
        seq = [_unpack_payload(v, to_device) for v in spec[2]]
        return tuple(seq) if spec[1] == "tuple" else seq
    return spec[1]


def _mp_worker_main(dataset, collate_in_worker, index_q, result_q, wid,
                    num_workers, worker_init_fn, use_shm):
    """Worker-process loop: fetch indices, collate to numpy, ship via
    shared memory. Runs with inherited (forked) dataset state; never
    touches JAX (custom collate_fns run in the parent)."""
    _worker_info.info = _WorkerInfo(wid, num_workers, dataset)
    if worker_init_fn is not None:
        worker_init_fn(wid)
    while True:
        item = index_q.get()
        if item is None:
            return
        i, indices = item
        shm_names: list = []
        try:
            samples = [dataset[idx] for idx in indices]
            batch = _np_collate(samples) if collate_in_worker \
                else samples
            payload = _pack_payload(batch, use_shm, shm_names)
            result_q.put((i, payload, None))
        except Exception as e:  # exceptions must survive pickling
            for name in shm_names:
                try:
                    from multiprocessing import shared_memory
                    s = shared_memory.SharedMemory(name=name)
                    s.close()
                    s.unlink()
                except Exception:
                    pass
            result_q.put((i, None,
                          RuntimeError(f"DataLoader worker {wid}: "
                                       f"{type(e).__name__}: {e}")))


class DataLoader:
    """reference: python/paddle/fluid/reader.py:312. num_workers>0 runs
    map-style datasets in WORKER PROCESSES with shared-memory ndarray
    passing (fluid/dataloader/worker.py semantics) — Python-side decode/
    augment pipelines scale past the GIL; the parent stages batches onto
    the device. use_shared_memory=False (or iterable datasets) falls back
    to the thread pool, where device_put/compute release the GIL."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self._user_collate = collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 1)
        self.worker_init_fn = worker_init_fn
        self.use_shared_memory = use_shared_memory
        self.timeout = timeout
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)
                self.batch_size = batch_size

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset-backed loader has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _fetch(self, indices):
        samples = [self.dataset[i] for i in indices]
        return self.collate_fn(samples)

    def _iter_iterable(self):
        it = iter(self.dataset)
        while True:
            batch = list(itertools.islice(it, self.batch_size))
            if not batch:
                return
            if len(batch) < self.batch_size and getattr(self, "drop_last",
                                                        False):
                return
            yield self.collate_fn(batch)

    def __iter__(self):
        if self._iterable_mode:
            yield from self._iter_iterable()
            return
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield default_convert_fn(self.dataset[i])
            return
        if self.num_workers == 0:
            for indices in self.batch_sampler:
                yield self._fetch(indices)
            return
        if self.use_shared_memory and self._mp_supported():
            yield from self._iter_multiprocess()
            return
        yield from self._iter_threaded()

    @staticmethod
    def _mp_supported():
        import multiprocessing as mp
        return "fork" in mp.get_all_start_methods()

    def _dataset_is_fork_safe(self):
        """Samples must be JAX-free: a forked child touching the
        inherited PJRT client (jax.Array indexing / device fetch) can
        deadlock. Probe one sample in the parent (ONCE — cached across
        epochs); Tensor leaves route the loader to the thread pool."""
        cached = getattr(self, "_fork_safe", None)
        if cached is not None:
            return cached
        try:
            sample = self.dataset[0]
        except Exception:
            self._fork_safe = True
            return True  # let the worker surface the real error

        def has_tensor(obj):
            if isinstance(obj, Tensor):
                return True
            if isinstance(obj, dict):
                return any(has_tensor(v) for v in obj.values())
            if isinstance(obj, (list, tuple)):
                return any(has_tensor(v) for v in obj)
            return False

        self._fork_safe = not has_tensor(sample)
        return self._fork_safe

    def _iter_multiprocess(self):
        """Process-pool path: fork workers (dataset state inherited),
        indices out over a queue, batches back via shared memory, emitted
        in order with a bounded in-flight window."""
        import multiprocessing as mp
        if not self._dataset_is_fork_safe():
            yield from self._iter_threaded()
            return
        ctx = mp.get_context("fork")
        batches = list(self.batch_sampler)
        n_batches = len(batches)
        if n_batches == 0:
            return
        nw = min(self.num_workers, n_batches)
        index_q = ctx.Queue()
        result_q = ctx.Queue()
        procs = [
            ctx.Process(
                target=_mp_worker_main,
                args=(self.dataset, self._user_collate is None, index_q,
                      result_q, wid, nw, self.worker_init_fn,
                      True),
                daemon=True)
            for wid in range(nw)]
        for p in procs:
            p.start()
        max_ahead = nw * self.prefetch_factor
        dispatched = 0
        try:
            while dispatched < min(max_ahead, n_batches):
                index_q.put((dispatched, batches[dispatched]))
                dispatched += 1
            pending: dict[int, tuple] = {}
            import queue as _queue
            deadline = None
            for i in range(n_batches):
                while i not in pending:
                    # poll so a dead worker (OOM-kill, native segfault)
                    # raises instead of hanging the parent forever
                    try:
                        j, payload, err = result_q.get(timeout=2.0)
                    except _queue.Empty:
                        dead = [w for w, p in enumerate(procs)
                                if not p.is_alive()
                                and p.exitcode not in (0, None)]
                        if dead:
                            raise RuntimeError(
                                f"DataLoader worker(s) {dead} exited "
                                f"abnormally (exitcodes "
                                f"{[procs[w].exitcode for w in dead]})")
                        if self.timeout:
                            import time as _time
                            if deadline is None:
                                deadline = _time.monotonic() + \
                                    self.timeout
                            elif _time.monotonic() > deadline:
                                raise RuntimeError(
                                    f"DataLoader timed out after "
                                    f"{self.timeout}s waiting for "
                                    f"batch {i}")
                        continue
                    deadline = None
                    pending[j] = (payload, err)
                payload, err = pending.pop(i)
                if dispatched < n_batches:
                    index_q.put((dispatched, batches[dispatched]))
                    dispatched += 1
                if err is not None:
                    raise err
                if self._user_collate is None:
                    yield _unpack_payload(payload, to_device=True)
                else:
                    samples = _unpack_payload(payload, to_device=False)
                    yield self.collate_fn(samples)
        finally:
            for _ in procs:
                index_q.put(None)
            for p in procs:
                p.join(timeout=2.0)
                if p.is_alive():
                    p.terminate()
            # drain any landed-but-unconsumed shm segments: both the
            # reorder buffer and anything still queued
            for payload, _err in pending.values():
                if payload is not None:
                    try:
                        _unpack_payload(payload, to_device=False)
                    except Exception:
                        pass
            pending.clear()
            try:
                while True:
                    _, payload, err = result_q.get_nowait()
                    if payload is not None:
                        _unpack_payload(payload, to_device=False)
            except Exception:
                pass

    def _iter_threaded(self):
        work_q: queue.Queue = queue.Queue()
        done_marker = object()
        batches = list(self.batch_sampler)
        results: dict[int, object] = {}
        results_lock = threading.Condition()
        stop = threading.Event()
        n_batches = len(batches)
        for item in enumerate(batches):
            work_q.put(item)
        for _ in range(self.num_workers):
            work_q.put(done_marker)
        max_ahead = self.num_workers * self.prefetch_factor
        next_emit = [0]

        def worker(wid):
            _worker_info.info = _WorkerInfo(wid, self.num_workers,
                                            self.dataset)
            if self.worker_init_fn is not None:
                self.worker_init_fn(wid)
            while not stop.is_set():
                item = work_q.get()
                if item is done_marker:
                    return
                i, indices = item
                with results_lock:
                    while (i - next_emit[0] >= max_ahead
                           and not stop.is_set()):
                        results_lock.wait(timeout=1.0)
                if stop.is_set():
                    return
                try:
                    out = self._fetch(indices)
                except Exception as e:  # propagate to consumer
                    out = e
                with results_lock:
                    results[i] = out
                    results_lock.notify_all()

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()
        try:
            for i in range(n_batches):
                with results_lock:
                    while i not in results:
                        results_lock.wait()
                    out = results.pop(i)
                    next_emit[0] = i + 1
                    results_lock.notify_all()
                if isinstance(out, Exception):
                    raise out
                yield out
        finally:
            # consumer finished or bailed early: release parked workers so
            # no threads (or their queued batches) outlive this iterator
            stop.set()
            with results_lock:
                results_lock.notify_all()
            for t in threads:
                t.join(timeout=2.0)
            results.clear()
