"""Request/response surface of the online serving engine.

A `Request` is one user generation job moving through the continuous-
batching lifecycle:

    QUEUED -> PREFILL -> DECODE -> FINISHED | CANCELLED
                 ^          |
                 +- PREEMPTED (overload: banked + swapped to host,
                    re-queued; resumes via swap-in)

PREFILL now spans MULTIPLE engine steps for long prompts: the engine
feeds the prompt through one fixed-shape chunk program per step
(chunked prefill), interleaved with the residents' decode steps, and
flips the request to DECODE after the final chunk. Admission also
allocates the request's KV pages (`pages`) from the shared paged pool;
they return to the pool when the request retires. States advance only
at step boundaries of the engine (between compiled program
invocations), never inside one, so the compiled prefill/decode
programs themselves stay fixed-shape. Per-request sampling knobs live in
`SamplingParams`; the engine vectorizes them across slots (one value per
slot row) and evaluates them on device, reusing the same nucleus filter
(`nlp.generation._top_p_filter`) as the offline CompiledGenerator.
"""
from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable, List, Optional

import numpy as np

__all__ = ["RequestState", "SamplingParams", "Request", "RequestOutput"]


class RequestState(Enum):
    QUEUED = 0
    PREFILL = 1
    DECODE = 2
    FINISHED = 3
    CANCELLED = 4
    # preempted under overload: its emitted tokens are banked, its KV
    # pages swapped to the host tier, and it waits in the queue to
    # resume (swap-in restores pos; the stream continues untouched)
    PREEMPTED = 5


@dataclass
class SamplingParams:
    """Per-request decode knobs (the serving form of the generate()
    kwargs). greedy=True (default) is argmax decoding — bit-identical
    to CompiledGenerator's greedy path; setting any of top_k/top_p or
    greedy=False samples on device with this request's own
    temperature/top-k/top-p while slot neighbors keep theirs."""

    max_new_tokens: int = 16
    temperature: float = 1.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    greedy: bool = True
    eos_token_id: Optional[int] = None
    timeout_s: Optional[float] = None
    # overload scheduling (lower value = more important, 0 default):
    # the queue orders by (priority, deadline, arrival) and a blocked
    # higher-priority request may PREEMPT the lowest-priority resident
    priority: int = 0
    # placement deadline in seconds from arrival: if it expires while
    # the request is still QUEUED it fails fast as "deadline" (HTTP
    # 504) instead of burning a queue slot. Runtime limits stay
    # timeout_s's job — a started request is never deadline-failed.
    deadline_s: Optional[float] = None
    # multi-tenant LoRA serving (serving/adapters.py): which
    # registered adapter this request decodes under; 0 = the base
    # model. Riding on the sampling params keeps tenant identity
    # attached through migration (the Ticket re-places the same
    # sampling) and preemption-resume for free.
    adapter_id: int = 0
    # grammar-constrained decoding (serving/grammar.py): the
    # declarative constraint this request's output must satisfy; the
    # engine materializes a per-request automaton at admission.
    # Requires eos_token_id (EOS is how a structurally complete
    # stream terminates) and an engine built with the grammar gate on.
    grammar: Optional[object] = None
    # mid-stream migration support: when the router re-places a
    # constrained request, the banked emitted tokens become the tail
    # of the new prompt — this counts how many trailing PROMPT tokens
    # are grammar-governed output the automaton must replay before
    # resuming. 0 for every request that never migrated.
    grammar_prefix: int = 0
    # embeddings/scoring lane: prefill-only — the request runs its
    # prompt through chunked prefill exactly like a generation
    # request (same paging, same token-budget packing), then retires
    # at cursor end returning the pooled last-hidden-state instead of
    # decoding. max_new_tokens/eos/etc are ignored.
    embed: bool = False
    # session pinning: a stable conversation id. On normal retirement
    # the request's radix-inserted prefix pages are PINNED for the
    # engine's session TTL (a tier between "resident" and
    # "evictable"), so the session's next turn hits warm KV by
    # contract, not by LRU luck.
    session: Optional[str] = None

    def __post_init__(self):
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.adapter_id < 0:
            raise ValueError("adapter_id must be >= 0")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError("deadline_s must be > 0")
        if self.top_k is not None and self.top_k < 1:
            raise ValueError("top_k must be >= 1")
        if self.top_p is not None and not (0.0 < self.top_p <= 1.0):
            raise ValueError("top_p must be in (0, 1]")
        if self.top_k is not None or self.top_p is not None:
            self.greedy = False
        if self.grammar_prefix < 0:
            raise ValueError("grammar_prefix must be >= 0")
        if self.grammar is not None:
            if self.embed:
                raise ValueError(
                    "grammar and embed are mutually exclusive")
            if self.eos_token_id is None:
                raise ValueError(
                    "grammar requires eos_token_id — EOS is the only "
                    "way a structurally complete stream terminates")


_FINISH_SENTINEL = object()


class Request:
    """One queued/running generation job. Created by
    ServingEngine.add_request; user-facing handles are the incremental
    token stream (`on_token` callback or the blocking `stream()`
    iterator) and the final `RequestOutput`."""

    def __init__(self, request_id: str, prompt_ids, sampling: SamplingParams,
                 on_token: Optional[Callable] = None, arrival_t: float = None):
        self.request_id = request_id
        self.prompt_ids = np.asarray(prompt_ids).reshape(-1)
        if self.prompt_ids.size == 0:
            raise ValueError("empty prompt")
        self.sampling = sampling
        self.on_token = on_token
        self.state = RequestState.QUEUED
        self.output_tokens: List[int] = []
        # stop|length|cancelled|timeout|deadline|replica_failure|
        # poisoned|aborted
        self.finish_reason: Optional[str] = None
        # typed terminal error, when the finish reason carries one
        # (today: PoisonedRequest attached by the engine's quarantine)
        self.error: Optional[BaseException] = None
        self.slot: Optional[int] = None
        # KV pages granted at admission (paged pool); None while queued
        self.pages: Optional[List[int]] = None
        # prefix-cache hit: how many prompt tokens were served from
        # shared cached pages (0 = cold miss or cache off); the grant
        # handle lives here between reserve and retirement
        self.cached_tokens: int = 0
        self._prefix_grant = None
        # speculative decoding: emitted tokens that arrived as
        # VERIFIED drafts (each one skipped a full decode step; 0 with
        # speculation off) — usage.accepted_draft_tokens over HTTP
        self.accepted_draft_tokens: int = 0
        # overload preemption: how many times this request was
        # preempted (banked + swapped to host + resumed) on this
        # engine — usage.preemptions over HTTP
        self.preemptions: int = 0
        # multi-tenant adapter claim (engine-owned): the (pool page,
        # LoRA scale) binding granted at reserve time, and whether
        # the request currently holds a reference on its adapter's
        # pool page (released at retirement/preemption)
        self._adapter_binding = (0, 0.0)
        self._adapter_held = False
        # embeddings lane: the pooled last-hidden-state (float32
        # [hidden]) set when an embed=True request retires at cursor
        # end; None for generation requests
        self.embedding: Optional[np.ndarray] = None
        # preemption swap handle (engine-owned): host-tier slots +
        # coverage of the banked KV while the request waits to resume;
        # None whenever the request is not preempted-with-swapped-KV
        self._swap = None
        # committed token sequence frozen at the last preemption
        # (prompt + every emitted token): the resume prefill source —
        # None until first preempted
        self._resume_ids = None
        # timeline (engine clock): arrival -> admitted (slot granted,
        # prefill) -> first token -> finished
        self.arrival_t = time.monotonic() if arrival_t is None else arrival_t
        self.admitted_t: Optional[float] = None
        self.first_token_t: Optional[float] = None
        self.finished_t: Optional[float] = None
        self._last_token_t: Optional[float] = None
        self._stream_q: "queue.Queue" = queue.Queue()
        self._done = threading.Event()

    # -- engine-side transitions ------------------------------------------
    def _emit(self, token: int, now: float):
        self.output_tokens.append(token)
        if self.first_token_t is None:
            self.first_token_t = now
        self._last_token_t = now
        self._stream_q.put(token)
        if self.on_token is not None:
            self.on_token(self, token)

    def _finish(self, reason: str, now: float):
        self.finish_reason = reason
        self.finished_t = now
        self.state = (RequestState.CANCELLED if reason == "cancelled"
                      else RequestState.FINISHED)
        self._stream_q.put(_FINISH_SENTINEL)
        self._done.set()

    @property
    def deadline(self) -> Optional[float]:
        if self.sampling.timeout_s is None:
            return None
        return self.arrival_t + self.sampling.timeout_s

    @property
    def place_deadline(self) -> Optional[float]:
        """Absolute time by which the request must have been ADMITTED
        (deadline_s from arrival); None = no placement deadline."""
        if self.sampling.deadline_s is None:
            return None
        return self.arrival_t + self.sampling.deadline_s

    @property
    def prefill_ids(self) -> np.ndarray:
        """The token sequence the engine prefills for this request:
        the original prompt, or — after a preemption — the committed
        sequence frozen at preempt time (prompt + banked emitted
        tokens), so the resume re-prefill regenerates exactly the
        state the preempted slot held."""
        return (self._resume_ids if self._resume_ids is not None
                else self.prompt_ids)

    # -- user-facing ------------------------------------------------------
    @property
    def finished(self) -> bool:
        return self.state in (RequestState.FINISHED,
                              RequestState.CANCELLED)

    def stream(self):
        """Blocking token iterator — use when the engine is pumped from
        another thread (engine.run()); yields tokens as they decode."""
        while True:
            tok = self._stream_q.get()
            if tok is _FINISH_SENTINEL:
                return
            yield tok

    def next_event(self, timeout: Optional[float] = None):
        """Poll-able stream read for front-ends that must interleave
        token delivery with liveness checks (SSE writers probing for
        client disconnect): returns ("token", id), ("finish", reason),
        or ("idle", None) when `timeout` elapses with nothing queued."""
        try:
            tok = self._stream_q.get(timeout=timeout)
        except queue.Empty:
            return ("idle", None)
        if tok is _FINISH_SENTINEL:
            return ("finish", self.finish_reason)
        return ("token", tok)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def output(self) -> "RequestOutput":
        return RequestOutput(
            request_id=self.request_id,
            prompt_token_ids=self.prompt_ids.tolist(),
            token_ids=list(self.output_tokens),
            finish_reason=self.finish_reason,
            cached_tokens=self.cached_tokens,
            accepted_draft_tokens=self.accepted_draft_tokens,
            preemptions=self.preemptions,
            embedding=(None if self.embedding is None
                       else np.asarray(self.embedding)),
            ttft_s=(None if self.first_token_t is None
                    else self.first_token_t - self.arrival_t),
            queue_wait_s=(None if self.admitted_t is None
                          else self.admitted_t - self.arrival_t),
            e2e_s=(None if self.finished_t is None
                   else self.finished_t - self.arrival_t))

    def __repr__(self):
        return (f"Request({self.request_id!r}, state={self.state.name}, "
                f"prompt_len={self.prompt_ids.size}, "
                f"generated={len(self.output_tokens)})")


@dataclass
class RequestOutput:
    """Final result handed back when a request leaves the engine."""

    request_id: str
    prompt_token_ids: List[int]
    token_ids: List[int]
    finish_reason: Optional[str]
    # prompt tokens served from the prefix cache (OpenAI-style
    # usage.cached_tokens in the HTTP layer)
    cached_tokens: int = 0
    # emitted tokens that arrived as VERIFIED speculative drafts
    # (usage.accepted_draft_tokens over HTTP; 0 with speculation off)
    accepted_draft_tokens: int = 0
    # how many times this request was MIGRATED mid-stream to another
    # replica after its host died (usage.migrations over HTTP); only
    # the router's merged Ticket view sets it nonzero
    migrations: int = 0
    # how many times this request was PREEMPTED under overload (banked
    # + swapped to the host tier + resumed, token-identically) —
    # usage.preemptions over HTTP
    preemptions: int = 0
    ttft_s: Optional[float] = None
    queue_wait_s: Optional[float] = None
    e2e_s: Optional[float] = None
    metrics: dict = field(default_factory=dict)
    # embeddings lane: pooled last-hidden-state for embed=True
    # requests (float32 [hidden]); None for generation requests
    embedding: Optional[np.ndarray] = None
