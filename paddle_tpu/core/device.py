"""Device / Place abstraction.

TPU-native replacement for ``phi::Place`` / ``platform::DeviceContextPool``
(reference: paddle/fluid/platform/device_context.h:351,
paddle/phi/common/place.h). Devices are JAX devices; there are no streams to
manage — XLA/PJRT executes asynchronously and dependencies are tracked by
the runtime, so Paddle's stream/event machinery collapses away.

Place strings accepted: "cpu", "tpu", "tpu:0", "gpu"/"gpu:0" (alias of the
accelerator if present), "xla:0".
"""
from __future__ import annotations

import jax

__all__ = [
    "Place", "CPUPlace", "TPUPlace", "XLAPlace", "CUDAPlace", "CUDAPinnedPlace",
    "set_device", "get_device", "get_all_devices", "device_count",
    "is_compiled_with_cuda", "is_compiled_with_rocm", "is_compiled_with_xpu",
    "is_compiled_with_npu", "is_compiled_with_mlu", "is_compiled_with_ipu",
    "is_compiled_with_cinn", "is_compiled_with_distribute", "jax_device",
]


class Place:
    """A device identified by (kind, index). Maps onto one jax.Device."""

    __slots__ = ("kind", "index")

    def __init__(self, kind: str, index: int = 0):
        self.kind = kind
        self.index = index

    def __repr__(self):
        return f"Place({self.kind}:{self.index})"

    def __eq__(self, other):
        return (isinstance(other, Place)
                and self.kind == other.kind and self.index == other.index)

    def __hash__(self):
        return hash((self.kind, self.index))

    def is_cpu_place(self):
        return self.kind == "cpu"

    def is_tpu_place(self):
        return self.kind in ("tpu", "xla")

    # Paddle compat aliases
    def is_gpu_place(self):
        return self.kind in ("tpu", "xla", "gpu")

    def get_device_id(self):
        return self.index

    def jax_device(self) -> jax.Device:
        # LOCAL devices only: in a multi-process job jax.devices() spans
        # all hosts and indexing it would hand back a non-addressable
        # device (rank N putting its batch on rank 0's chip)
        if self.kind == "cpu":
            cpus = [d for d in jax.local_devices() if d.platform == "cpu"]
            if not cpus:
                cpus = jax.local_devices(backend="cpu")
            return cpus[0]
        accel = _accelerator_devices()
        if not accel:
            cpus = [d for d in jax.local_devices() if d.platform == "cpu"]
            return cpus[self.index % len(cpus)]
        return accel[self.index % len(accel)]


def CPUPlace():
    return Place("cpu", 0)


def TPUPlace(index: int = 0):
    return Place("tpu", index)


def XLAPlace(index: int = 0):
    return Place("xla", index)


def CUDAPlace(index: int = 0):
    # Paddle-compat alias: "gpu" means "the accelerator" here.
    return Place("tpu", index)


def CUDAPinnedPlace():
    return Place("cpu", 0)


def _accelerator_devices():
    devs = jax.local_devices()
    if devs and devs[0].platform != "cpu":
        return devs
    return []


_current_place: Place | None = None


def _default_place() -> Place:
    return Place("tpu", 0) if _accelerator_devices() else Place("cpu", 0)


def set_device(device) -> Place:
    """paddle.device.set_device parity (python/paddle/device/__init__.py)."""
    global _current_place
    _current_place = _parse(device)
    return _current_place


def get_device() -> str:
    p = _current_place or _default_place()
    return f"{p.kind}:{p.index}"


def current_place() -> Place:
    return _current_place or _default_place()


def _parse(device) -> Place:
    if isinstance(device, Place):
        return device
    if isinstance(device, jax.Device):
        kind = "cpu" if device.platform == "cpu" else "tpu"
        return Place(kind, device.id)
    s = str(device).lower()
    if ":" in s:
        kind, idx = s.split(":", 1)
        idx = int(idx)
    else:
        kind, idx = s, 0
    if kind in ("gpu", "cuda", "xla", "tpu"):
        kind = "tpu" if _accelerator_devices() else "cpu"
        return Place(kind, idx)
    if kind == "cpu":
        return Place("cpu", idx)
    raise ValueError(f"Unknown device {device!r}")


def jax_device(place=None) -> jax.Device:
    if place is None:
        return current_place().jax_device()
    return _parse(place).jax_device()


def get_all_devices():
    return [f"{'cpu' if d.platform == 'cpu' else 'tpu'}:{d.id}" for d in jax.devices()]


def device_count() -> int:
    return len(jax.devices())


# Capability probes (Paddle compat; this build is WITH_GPU=OFF by design).
def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_mlu():
    return False


def is_compiled_with_ipu():
    return False


def is_compiled_with_cinn():
    return False


def is_compiled_with_distribute():
    return True
