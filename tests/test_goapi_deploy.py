"""Go bindings over the C inference ABI (reference: inference/goapi/).

save -> load -> run parity, mirroring tests/test_capi_deploy.py: a Go
program (deploy/goapi/demo) consumes the saved model through cgo +
libpaddle_tpu_c.so and must print the same outputs the in-process
Python predictor computes. Skips when no Go toolchain is installed.
"""
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOAPI = os.path.join(REPO, "paddle_tpu", "deploy", "goapi")


@pytest.mark.skipif(shutil.which("go") is None, reason="no go toolchain")
def test_go_program_runs_saved_model(tmp_path):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import jit
    from paddle_tpu.jit.api import InputSpec

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 3))
    model.eval()
    prefix = str(tmp_path / "toy")
    jit.save(model, prefix,
             input_spec=[InputSpec([2, 4], "float32", "x")])

    x = (0.25 * np.arange(8, dtype=np.float32) - 1.0).reshape(2, 4)
    import paddle_tpu.inference as inf
    want = inf.create_predictor(inf.Config(prefix)).run([x])[0]

    from paddle_tpu import deploy
    so = deploy.build_capi(out_dir=str(tmp_path))
    so_dir = os.path.dirname(so)
    # cgo expects lib<name>.so for -lpaddle_tpu_c
    libname = os.path.join(so_dir, "libpaddle_tpu_c.so")
    if not os.path.exists(libname):
        shutil.copy(so, libname)

    env = dict(os.environ)
    env["CGO_ENABLED"] = "1"
    env["CGO_CFLAGS"] = f"-I{os.path.dirname(deploy.capi_header_path())}"
    env["CGO_LDFLAGS"] = (f"-L{so_dir} -lpaddle_tpu_c "
                          f"-Wl,-rpath,{so_dir}")
    exe = str(tmp_path / "go_demo")
    build = subprocess.run(
        ["go", "build", "-o", exe, "./demo"], cwd=GOAPI, env=env,
        capture_output=True, text=True, timeout=600)
    assert build.returncode == 0, build.stderr[-2000:]

    env["PADDLE_TPU_FORCE_CPU_DEVICES"] = "1"
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + [p for p in sys.path if p and os.path.isdir(p)])
    proc = subprocess.run([exe, prefix], env=env, capture_output=True,
                          text=True, timeout=300)
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    out_lines = dict(l.split("=", 1) for l in
                     proc.stdout.strip().splitlines() if "=" in l)
    assert out_lines["inputs"].startswith("1 ")
    assert out_lines["out_shape"] == "2x3"
    got = np.array([float(v) for v in out_lines["out"].split()],
                   np.float32).reshape(2, 3)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
