"""paddle.summary / paddle.flops (reference: hapi/model_summary.py,
hapi/dynamic_flops.py).

flops() is TPU-native: instead of per-layer hook arithmetic, the model
forward is lowered through XLA and the compiler's own cost model is
read back — the number the hardware will actually run.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["summary", "flops"]


def _example_inputs(input_size, dtypes=None):
    import jax.numpy as jnp
    if isinstance(input_size, tuple) and input_size and \
            isinstance(input_size[0], (tuple, list)):
        sizes = list(input_size)
    else:
        sizes = [input_size]
    dtypes = dtypes or ["float32"] * len(sizes)
    outs = []
    for shape, dt in zip(sizes, dtypes):
        shape = [1 if (d is None or d == -1) else int(d) for d in shape]
        outs.append(Tensor(jnp.zeros(shape, np.dtype(dt))))
    return outs


def summary(net, input_size=None, dtypes=None, input=None):
    """Layer-wise summary table (reference: hapi/model_summary.py
    summary). Returns {'total_params', 'trainable_params'}."""
    rows = []
    hooks = []

    def make_hook(name, layer):
        def hook(lyr, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (list, tuple)) \
                else outputs
            shape = list(out.shape) if hasattr(out, "shape") else "?"
            n = sum(int(np.prod(p.shape)) for p in lyr.parameters(
                include_sublayers=False)) if hasattr(
                    lyr, "parameters") else 0
            rows.append((name, type(lyr).__name__, shape, n))
        return hook

    for name, layer in net.named_sublayers():
        try:
            hooks.append(layer.register_forward_post_hook(
                make_hook(name, layer)))
        except Exception:
            pass
    try:
        if input is not None:
            net(*(input if isinstance(input, (list, tuple))
                  else [input]))
        elif input_size is not None:
            net(*_example_inputs(input_size, dtypes))
    finally:
        for h in hooks:
            try:
                h.remove()
            except Exception:
                pass

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)
    lines = [f"{'Layer (type)':<38}{'Output Shape':<24}{'Param #':>12}",
             "=" * 74]
    for name, typ, shape, n in rows:
        lines.append(f"{name + ' (' + typ + ')':<38}"
                     f"{str(shape):<24}{n:>12,}")
    lines += ["=" * 74,
              f"Total params: {total:,}",
              f"Trainable params: {trainable:,}",
              f"Non-trainable params: {total - trainable:,}"]
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}


def flops(net, input_size=None, inputs=None, custom_ops=None,
          print_detail=False):
    """Forward FLOPs from XLA's cost model (reference:
    hapi/dynamic_flops.py flops — hook-based estimates there; the
    compiler's own count here)."""
    import jax
    ins = (inputs if inputs is not None
           else _example_inputs(input_size))
    ins = ins if isinstance(ins, (list, tuple)) else [ins]
    params = [p for p in net.parameters()]
    vals = [p._value for p in params]

    def pure(pvals, *xs):
        originals = [p._value for p in params]
        try:
            for p, v in zip(params, pvals):
                p._value = v
            out = net(*[Tensor(x) for x in xs])
            out = out[0] if isinstance(out, (list, tuple)) else out
            return out._value
        finally:
            for p, v in zip(params, originals):
                p._value = v

    was_training = getattr(net, "training", False)
    net.eval()
    try:
        compiled = jax.jit(pure).lower(
            vals, *[t._value for t in ins]).compile()
    finally:
        if was_training:
            net.train()
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, list) else (ca or {})
    total = int(ca.get("flops", 0))
    if print_detail:
        print(f"FLOPs (XLA cost model, forward): {total:,}")
        print(f"bytes accessed: {int(ca.get('bytes accessed', 0)):,}")
    return total
