"""In-program collectives over named mesh axes.

TPU-native replacement for the static collective op zoo (reference:
paddle/fluid/operators/collective/ — c_allreduce_*, c_allgather,
c_reducescatter, global_scatter/global_gather, partial_send/recv; 160
files, 15.1k LoC). Each function here is a thin alias of the XLA
collective HLO it lowers to; used inside shard_map / pjit programs where
GSPMD doesn't already infer the collective. Channel management, comm
streams, and sync ops (c_sync_calc_stream…) have no equivalent — XLA
schedules collectives on ICI itself.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["psum", "pmean", "pmax", "pmin", "ppermute", "all_gather",
           "all_to_all", "reduce_scatter", "axis_index", "axis_size",
           "roll_along_axis"]

psum = jax.lax.psum
pmean = jax.lax.pmean
pmax = jax.lax.pmax
pmin = jax.lax.pmin
ppermute = jax.lax.ppermute
axis_index = jax.lax.axis_index


def axis_size(axis_name):
    return jax.lax.axis_size(axis_name) if hasattr(jax.lax, "axis_size") \
        else jax.lax.psum(1, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def all_to_all(x, axis_name, split_axis, concat_axis, tiled=True):
    return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_dimension=0, tiled=True):
    return jax.lax.psum_scatter(x, axis_name,
                                scatter_dimension=scatter_dimension,
                                tiled=tiled)


def roll_along_axis(x, axis_name, shift=1):
    """Ring shift: device i sends to device (i+shift) % n — the building
    block of ring attention and pipeline p2p."""
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)
