"""nn.quant: weight-only int8/int4 streaming + llm.int8 matmul.

Reference analogue: the int8 inference stack
(fused_multi_transformer_int8_op.cu / attn_gemm_int8.h). Checks
quantize->dequantize round-trips, weight_only_linear parity with the
dequantized matmul, the int8 dot_general path, layer swapping, and the
quantized GPT decode path end-to-end (compiled generator).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.nn import quant


def _randw(rs, i, o):
    return (rs.randn(i, o) * 0.1).astype(np.float32)


class TestWeightQuantize:
    def test_int8_roundtrip(self):
        rs = np.random.RandomState(0)
        w = _randw(rs, 64, 32)
        q, s = quant.weight_quantize(w, algo="weight_only_int8")
        assert str(q.dtype).endswith("int8") and q.shape == [64, 32]
        assert s.shape == [32]
        wd = quant.weight_dequantize(q, s).numpy()
        # absmax int8: max error is scale/2 = absmax/254 per channel
        err = np.abs(wd - w).max(axis=0)
        bound = np.abs(w).max(axis=0) / 127.0
        assert (err <= bound + 1e-7).all()

    def test_int4_roundtrip_packed(self):
        rs = np.random.RandomState(1)
        w = _randw(rs, 64, 16)
        q, s = quant.weight_quantize(w, algo="weight_only_int4")
        assert q.shape == [32, 16], "two nibbles per byte"
        wd = quant.weight_dequantize(
            q, s, algo="weight_only_int4", in_features=64).numpy()
        bound = np.abs(w).max(axis=0) / 7.0
        assert (np.abs(wd - w).max(axis=0) <= bound + 1e-7).all()

    def test_int4_group_scales(self):
        rs = np.random.RandomState(2)
        w = _randw(rs, 64, 8)
        q, s = quant.weight_quantize(w, algo="weight_only_int4",
                                     group_size=16)
        assert s.shape == [4, 8]
        wd = quant.weight_dequantize(
            q, s, algo="weight_only_int4", in_features=64,
            group_size=16).numpy()
        wg = w.reshape(4, 16, 8)
        bound = np.abs(wg).max(axis=1) / 7.0   # per-group bound
        err = np.abs(wd.reshape(4, 16, 8) - wg).max(axis=1)
        assert (err <= bound + 1e-7).all()

    def test_bad_algo_raises(self):
        with pytest.raises(ValueError):
            quant.weight_quantize(np.ones((4, 4), np.float32),
                                  algo="int3")


class TestWeightOnlyLinear:
    def test_int8_matches_dequant_matmul(self):
        rs = np.random.RandomState(3)
        w = _randw(rs, 32, 24)
        x = rs.randn(4, 32).astype(np.float32)
        q, s = quant.weight_quantize(w, algo="weight_only_int8")
        got = quant.weight_only_linear(paddle.to_tensor(x), q,
                                       weight_scale=s).numpy()
        want = x @ quant.weight_dequantize(q, s).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_int4_group_matches(self):
        rs = np.random.RandomState(4)
        w = _randw(rs, 32, 24)
        x = rs.randn(4, 32).astype(np.float32)
        q, s = quant.weight_quantize(w, algo="weight_only_int4",
                                     group_size=8)
        got = quant.weight_only_linear(
            paddle.to_tensor(x), q, weight_scale=s, weight_dtype="int4",
            in_features=32, group_size=8).numpy()
        want = x @ quant.weight_dequantize(
            q, s, algo="weight_only_int4", in_features=32,
            group_size=8).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_activation_grad_flows(self):
        rs = np.random.RandomState(5)
        w = _randw(rs, 16, 8)
        q, s = quant.weight_quantize(w)
        x = paddle.to_tensor(rs.randn(2, 16).astype(np.float32),
                             stop_gradient=False)
        y = quant.weight_only_linear(x, q, weight_scale=s)
        y.sum().backward()
        wd = quant.weight_dequantize(q, s).numpy()
        np.testing.assert_allclose(x.grad.numpy(),
                                   np.tile(wd.sum(1), (2, 1)),
                                   rtol=1e-4, atol=1e-5)

    def test_llm_int8_close_to_float(self):
        rs = np.random.RandomState(6)
        w = _randw(rs, 64, 32)
        x = rs.randn(8, 64).astype(np.float32)
        q, s = quant.weight_quantize(w)
        got = quant.llm_int8_linear(paddle.to_tensor(x), q,
                                    weight_scale=s).numpy()
        want = x @ w
        # two int8 quantizations (weights + per-token activations)
        assert np.abs(got - want).max() < 0.05 * np.abs(want).max() + 0.05

    def test_layer_swap(self):
        rs = np.random.RandomState(7)
        lin = nn.Linear(16, 8)
        lin.weight.set_value(paddle.to_tensor(_randw(rs, 16, 8)))
        model = nn.Sequential(lin, nn.ReLU(), nn.Linear(8, 4))
        n = quant.quantize_for_decode(model, algo="weight_only_int8")
        assert n == 2
        assert isinstance(model[0], quant.WeightOnlyLinear)
        x = rs.randn(2, 16).astype(np.float32)
        y = model(paddle.to_tensor(x)).numpy()
        assert np.isfinite(y).all()


class TestQuantizedGPTDecode:
    def _model(self):
        from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
        cfg = GPTConfig(vocab_size=512, hidden_size=64,
                        num_hidden_layers=2, num_attention_heads=4,
                        max_position_embeddings=128,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
        paddle.seed(11)
        return GPTForCausalLM(cfg), cfg

    def test_quantized_logits_close_and_generate(self):
        model, cfg = self._model()
        model.eval()
        rs = np.random.RandomState(0)
        ids = paddle.to_tensor(rs.randint(0, 512, (2, 12)))
        ref_logits = model(ids).numpy()

        qmodel, _ = self._model()   # same seed -> same weights
        qmodel.eval()
        n = quant.quantize_for_decode(qmodel, algo="weight_only_int8")
        assert n == 2 * 4  # qkv, out, fc1, fc2 per layer
        assert qmodel._qhead_algo == "weight_only_int8"
        q_logits = qmodel(ids).numpy()
        # int8 weight error is small relative to logit scale
        denom = np.abs(ref_logits).max()
        assert np.abs(q_logits - ref_logits).max() < 0.05 * denom + 0.05

        out_ref = model.generate(ids, max_new_tokens=8).numpy()
        out_q = qmodel.generate(ids, max_new_tokens=8).numpy()
        assert out_q.shape == out_ref.shape
        # greedy tokens should mostly agree at int8
        agree = (out_ref[:, 12:] == out_q[:, 12:]).mean()
        assert agree >= 0.5, f"only {agree:.0%} of greedy tokens agree"

    def test_int4_generate_runs(self):
        qmodel, cfg = self._model()
        qmodel.eval()
        quant.quantize_for_decode(qmodel, algo="weight_only_int4",
                                  group_size=16)
        rs = np.random.RandomState(0)
        ids = paddle.to_tensor(rs.randint(0, 512, (2, 12)))
        out = qmodel.generate(ids, max_new_tokens=6).numpy()
        assert out.shape == (2, 18)
        assert (out[:, :12] == ids.numpy()).all()
