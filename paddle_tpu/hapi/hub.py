"""paddle.hub: hubconf.py entrypoint loading.

Reference: python/paddle/hapi/hub.py (list/help/load over a repo dir
containing `hubconf.py`, sources github/gitee/local). The `local`
source is implemented in full — a directory with a hubconf exposing
callables and an optional `dependencies` list. The network sources are
gated: this environment has no egress, and a TPU pod's workers should
load models from mounted storage anyway.
"""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = []

VAR_DEPENDENCY = "dependencies"
MODULE_HUBCONF = "hubconf.py"


def _check_source(source):
    if source not in ("github", "gitee", "local"):
        raise ValueError(
            f"unknown source {source!r}: expected github/gitee/local")
    if source != "local":
        raise NotImplementedError(
            f"hub source {source!r} needs network access, which the "
            "TPU build gates off; clone the repo yourself and use "
            "source='local' with its path")


def _import_hubconf(repo_dir):
    repo_dir = os.path.expanduser(repo_dir)
    path = os.path.join(repo_dir, MODULE_HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no {MODULE_HUBCONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    module = importlib.util.module_from_spec(spec)
    added = repo_dir not in sys.path
    if added:
        sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(module)
    finally:
        if added:  # never strip a pre-existing user entry
            sys.path.remove(repo_dir)
    deps = getattr(module, VAR_DEPENDENCY, None)
    if deps:
        missing = [d for d in deps
                   if importlib.util.find_spec(d) is None]
        if missing:
            raise RuntimeError(
                f"hubconf dependencies not installed: {missing}")
    return module


def _entrypoints(module):
    return [k for k, v in vars(module).items()
            if callable(v) and not k.startswith("_")]


def list(repo_dir, source="github", force_reload=False):
    """Entrypoint names exposed by the repo's hubconf."""
    _check_source(source)
    return _entrypoints(_import_hubconf(repo_dir))


def help(repo_dir, model, source="github", force_reload=False):
    """The docstring of one hubconf entrypoint."""
    _check_source(source)
    module = _import_hubconf(repo_dir)
    entry = getattr(module, model, None)
    if entry is None or not callable(entry):
        raise RuntimeError(f"Cannot find callable {model} in hubconf")
    return entry.__doc__


def load(repo_dir, model, source="github", force_reload=False,
         **kwargs):
    """Call a hubconf entrypoint (usually returns a constructed
    Layer)."""
    _check_source(source)
    module = _import_hubconf(repo_dir)
    entry = getattr(module, model, None)
    if entry is None or not callable(entry):
        raise RuntimeError(f"Cannot find callable {model} in hubconf")
    return entry(**kwargs)
