"""paddle.static.nn: static-graph layer builders.

Reference: python/paddle/static/nn/__init__.py (fc, embedding,
batch_norm, conv2d, ...) and static/nn/control_flow.py:874 (cond,
while_loop, case, switch_case). Each builder creates parameters on
first call and applies the functional op — under the recording Program
this appends the same DAG the reference's LayerHelper.append_op would.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
# control flow: identical objects — under static recording their lax
# lowering is captured as one program node
from ..ops.control_flow import (cond, case, switch_case,  # noqa: F401
                                while_loop)

__all__ = ["fc", "embedding", "batch_norm", "conv2d", "cond", "case",
           "switch_case", "while_loop", "static_pylayer"]

def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """reference: static/nn/common.py fc."""
    from ..nn.layer.common import Linear
    from ..ops import manipulation
    import paddle_tpu.nn.functional as F
    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    layer = Linear(in_dim, size, weight_attr=weight_attr,
                   bias_attr=bias_attr)
    if len(x.shape) > num_flatten_dims + 1:
        # -1 on the batch dim: the build-time placeholder batch (1) must
        # not be baked into the program (feeds carry the real batch)
        x = manipulation.reshape(
            x, [-1] + list(x.shape[1:num_flatten_dims]) + [in_dim])
    out = layer(x)
    if activation:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    from ..nn.layer.common import Embedding
    layer = Embedding(size[0], size[1], padding_idx=padding_idx,
                      weight_attr=param_attr)
    return layer(input)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               is_test=False, **kwargs):
    from ..nn.layer.norm import BatchNorm2D, BatchNorm1D
    import paddle_tpu.nn.functional as F
    ch = input.shape[1]
    cls = BatchNorm2D if len(input.shape) == 4 else BatchNorm1D
    layer = cls(ch, momentum=momentum, epsilon=epsilon)
    if is_test:
        layer.eval()
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, data_format="NCHW", **kwargs):
    from ..nn.layer.conv import Conv2D
    import paddle_tpu.nn.functional as F
    layer = Conv2D(input.shape[1], num_filters, filter_size,
                   stride=stride, padding=padding, dilation=dilation,
                   groups=groups)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def static_pylayer(*args, **kwargs):
    raise NotImplementedError(
        "static_pylayer: use paddle_tpu.autograd.PyLayer in dynamic "
        "mode; the recording Program captures it as one op")
