"""Common functional ops: linear, dropout, embedding, padding, interpolate.

TPU-native replacement for python/paddle/nn/functional/common.py and the
matching PHI kernels. Dropout takes an explicit threefry key input (kept
pure so it works identically under eager and pjit tracing — the reference's
stateful per-device Philox generator has no TPU analogue).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core import random as random_mod
from ...core.dispatch import register_op
from ...core.tensor import Tensor
from ...ops._helpers import as_tensor, apply_op

__all__ = ["linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout",
           "embedding", "one_hot", "pad", "zeropad2d", "interpolate",
           "upsample", "pixel_shuffle", "pixel_unshuffle", "channel_shuffle",
           "cosine_similarity", "bilinear", "label_smooth", "unfold", "fold",
           "class_center_sample", "linear_bias", "affine_grid",
           "grid_sample", "sequence_mask", "temporal_shift",
           "max_unpool2d"]


# -- linear ------------------------------------------------------------------

register_op("linear", lambda x, w: jnp.matmul(x, w))
register_op("linear_bias", lambda x, w, b: jnp.matmul(x, w) + b)


def linear(x, weight, bias=None, name=None):
    """y = x @ W (+ b). Weight is [in, out] (paddle convention).

    Lowered as one dot_general (+fused add) on the MXU; replaces the
    cuBLASLt epilogue path (fused_gemm_epilogue_op.cu) for free via XLA.
    """
    x, weight = as_tensor(x), as_tensor(weight)
    if bias is None:
        return apply_op("linear", x, weight)
    return apply_op("linear_bias", x, weight, as_tensor(bias))


def linear_bias(x, weight, bias):
    return linear(x, weight, bias)


# -- dropout -----------------------------------------------------------------

def _use_rbg_dropout():
    # PADDLE_TPU_RBG_DROPOUT=0 restores the threefry mask stream for
    # exact-mask reproducibility against pre-r4 goldens (ADVICE r4);
    # default stays rbg (the threefry path alone cost ~30% of a
    # BERT-base train step)
    import os
    return os.environ.get("PADDLE_TPU_RBG_DROPOUT", "1") != "0"


def _fast_bits_key(key):
    """Raw threefry uint32[2] -> typed rbg key. The mask bits then come
    from the TPU's rng_bit_generator HLO instead of per-element
    threefry — on v5e the threefry path alone cost ~30% of a BERT-base
    train step (25 dropout sites x [B,L,H] masks). rbg is weaker
    statistically but ample for dropout; mask streams differ from the
    threefry ones, so fixed-seed mask values are not stable across this
    change (distributions and determinism per (seed, draw) are; set
    PADDLE_TPU_RBG_DROPOUT=0 for the old stream). The rbg key derives
    from the threefry words by XOR with distinct odd constants (the
    murmur/boost hash-combine multipliers) purely to decorrelate the
    four lanes."""
    if not _use_rbg_dropout():
        return key
    k = key.reshape(-1).astype(jnp.uint32)
    data = jnp.stack([k[0], k[1],
                      k[0] ^ jnp.uint32(0x9E3779B9),
                      k[1] ^ jnp.uint32(0x85EBCA6B)])
    return jax.random.wrap_key_data(data, impl="rbg")


def _dropout_fwd(x, key, p, upscale):
    if p == 0.0:
        return x
    keep = 1.0 - p
    mask = jax.random.bernoulli(_fast_bits_key(key), keep, x.shape)
    if upscale:
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
    return jnp.where(mask, x, 0.0).astype(x.dtype)


def _dropout_axis_fwd(x, key, p, upscale, mask_shape):
    if p == 0.0:
        return x
    keep = 1.0 - p
    mask = jax.random.bernoulli(_fast_bits_key(key), keep, mask_shape)
    mask = jnp.broadcast_to(mask, x.shape)
    if upscale:
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
    return jnp.where(mask, x, 0.0).astype(x.dtype)


register_op("dropout", _dropout_fwd)
register_op("dropout_axis", _dropout_axis_fwd)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    x = as_tensor(x)
    p = float(p)
    if not training:
        if mode == "upscale_in_train":
            return x
        from ...ops import math as math_ops
        return math_ops.scale(x, 1.0 - p)
    if p == 0.0:
        return x
    upscale = mode == "upscale_in_train"
    key = Tensor(random_mod.next_key())
    if axis is None:
        return apply_op("dropout", x, key, attrs=dict(p=p, upscale=upscale))
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    mask_shape = tuple(x.shape[i] if i in axes else 1 for i in range(x.ndim))
    return apply_op("dropout_axis", x, key,
                    attrs=dict(p=p, upscale=upscale, mask_shape=mask_shape))


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    x = as_tensor(x)
    if data_format.startswith("NC"):
        return dropout(x, p, axis=[0, 1], training=training)
    return dropout(x, p, axis=[0, 3], training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    x = as_tensor(x)
    if data_format.startswith("NC"):
        return dropout(x, p, axis=[0, 1], training=training)
    return dropout(x, p, axis=[0, 4], training=training)


def _alpha_dropout_fwd(x, key, p):
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = 1.0 - p
    a = (keep + alpha_p ** 2 * keep * (1 - keep)) ** -0.5
    b = -a * alpha_p * (1 - keep)
    mask = jax.random.bernoulli(_fast_bits_key(key), keep, x.shape)
    return (a * jnp.where(mask, x, alpha_p) + b).astype(x.dtype)


register_op("alpha_dropout", _alpha_dropout_fwd)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = as_tensor(x)
    if not training or p == 0.0:
        return x
    key = Tensor(random_mod.next_key())
    return apply_op("alpha_dropout", x, key, attrs=dict(p=float(p)))


# -- embedding ---------------------------------------------------------------

def _embedding_fwd(ids, w, padding_idx):
    out = jnp.take(w, ids, axis=0)
    if padding_idx is not None:
        mask = (ids != padding_idx)[..., None]
        out = jnp.where(mask, out, 0.0)
    return out


register_op("embedding", _embedding_fwd)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Vocab lookup = gather from the [V, D] table; `sparse` is accepted for
    API parity but meaningless under XLA (grads are dense scatter-adds,
    reference: paddle/fluid/operators/lookup_table_v2_op.cu)."""
    x, weight = as_tensor(x), as_tensor(weight)
    if padding_idx is not None:
        padding_idx = int(padding_idx)
        if padding_idx < 0:
            padding_idx += weight.shape[0]
    return apply_op("embedding", x, weight,
                    attrs=dict(padding_idx=padding_idx))


register_op("one_hot_op",
            lambda x, num_classes: jax.nn.one_hot(x, num_classes),
            nondiff=False)


def one_hot(x, num_classes, name=None):
    return apply_op("one_hot_op", as_tensor(x),
                    attrs=dict(num_classes=int(num_classes)))


# -- padding -----------------------------------------------------------------

def _pad_nd_fwd(x, pad_pairs, mode, value):
    if mode == "constant":
        return jnp.pad(x, pad_pairs, mode="constant", constant_values=value)
    if mode == "reflect":
        return jnp.pad(x, pad_pairs, mode="reflect")
    if mode == "replicate":
        return jnp.pad(x, pad_pairs, mode="edge")
    if mode == "circular":
        return jnp.pad(x, pad_pairs, mode="wrap")
    raise ValueError(f"Unknown pad mode {mode}")


register_op("pad_nd", _pad_nd_fwd)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None,
        pad_from_left_axis=True):
    x = as_tensor(x)
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]
    nd = x.ndim
    if len(pad) == 2 * nd:
        # full-tensor pad, paddle semantics: [lo0, hi0, lo1, hi1, ...] when
        # pad_from_left_axis else reversed-from-last like torch
        if pad_from_left_axis:
            pairs = tuple((pad[2 * i], pad[2 * i + 1]) for i in range(nd))
        else:
            pairs = tuple((pad[2 * (nd - 1 - i)], pad[2 * (nd - 1 - i) + 1])
                          for i in range(nd))
    else:
        # spatial-only pad in data_format order: [l, r(, t, b)(, f, bk)]
        n_sp = len(pad) // 2
        channel_last = not data_format.startswith("NC")
        sp_axes = (list(range(1, 1 + n_sp)) if channel_last
                   else list(range(2, 2 + n_sp)))
        # paddle orders spatial pads from the last axis group: for NCHW pad
        # is [left,right,top,bottom] = W then H
        pairs_l = [(0, 0)] * nd
        for i, ax in enumerate(reversed(sp_axes)):
            pairs_l[ax] = (pad[2 * i], pad[2 * i + 1])
        pairs = tuple(pairs_l)
    return apply_op("pad_nd", x, attrs=dict(pad_pairs=pairs, mode=mode,
                                            value=float(value)))


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0,
               data_format=data_format)


# -- interpolate -------------------------------------------------------------

def _interp_fwd(x, out_sizes, mode, align_corners, channel_last):
    n_sp = len(out_sizes)
    if channel_last:
        sp_axes = tuple(range(1, 1 + n_sp))
    else:
        sp_axes = tuple(range(2, 2 + n_sp))
    method = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
              "trilinear": "linear", "bicubic": "cubic",
              "area": "linear"}[mode]
    if align_corners and method != "nearest":
        # jax.image has no align_corners; do it per-axis with explicit
        # gather-weights
        return _align_corners_resize(x, out_sizes, sp_axes, method)
    new_shape = list(x.shape)
    for ax, s in zip(sp_axes, out_sizes):
        new_shape[ax] = s
    return jax.image.resize(x, tuple(new_shape), method=method)


def _align_corners_resize(x, out_sizes, sp_axes, method):
    out = x
    for ax, o in zip(sp_axes, out_sizes):
        i = out.shape[ax]
        if o == 1 or i == 1:
            idx = jnp.zeros((o,), dtype=jnp.int32)
            out = jnp.take(out, idx, axis=ax)
            continue
        pos = jnp.linspace(0.0, i - 1.0, o)
        if method == "nearest":
            idx = jnp.round(pos).astype(jnp.int32)
            out = jnp.take(out, idx, axis=ax)
        else:
            lo = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, i - 1)
            hi = jnp.clip(lo + 1, 0, i - 1)
            w = (pos - lo).astype(out.dtype)
            shape = [1] * out.ndim
            shape[ax] = o
            w = w.reshape(shape)
            out = jnp.take(out, lo, axis=ax) * (1 - w) + \
                jnp.take(out, hi, axis=ax) * w
    return out


register_op("interpolate", _interp_fwd)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    x = as_tensor(x)
    channel_last = not data_format.startswith("NC")
    n_sp = x.ndim - 2
    if channel_last:
        spatial = x.shape[1:1 + n_sp]
    else:
        spatial = x.shape[2:2 + n_sp]
    if size is not None:
        if isinstance(size, Tensor):
            size = size.tolist()
        if isinstance(size, (int, np.integer)):
            size = [int(size)] * n_sp
        out_sizes = tuple(int(s) for s in size)
    else:
        if scale_factor is None:
            raise ValueError("one of size/scale_factor required")
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * n_sp
        out_sizes = tuple(int(spatial[i] * scale_factor[i])
                          for i in range(n_sp))
    return apply_op("interpolate", x,
                    attrs=dict(out_sizes=out_sizes, mode=mode,
                               align_corners=bool(align_corners),
                               channel_last=channel_last))


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW",
             name=None):
    return interpolate(x, size, scale_factor, mode, align_corners,
                       align_mode, data_format)


# -- pixel shuffle et al -----------------------------------------------------

def _pixel_shuffle_fwd(x, r, channel_last):
    if channel_last:
        n, h, w, c = x.shape
        x = x.reshape(n, h, w, r, r, c // (r * r))
        x = x.transpose(0, 1, 3, 2, 4, 5)
        return x.reshape(n, h * r, w * r, c // (r * r))
    n, c, h, w = x.shape
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = x.transpose(0, 1, 4, 2, 5, 3)
    return x.reshape(n, c // (r * r), h * r, w * r)


def _pixel_unshuffle_fwd(x, r, channel_last):
    if channel_last:
        n, h, w, c = x.shape
        x = x.reshape(n, h // r, r, w // r, r, c)
        x = x.transpose(0, 1, 3, 2, 4, 5)
        return x.reshape(n, h // r, w // r, c * r * r)
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // r, r, w // r, r)
    x = x.transpose(0, 1, 3, 5, 2, 4)
    return x.reshape(n, c * r * r, h // r, w // r)


register_op("pixel_shuffle", _pixel_shuffle_fwd)
register_op("pixel_unshuffle", _pixel_unshuffle_fwd)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    return apply_op("pixel_shuffle", as_tensor(x),
                    attrs=dict(r=int(upscale_factor),
                               channel_last=not data_format.startswith("NC")))


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    return apply_op("pixel_unshuffle", as_tensor(x),
                    attrs=dict(r=int(downscale_factor),
                               channel_last=not data_format.startswith("NC")))


def _channel_shuffle_fwd(x, groups, channel_last):
    if channel_last:
        n, h, w, c = x.shape
        x = x.reshape(n, h, w, groups, c // groups)
        x = jnp.swapaxes(x, 3, 4)
        return x.reshape(n, h, w, c)
    n, c, h, w = x.shape
    x = x.reshape(n, groups, c // groups, h, w)
    x = jnp.swapaxes(x, 1, 2)
    return x.reshape(n, c, h, w)


register_op("channel_shuffle", _channel_shuffle_fwd)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    return apply_op("channel_shuffle", as_tensor(x),
                    attrs=dict(groups=int(groups),
                               channel_last=not data_format.startswith("NC")))


# -- similarity / misc -------------------------------------------------------

register_op("cosine_similarity_op",
            lambda x1, x2, axis, eps:
            jnp.sum(x1 * x2, axis=axis) /
            jnp.maximum(jnp.linalg.norm(x1, axis=axis) *
                        jnp.linalg.norm(x2, axis=axis), eps))


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    return apply_op("cosine_similarity_op", as_tensor(x1), as_tensor(x2),
                    attrs=dict(axis=int(axis), eps=float(eps)))


register_op("bilinear_op",
            lambda x1, x2, w: jnp.einsum("bi,oij,bj->bo", x1, w, x2))
register_op("bilinear_bias_op",
            lambda x1, x2, w, b: jnp.einsum("bi,oij,bj->bo", x1, w, x2) + b)


def bilinear(x1, x2, weight, bias=None, name=None):
    x1, x2, weight = as_tensor(x1), as_tensor(x2), as_tensor(weight)
    if bias is None:
        return apply_op("bilinear_op", x1, x2, weight)
    return apply_op("bilinear_bias_op", x1, x2, weight, as_tensor(bias))


register_op("label_smooth_op",
            lambda label, epsilon: (1.0 - epsilon) * label +
            epsilon / label.shape[-1])


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = as_tensor(label)
    if prior_dist is not None:
        prior_dist = as_tensor(prior_dist)
        return apply_op("label_smooth_prior_op", label, prior_dist,
                        attrs=dict(epsilon=float(epsilon)))
    return apply_op("label_smooth_op", label,
                    attrs=dict(epsilon=float(epsilon)))


register_op("label_smooth_prior_op",
            lambda label, prior, epsilon:
            (1.0 - epsilon) * label + epsilon * prior)


# -- unfold / fold (im2col) --------------------------------------------------

def _unfold_fwd(x, kernel, stride, padding, dilation):
    n, c, h, w = x.shape
    kh, kw = kernel
    patches = jax.lax.conv_general_dilated_patches(
        x, filter_shape=(kh, kw), window_strides=stride,
        padding=[tuple(p) for p in padding], rhs_dilation=dilation,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # patches: [N, C*kh*kw, oh, ow] -> [N, C*kh*kw, L]
    return patches.reshape(n, c * kh * kw, -1)


register_op("unfold_op", _unfold_fwd)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    from .conv import _norm_tuple, _norm_padding
    x = as_tensor(x)
    kernel = _norm_tuple(kernel_sizes, 2, "kernel_sizes")
    stride = _norm_tuple(strides, 2, "strides")
    dilation = _norm_tuple(dilations, 2, "dilations")
    padding = _norm_padding(paddings, 2, "NCHW")
    return apply_op("unfold_op", x,
                    attrs=dict(kernel=kernel, stride=stride, padding=padding,
                               dilation=dilation))


def _fold_fwd(x, output_sizes, kernel, stride, padding, dilation):
    n, ckk, l = x.shape
    kh, kw = kernel
    c = ckk // (kh * kw)
    oh, ow = output_sizes
    # number of sliding positions
    eff_kh = (kh - 1) * dilation[0] + 1
    eff_kw = (kw - 1) * dilation[1] + 1
    nh = (oh + padding[0][0] + padding[0][1] - eff_kh) // stride[0] + 1
    nw = (ow + padding[1][0] + padding[1][1] - eff_kw) // stride[1] + 1
    cols = x.reshape(n, c, kh, kw, nh, nw)
    out = jnp.zeros((n, c, oh + padding[0][0] + padding[0][1],
                     ow + padding[1][0] + padding[1][1]), dtype=x.dtype)
    for i in range(kh):
        for j in range(kw):
            hi = i * dilation[0]
            wj = j * dilation[1]
            out = out.at[:, :, hi:hi + nh * stride[0]:stride[0],
                         wj:wj + nw * stride[1]:stride[1]].add(
                cols[:, :, i, j])
    return out[:, :, padding[0][0]:padding[0][0] + oh,
               padding[1][0]:padding[1][0] + ow]


register_op("fold_op", _fold_fwd)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    from .conv import _norm_tuple, _norm_padding
    x = as_tensor(x)
    out_sizes = _norm_tuple(output_sizes, 2, "output_sizes")
    kernel = _norm_tuple(kernel_sizes, 2, "kernel_sizes")
    stride = _norm_tuple(strides, 2, "strides")
    dilation = _norm_tuple(dilations, 2, "dilations")
    padding = _norm_padding(paddings, 2, "NCHW")
    return apply_op("fold_op", x,
                    attrs=dict(output_sizes=out_sizes, kernel=kernel,
                               stride=stride, padding=padding,
                               dilation=dilation))


def class_center_sample(label, num_classes, num_samples, group=None):
    raise NotImplementedError(
        "class_center_sample (PartialFC) lands with the distributed "
        "margin-loss work")


def _affine_grid_fwd(theta, out_shape, align_corners):
    n, c, h, w = out_shape
    if align_corners:
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
    else:
        ys = (jnp.arange(h) * 2 + 1) / h - 1.0
        xs = (jnp.arange(w) * 2 + 1) / w - 1.0
    gy = jnp.repeat(ys, w).reshape(h, w)
    gx = jnp.tile(xs, (h, 1))
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1).reshape(-1, 3)  # [hw, 3]
    out = jnp.einsum("nij,pj->npi", theta, base)              # [n,hw,2]
    return out.reshape(n, h, w, 2)


register_op("affine_grid", _affine_grid_fwd)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """reference: nn/functional/vision.py affine_grid (affine_grid_op)."""
    return apply_op("affine_grid", as_tensor(theta),
                    attrs=dict(out_shape=tuple(int(s) for s in out_shape),
                               align_corners=bool(align_corners)))


def _grid_sample_fwd(x, grid, mode, padding_mode, align_corners):
    """x: [N, C, H, W]; grid: [N, Ho, Wo, 2] in [-1, 1]."""
    n, c, h, w = x.shape

    def unnormalize(coord, size):
        if align_corners:
            return (coord + 1.0) / 2.0 * (size - 1)
        return ((coord + 1.0) * size - 1.0) / 2.0

    gx = unnormalize(grid[..., 0], w)      # [N, Ho, Wo]
    gy = unnormalize(grid[..., 1], h)

    def reflect(coord, size):
        # mirror into [0, size-1] (align_corners) / [-0.5, size-0.5]
        if align_corners:
            span = 2 * (size - 1)
            if span == 0:
                return jnp.zeros_like(coord)
            c = jnp.abs(coord) % span
            return jnp.where(c > size - 1, span - c, c)
        span = 2 * size
        c = jnp.abs(coord + 0.5) % span
        c = jnp.where(c > size, span - c, c) - 0.5
        return jnp.clip(c, 0, size - 1)

    if padding_mode == "reflection":
        gx = reflect(gx, w)
        gy = reflect(gy, h)

    def sample_one(feat, yy, xx):
        if mode == "nearest":
            yi = jnp.clip(jnp.round(yy), 0, h - 1).astype(jnp.int32)
            xi = jnp.clip(jnp.round(xx), 0, w - 1).astype(jnp.int32)
            out = feat[:, yi, xi]
            inb = ((yy >= -0.5) & (yy <= h - 0.5)
                   & (xx >= -0.5) & (xx <= w - 0.5))
        else:
            y0 = jnp.floor(yy)
            x0 = jnp.floor(xx)
            ly, lx = yy - y0, xx - x0

            def at(yi, xi):
                v = feat[:, jnp.clip(yi, 0, h - 1).astype(jnp.int32),
                         jnp.clip(xi, 0, w - 1).astype(jnp.int32)]
                if padding_mode == "zeros":
                    ok = ((yi >= 0) & (yi <= h - 1)
                          & (xi >= 0) & (xi <= w - 1))
                    v = v * ok.astype(v.dtype)
                return v

            out = (at(y0, x0) * (1 - ly) * (1 - lx)
                   + at(y0, x0 + 1) * (1 - ly) * lx
                   + at(y0 + 1, x0) * ly * (1 - lx)
                   + at(y0 + 1, x0 + 1) * ly * lx)
            inb = None
        if mode == "nearest" and padding_mode == "zeros":
            out = out * inb.astype(out.dtype)
        return out

    return jax.vmap(sample_one)(x, gy, gx)


register_op("grid_sample", _grid_sample_fwd)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """reference: nn/functional/vision.py grid_sample (grid_sampler_op)."""
    return apply_op("grid_sample", as_tensor(x), as_tensor(grid),
                    attrs=dict(mode=mode, padding_mode=padding_mode,
                               align_corners=bool(align_corners)))


register_op(
    "sequence_mask",
    lambda lengths, maxlen, dtype_str: (
        jnp.arange(maxlen) <
        lengths[..., None]).astype(dtype_str),
    nondiff=True)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """reference: nn/functional/sequence_mask (sequence LoD legacy made
    static-shape: [B] lengths -> [B, maxlen] mask)."""
    x = as_tensor(x)
    if maxlen is None:
        maxlen = int(np.asarray(x._value).max())
    from ...core import dtype as dtypes
    return apply_op("sequence_mask", x,
                    attrs=dict(maxlen=int(maxlen),
                               dtype_str=str(np.dtype(
                                   dtypes.to_np_dtype(dtype)))))


def _temporal_shift_fwd(x, seg_num, shift_ratio):
    nt, c, h, w = x.shape
    n = nt // seg_num
    x = x.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    left = jnp.concatenate([x[:, 1:, :fold],
                            jnp.zeros_like(x[:, :1, :fold])], axis=1)
    right = jnp.concatenate([jnp.zeros_like(x[:, :1, fold:2 * fold]),
                             x[:, :-1, fold:2 * fold]], axis=1)
    rest = x[:, :, 2 * fold:]
    return jnp.concatenate([left, right, rest],
                           axis=2).reshape(nt, c, h, w)


register_op("temporal_shift", _temporal_shift_fwd)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None,
                   data_format="NCHW"):
    """reference: nn/functional temporal_shift (temporal_shift_op, TSM)."""
    x = as_tensor(x)
    if data_format == "NHWC":
        from ...ops.manipulation import transpose
        out = apply_op("temporal_shift", transpose(x, [0, 3, 1, 2]),
                       attrs=dict(seg_num=int(seg_num),
                                  shift_ratio=float(shift_ratio)))
        return transpose(out, [0, 2, 3, 1])
    return apply_op("temporal_shift", x,
                    attrs=dict(seg_num=int(seg_num),
                               shift_ratio=float(shift_ratio)))


def _max_unpool2d_fwd(x, indices, out_h, out_w):
    n, c, h, w = x.shape
    flat = x.reshape(n, c, -1)
    idx = indices.reshape(n, c, -1).astype(jnp.int32)
    out = jnp.zeros((n, c, out_h * out_w), x.dtype)
    out = jax.vmap(jax.vmap(
        lambda o, i, v: o.at[i].set(v)))(out, idx, flat)
    return out.reshape(n, c, out_h, out_w)


register_op("max_unpool2d", _max_unpool2d_fwd)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """reference: nn/functional max_unpool2d (unpool_op): scatter pooled
    values back to their argmax positions."""
    x = as_tensor(x)
    if stride is None:
        stride = kernel_size

    def _pair(v):
        return (v, v) if isinstance(v, int) else (int(v[0]), int(v[1]))

    if output_size is not None:
        out_h, out_w = output_size[-2], output_size[-1]
    else:
        kh, kw = _pair(kernel_size)
        sh, sw = _pair(stride)
        ph, pw = _pair(padding)
        out_h = (x.shape[2] - 1) * sh + kh - 2 * ph
        out_w = (x.shape[3] - 1) * sw + kw - 2 * pw
    return apply_op("max_unpool2d", x, as_tensor(indices),
                    attrs=dict(out_h=int(out_h), out_w=int(out_w)))
