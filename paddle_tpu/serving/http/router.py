"""Multi-replica router: placement, failover, deadlines, drain.

Fronts N `EngineDriver` replicas with:

- **Least-loaded placement**: replicas are ranked by
  (queue depth, inflight, -free pages) — the emptiest queue wins, free
  KV pages break ties, so a replica whose pool is fragmented by long
  residents yields to one with headroom.
- **Typed load shedding**: when every healthy replica's admission queue
  is full, `submit` re-raises `QueueFull` (HTTP 429 + Retry-After);
  when none is healthy (or the router is draining), `EngineClosed`
  (HTTP 503).
- **Retry of UNSTARTED requests**: a request that dies with reason
  "replica_failure" and zero emitted tokens never started decoding —
  the `Ticket` transparently resubmits it on a surviving replica with
  capped exponential backoff + full jitter. Requests that already
  streamed tokens are NOT retried (the client saw output; replaying
  could diverge for sampled requests).
- **Graceful drain**: `drain()` stops admission, drains every replica
  in parallel (residents finish, queued are aborted), and joins the
  driver threads. `/readyz` flips to 503 the moment drain begins.
"""
from __future__ import annotations

import itertools
import random
import threading
import time
from typing import List, Optional, Sequence, Tuple

from ..errors import EngineClosed, QueueFull, ServingError
from ..request import Request, RequestOutput, SamplingParams
from .driver import EngineDriver, ReplicaDead

__all__ = ["Router", "Ticket"]

_RETRYABLE_REASON = "replica_failure"


class Ticket:
    """One client request's journey through the router — possibly
    spanning several engine-level Request attempts across replicas.
    `events()` is the single consumption point: it forwards tokens,
    surfaces idle beats (for disconnect probing), and performs the
    unstarted-request failover transparently."""

    def __init__(self, router: "Router", ticket_id: str, prompt_ids,
                 sampling: Optional[SamplingParams]):
        self.id = ticket_id
        self._router = router
        self._prompt_ids = prompt_ids
        self._sampling = sampling
        self.attempts = 1
        self.error: Optional[ServingError] = None
        # may raise QueueFull/EngineClosed straight to the HTTP layer
        self.driver, self.request = router._place(prompt_ids, sampling,
                                                  exclude=())
        self._tried = [self.driver]

    # -- consumption -------------------------------------------------------
    def events(self, poll_s: float = 0.05):
        """Yield ("token", id) / ("idle", None) / ("done", reason) /
        ("error", exc). "idle" fires every `poll_s` with no token so the
        caller can probe client liveness; after "done"/"error" the
        generator returns."""
        while True:
            req = self.request
            kind, val = req.next_event(timeout=poll_s)
            if kind == "token":
                yield ("token", val)
            elif kind == "idle":
                yield ("idle", None)
            elif (val == _RETRYABLE_REASON and not req.output_tokens):
                try:
                    self._retry()
                except ServingError as exc:
                    self.error = exc
                    yield ("error", exc)
                    return
            else:
                yield ("done", val)
                return

    def result(self, poll_s: float = 0.05) -> RequestOutput:
        """Blocking non-stream path: consume to completion. Raises the
        terminal ServingError if every attempt failed."""
        for kind, val in self.events(poll_s=poll_s):
            if kind == "error":
                raise val
            if kind == "done":
                break
        return self.request.output()

    def cancel(self):
        """Client went away: evict the live attempt and reclaim its
        slot/pages at the replica's next step boundary."""
        self.driver.cancel(self.request.request_id)

    # -- failover ----------------------------------------------------------
    def _retry(self):
        """Resubmit an unstarted request on another replica, capped
        exponential backoff + full jitter between attempts."""
        r = self._router
        last: Optional[ServingError] = None
        for attempt in range(r.max_retries):
            delay = min(r.backoff_cap_s,
                        r.backoff_base_s * (2 ** attempt))
            time.sleep(delay * r._jitter())
            try:
                self.driver, self.request = r._place(
                    self._prompt_ids, self._sampling,
                    exclude=self._tried)
            except (QueueFull, EngineClosed) as exc:
                last = exc
                continue
            self._tried.append(self.driver)
            self.attempts += 1
            with r._lock:
                r.retries_total += 1
            return
        raise last if last is not None else EngineClosed(
            "failover retries exhausted")


class Router:
    def __init__(self, drivers: Sequence[EngineDriver], *,
                 max_retries: int = 3, backoff_base_s: float = 0.05,
                 backoff_cap_s: float = 2.0,
                 default_timeout_s: Optional[float] = None,
                 jitter=None):
        if not drivers:
            raise ValueError("router needs at least one driver")
        names = [d.name for d in drivers]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate driver names: {names}")
        self.drivers: List[EngineDriver] = list(drivers)
        self.max_retries = int(max_retries)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.default_timeout_s = default_timeout_s
        # full jitter in (0, 1]: decorrelates thundering-herd retries
        self._jitter = jitter or (lambda: random.random() or 1.0)
        self._lock = threading.Lock()
        self._draining = False
        self._ids = itertools.count()
        self.retries_total = 0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "Router":
        for d in self.drivers:
            d.start()
        return self

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def healthy(self) -> bool:
        """Liveness: at least one replica pump thread is serving."""
        return any(d.healthy for d in self.drivers)

    @property
    def ready(self) -> bool:
        """Readiness: healthy AND still admitting (not draining)."""
        return not self._draining and self.healthy

    def drain(self, timeout: Optional[float] = None):
        """Stop admitting, finish every resident on every replica,
        join the driver threads. Safe to call more than once."""
        self._draining = True
        threads = [threading.Thread(target=d.drain, args=(timeout,),
                                    daemon=True)
                   for d in self.drivers]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout)

    # -- submission --------------------------------------------------------
    def submit(self, prompt_ids, sampling: Optional[SamplingParams] = None,
               ticket_id: Optional[str] = None) -> Ticket:
        """Place a request on the least-loaded healthy replica. Raises
        QueueFull (429) when every healthy replica sheds, EngineClosed
        (503) when draining or no replica is healthy."""
        if self._draining:
            raise EngineClosed("router is draining")
        if sampling is not None and sampling.timeout_s is None \
                and self.default_timeout_s is not None:
            sampling.timeout_s = self.default_timeout_s
        if ticket_id is None:
            ticket_id = f"cmpl-{next(self._ids)}"
        return Ticket(self, ticket_id, prompt_ids, sampling)

    def _place(self, prompt_ids, sampling,
               exclude: Sequence[EngineDriver]
               ) -> Tuple[EngineDriver, Request]:
        if self._draining:
            raise EngineClosed("router is draining")
        cands = [d for d in self.drivers
                 if d.healthy and d not in exclude]
        if not cands:
            # every survivor already tried: allow re-tries on them
            # rather than failing a retryable request outright
            cands = [d for d in self.drivers if d.healthy]
        if not cands:
            raise EngineClosed("no healthy replica")
        cands.sort(key=self._load_key)
        last: Optional[ServingError] = None
        for d in cands:
            try:
                return d, d.submit(prompt_ids, sampling)
            except QueueFull as exc:
                last = exc
            except (ReplicaDead, EngineClosed) as exc:
                # raced into death/drain between the health check and
                # the submit; try the next candidate
                last = exc
        if isinstance(last, QueueFull):
            raise last
        raise EngineClosed("no replica accepted the request") from last

    @staticmethod
    def _load_key(d: EngineDriver):
        s = d.stats()
        return (s["queue_depth"], s["inflight"], -s["free_pages"])

    # -- observability -----------------------------------------------------
    def stats(self) -> dict:
        return {
            "ready": self.ready,
            "draining": self._draining,
            "replicas": [d.stats() for d in self.drivers],
            "retries_total": self.retries_total,
        }

    def metrics_snapshots(self) -> dict:
        """{replica name: engine metrics snapshot} for /metrics."""
        return {d.name: d.engine.metrics.snapshot()
                for d in self.drivers}
