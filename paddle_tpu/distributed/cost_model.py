"""Auto-parallel cost model + mesh tuner.

Reference: python/paddle/distributed/auto_parallel/cost_model.py (an
analytic per-op cost graph) and auto_parallel/tuner/ (profile-driven
search over dist attrs). The TPU-first replacement does not re-derive
per-op costs by hand: XLA already computes them. For every candidate
mesh factorization we AOT-compile the REAL train step (GSPMD inserts
the collectives) and read the compiler's own `cost_analysis()` /
`memory_analysis()` — flops, bytes accessed, and per-device peak
buffers of the exact program that would run — then rank by an analytic
time estimate.

    from paddle_tpu.distributed import cost_model
    report = cost_model.tune_mesh(build_step, n_devices=8,
                                  axis_names=("dp", "mp"))
    best = report.best  # MeshPlan(shape={'dp': 4, 'mp': 2}, ...)

`build_step(mesh)` builds model/optimizer/batch under the given
ProcessMesh and returns either a `jit.CompiledTrainStep` together with
its batch — `(step, batch)` — or a pre-lowered `jax.stages.Lowered`.
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

__all__ = ["MeshPlan", "TuneReport", "tune_mesh", "analyze_lowered",
           "chip_specs"]


# Per-chip peak numbers for the analytic time model; keyed by substring
# of device_kind (fallback: generic). (flops/s bf16, HBM bytes/s,
# ICI bytes/s per link)
_CHIPS = {
    "v5p": (459e12, 2765e9, 100e9),
    "v5 lite": (197e12, 819e9, 50e9),
    "v5e": (197e12, 819e9, 50e9),
    "v4": (275e12, 1228e9, 50e9),
    "v3": (123e12, 900e9, 50e9),
    "cpu": (1e11, 50e9, 10e9),
}


def chip_specs(device_kind: str):
    kind = (device_kind or "").lower()
    for k, v in _CHIPS.items():
        if k in kind:
            return v
    return _CHIPS["cpu"]


@dataclass
class MeshPlan:
    shape: dict                    # axis name -> degree
    flops: float = 0.0             # whole-program FLOPs (all devices)
    bytes_accessed: float = 0.0
    peak_bytes: Optional[int] = None   # per-device arg+temp+out bytes
    est_seconds: Optional[float] = None
    error: Optional[str] = None

    def fits(self, hbm_bytes):
        return self.peak_bytes is not None and \
            self.peak_bytes <= hbm_bytes


@dataclass
class TuneReport:
    plans: list = field(default_factory=list)

    @property
    def best(self) -> Optional[MeshPlan]:
        ok = [p for p in self.plans if p.error is None
              and p.est_seconds is not None]
        return min(ok, key=lambda p: p.est_seconds) if ok else None

    def summary(self):
        lines = []
        for p in sorted(self.plans,
                        key=lambda p: (p.error is not None,
                                       p.est_seconds or 0)):
            if p.error:
                lines.append(f"{p.shape}: FAILED {p.error[:60]}")
            else:
                mem = (f"{p.peak_bytes / 1e6:.0f}MB"
                       if p.peak_bytes is not None else "?")
                lines.append(
                    f"{p.shape}: est {p.est_seconds * 1e3:.2f} ms, "
                    f"{p.flops / 1e9:.1f} GFLOP, peak/device {mem}")
        return "\n".join(lines)


def _factorizations(n, k):
    """All ordered k-tuples of positive ints whose product is n."""
    if k == 1:
        yield (n,)
        return
    for d in range(1, n + 1):
        if n % d == 0:
            for rest in _factorizations(n // d, k - 1):
                yield (d,) + rest


def analyze_lowered(lowered, n_devices, device_kind=None):
    """Compile a lowered computation and pull XLA's own numbers."""
    import jax
    comp = lowered.compile()
    ca = comp.cost_analysis() or {}
    flops = float(ca.get("flops", 0.0))
    bytes_acc = float(ca.get("bytes accessed", 0.0))
    peak = None
    try:
        ms = comp.memory_analysis()
        peak = int(ms.argument_size_in_bytes + ms.temp_size_in_bytes
                   + ms.output_size_in_bytes)
    except Exception:
        pass
    kind = device_kind or getattr(jax.devices()[0], "device_kind", "")
    peak_flops, hbm_bw, _ = chip_specs(kind)
    # roofline estimate of the per-device step time: compute and HBM
    # traffic are totals over the SPMD program, split across devices
    est = max(flops / n_devices / peak_flops,
              bytes_acc / n_devices / hbm_bw)
    return flops, bytes_acc, peak, est


def tune_mesh(build_step: Callable, n_devices: int,
              axis_names: Sequence[str] = ("dp", "mp"),
              hbm_bytes: Optional[int] = None) -> TuneReport:
    """Try every factorization of n_devices over axis_names, compile
    the real step per candidate, rank by the roofline estimate.
    Candidates whose per-device peak exceeds hbm_bytes are kept in the
    report but excluded from `best` via est=None."""
    from .mesh import ProcessMesh, set_mesh, get_mesh

    report = TuneReport()
    prev = get_mesh()
    try:
        for dims in _factorizations(int(n_devices), len(axis_names)):
            shape = dict(zip(axis_names, dims))
            plan = MeshPlan(shape=shape)
            report.plans.append(plan)
            try:
                mesh = ProcessMesh(shape=list(dims),
                                   dim_names=list(axis_names))
                set_mesh(mesh)
                built = build_step(mesh)
                if isinstance(built, tuple):
                    step, batch = built
                    lowered = step.compile_info(*batch)
                else:
                    lowered = built
                (plan.flops, plan.bytes_accessed, plan.peak_bytes,
                 plan.est_seconds) = analyze_lowered(lowered, n_devices)
                if hbm_bytes is not None and not plan.fits(hbm_bytes):
                    plan.error = (f"peak {plan.peak_bytes} exceeds HBM "
                                  f"{hbm_bytes}")
                    plan.est_seconds = None
            except Exception as e:  # candidate may simply not shard
                plan.error = f"{type(e).__name__}: {e}"
    finally:
        set_mesh(prev)
    return report
