"""Multiprocess DataLoader workers (shared-memory ndarray passing).

Reference: python/paddle/fluid/reader.py:312 +
fluid/dataloader/worker.py — worker subprocesses feeding batches through
shared memory so GIL-bound Python decode/augment pipelines scale.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader, Dataset, get_worker_info


class ArrayDataset(Dataset):
    def __init__(self, n=64, shape=(3, 32, 32)):
        self.x = np.arange(n * int(np.prod(shape)),
                           dtype=np.float32).reshape((n,) + shape)
        self.y = np.arange(n, dtype=np.int64)

    def __len__(self):
        return len(self.y)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


class HeavyTransformDataset(Dataset):
    """Pure-Python (GIL-bound) per-sample work — the ImageFolder decode/
    augment profile the reference's shm workers exist for."""

    def __init__(self, n=48, work=150_000):
        self.n = n
        self.work = work

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        acc = 0
        for j in range(self.work):  # deliberately holds the GIL
            acc += (i + j) % 7
        return np.full((64,), float(acc % 97), np.float32), i


class WorkerIdDataset(Dataset):
    def __len__(self):
        return 16

    def __getitem__(self, i):
        info = get_worker_info()
        wid = -1 if info is None else info.id
        return np.asarray([i, wid], np.int64)


class TestMultiprocessCorrectness:
    def test_batches_match_serial(self):
        ds = ArrayDataset(40)
        serial = [(x.numpy(), y.numpy()) for x, y in
                  DataLoader(ds, batch_size=8, num_workers=0)]
        mp = [(x.numpy(), y.numpy()) for x, y in
              DataLoader(ds, batch_size=8, num_workers=3)]
        assert len(serial) == len(mp) == 5
        for (xs, ys), (xm, ym) in zip(serial, mp):
            np.testing.assert_array_equal(xs, xm)
            np.testing.assert_array_equal(ys, ym)

    def test_shuffle_drop_last_and_reuse(self):
        ds = ArrayDataset(37)
        dl = DataLoader(ds, batch_size=8, num_workers=2, shuffle=True,
                        drop_last=True)
        for _ in range(2):  # loader is re-iterable
            seen = []
            for x, y in dl:
                assert x.shape == [8, 3, 32, 32]
                seen.extend(y.numpy().tolist())
            assert len(seen) == 32 and len(set(seen)) == 32

    def test_worker_exception_propagates(self):
        class Boom(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                if i == 5:
                    raise ValueError("bad sample 5")
                return np.zeros(4, np.float32)

        dl = DataLoader(Boom(), batch_size=4, num_workers=2)
        with pytest.raises(RuntimeError, match="bad sample 5"):
            list(dl)

    def test_worker_info_in_subprocess(self):
        dl = DataLoader(WorkerIdDataset(), batch_size=4, num_workers=2)
        wids = set()
        for b in dl:
            arr = b.numpy()
            wids.update(arr[:, 1].tolist())
        assert wids <= {0, 1} and len(wids) >= 1
        assert -1 not in wids  # info WAS set in the worker

    def test_user_collate_runs_in_parent(self):
        ds = ArrayDataset(16)
        marker = []

        def collate(samples):
            marker.append(len(samples))  # parent-side mutation visible
            xs = np.stack([s[0] for s in samples])
            return paddle.to_tensor(xs.sum(axis=(1, 2, 3)))

        out = list(DataLoader(ds, batch_size=4, num_workers=2,
                              collate_fn=collate))
        assert marker == [4, 4, 4, 4]  # ran in THIS process
        assert out[0].shape == [4]

    def test_thread_fallback_flag(self):
        ds = ArrayDataset(16)
        out = list(DataLoader(ds, batch_size=4, num_workers=2,
                              use_shared_memory=False))
        assert len(out) == 4


class TestMultiprocessThroughput:
    @pytest.mark.skipif((__import__("os").cpu_count() or 1) < 2,
                        reason="process pool cannot beat the GIL on a "
                               "single-core host — parallel speedup "
                               "needs >=2 cores")
    def test_gil_bound_pipeline_faster_than_threads(self):
        """The acceptance bar from the round-2 review: a Python-transform
        pipeline sustains a higher step rate on the process pool than on
        the thread pool (multi-core hosts; the CI box may be 1-core)."""
        ds = HeavyTransformDataset()
        nw = 4

        def run(use_shm):
            dl = DataLoader(ds, batch_size=4, num_workers=nw,
                            use_shared_memory=use_shm)
            t0 = time.perf_counter()
            n = sum(1 for _ in dl)
            return time.perf_counter() - t0, n

        t_proc, n1 = run(True)
        t_thread, n2 = run(False)
        assert n1 == n2 == 12
        # GIL serializes the thread pool; processes parallelize.
        assert t_proc < t_thread * 0.9, \
            f"mp {t_proc:.3f}s not faster than threads {t_thread:.3f}s"


class TestMultiprocessRobustness:
    def test_dead_worker_raises_not_hangs(self):
        class Killer(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                if i == 5:
                    import os
                    os._exit(13)  # simulate OOM-kill / native crash
                return np.zeros(4, np.float32)

        dl = DataLoader(Killer(), batch_size=4, num_workers=2)
        with pytest.raises(RuntimeError, match="exited abnormally"):
            list(dl)

    def test_tensor_dataset_routes_to_threads(self):
        """Samples holding jax-backed Tensors must not cross fork (the
        inherited PJRT client is not fork-safe)."""
        from paddle_tpu.io import TensorDataset
        xs = paddle.to_tensor(np.arange(32, dtype=np.float32)
                              .reshape(8, 4))
        ys = paddle.to_tensor(np.arange(8, dtype=np.int64))
        dl = DataLoader(TensorDataset([xs, ys]), batch_size=4,
                        num_workers=2)
        out = [(x.numpy(), y.numpy()) for x, y in dl]
        assert len(out) == 2
        np.testing.assert_array_equal(out[0][1], [0, 1, 2, 3])
