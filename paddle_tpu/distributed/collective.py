"""Collective communication API.

TPU-native replacement for paddle.distributed collectives (reference:
python/paddle/distributed/collective.py, communication/*, C++
ProcessGroupNCCL at distributed/collective/ProcessGroupNCCL.cc:169).

Execution model: ONE controller process drives the whole mesh (GSPMD).
There are no per-rank processes holding divergent tensors, so the eager
collectives here implement the "all ranks hold this tensor" semantics —
the exact behavior of the reference when every rank calls the collective
with equal values (which is what its own unit tests assert,
unittests/collective/collective_allreduce_api.py). Genuinely divergent
per-device data lives in SHARDED arrays, where collectives are expressed
in-program: use `paddle_tpu.distributed.shard_ops` (psum/all_gather/
all_to_all/ppermute over named mesh axes) inside shard_map/jit — those
lower to XLA collectives on ICI, replacing the c_* op zoo
(operators/collective/, 160 files).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .env import ParallelEnv, get_rank, get_world_size
from .mesh import get_mesh

__all__ = ["ReduceOp", "Group", "new_group", "get_group", "is_initialized",
           "all_reduce", "all_gather", "all_gather_object", "reduce",
           "broadcast", "broadcast_object_list", "scatter", "alltoall",
           "alltoall_single", "send", "recv", "isend", "irecv", "barrier",
           "reduce_scatter", "stream", "wait", "destroy_process_group",
           "get_backend"]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


_groups: dict = {}
_group_counter = [0]
_initialized = [False]


class Group:
    """A communication group. Binds to a mesh axis when axis_name given;
    otherwise a trivial (world) group."""

    def __init__(self, gid=0, axis_name=None, mesh=None, ranks=None):
        self.id = gid
        self.axis_name = axis_name
        self.mesh = mesh
        self._ranks = ranks

    @property
    def nranks(self):
        if self.axis_name is not None and self.mesh is not None:
            return self.mesh.get_dim_size(self.axis_name)
        if self._ranks:
            return len(self._ranks)
        return 1

    world_size = nranks

    @property
    def rank(self):
        return 0

    @property
    def ranks(self):
        return self._ranks or list(range(self.nranks))

    def get_group_rank(self, rank):
        return rank

    @property
    def process_group(self):
        return self

    def __repr__(self):
        return (f"Group(id={self.id}, axis={self.axis_name}, "
                f"nranks={self.nranks})")


def _default_group():
    if 0 not in _groups:
        _groups[0] = Group(0)
    return _groups[0]


def _nranks(group):
    return (group or _default_group()).nranks


def is_initialized():
    return _initialized[0]


def mark_initialized():
    _initialized[0] = True


def new_group(ranks=None, backend=None, timeout=None, axis_name=None):
    """reference: python/paddle/distributed/collective.py:174. Pass
    axis_name to bind the group to a mesh axis (its size = nranks)."""
    _group_counter[0] += 1
    gid = _group_counter[0]
    g = Group(gid, axis_name=axis_name, mesh=get_mesh(), ranks=ranks)
    _groups[gid] = g
    return g


def get_group(gid=0):
    return _groups.get(gid, _default_group())


def get_backend(group=None):
    return "xla"


def destroy_process_group(group=None):
    if group is None:
        _groups.clear()
        _initialized[0] = False
    else:
        _groups.pop(group.id, None)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In-place; "every rank holds `tensor`" semantics (see module doc)."""
    n = _nranks(group)
    if n == 1:
        return tensor
    if op == ReduceOp.SUM:
        tensor._rebind(tensor._value * n)
    elif op == ReduceOp.PROD:
        tensor._rebind(tensor._value ** n)
    # MAX/MIN/AVG over equal values are identity
    return tensor


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    n = _nranks(group)
    for _ in range(n):
        tensor_list.append(Tensor(tensor._value))
    return tensor_list


def all_gather_object(object_list, obj, group=None):
    n = _nranks(group)
    for _ in range(n):
        object_list.append(obj)
    return object_list


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op=op, group=group)


def broadcast(tensor, src=0, group=None, sync_op=True):
    return tensor


def broadcast_object_list(object_list, src=0, group=None):
    return object_list


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if tensor_list:
        tensor._rebind(tensor_list[0]._value)
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None,
             sync_op=True):
    """Equal-rank semantics: rank 0 receives every rank's chunk 0."""
    outs = [Tensor(in_tensor_list[0]._value)
            for _ in range(len(in_tensor_list))]
    if out_tensor_list is None:
        return outs
    if len(out_tensor_list) == 0:
        out_tensor_list.extend(outs)
    else:
        for o, v in zip(out_tensor_list, outs):
            o._rebind(v._value)
    return out_tensor_list


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    n = _nranks(group)
    if n == 1:
        val = in_tensor._value
    else:
        first = in_tensor._value.shape[0] // n
        chunk0 = in_tensor._value[:first]
        val = jnp.concatenate([chunk0] * n, axis=0)
    if out_tensor is not None:
        out_tensor._rebind(val)
        return out_tensor
    return Tensor(val)


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    n = _nranks(group)
    if tensor_list:
        src = tensor_list[0]._value
    else:
        src = tensor._value[:tensor._value.shape[0] // max(n, 1)]
    if op == ReduceOp.SUM and n > 1:
        src = src * n
    tensor._rebind(src)
    return tensor


def send(tensor, dst=0, group=None, sync_op=True):
    raise NotImplementedError(
        "cross-rank p2p does not exist in the single-controller GSPMD "
        "regime; use distributed.shard_ops.ppermute inside a compiled "
        "program for on-mesh p2p (the replacement for partial_send/recv, "
        "reference: operators/collective/partial_send_op.cc)")


def recv(tensor, src=0, group=None, sync_op=True):
    return send(tensor, src, group, sync_op)


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group)


class _Done:
    def wait(self):
        return

    def is_completed(self):
        return True


def barrier(group=None):
    jax.effects_barrier()
    return _Done()


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor):
        jax.block_until_ready(tensor._value)
    return None


class stream:
    """paddle.distributed.stream parity — stream-level knobs collapse
    under PJRT async execution (SURVEY.md §7)."""

    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    alltoall = staticmethod(alltoall)
    reduce_scatter = staticmethod(reduce_scatter)
    broadcast = staticmethod(broadcast)
    scatter = staticmethod(scatter)
