"""fleet.meta_parallel parity surface (reference:
python/paddle/distributed/fleet/meta_parallel/__init__.py)."""
from .pp_layers import (  # noqa: F401
    LayerDesc, SharedLayerDesc, PipelineLayer, SegmentLayers,
    PipelineParallel)
from .mp_layers import (  # noqa: F401
    VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
    ParallelCrossEntropy)
from ..parallel import DataParallel  # noqa: F401


class TensorParallel:
    """Wrapper marker (reference: meta_parallel/tensor_parallel.py) — the
    mp layers already carry their shardings; wrapping is identity."""

    def __new__(cls, model, hcg=None, **kwargs):
        return model


class ShardingParallel:
    def __new__(cls, model, hcg=None, **kwargs):
        return model


def get_rng_state_tracker():
    from .utils import RNGStatesTracker
    return RNGStatesTracker.global_tracker()
