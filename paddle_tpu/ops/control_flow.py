"""Data-dependent control flow: cond / while_loop / scan / switch_case.

TPU-native replacement for the reference's structural control-flow ops
(paddle/fluid/operators/controlflow/while_op.cc:86 WhileOp — runs a
sub-Block via Executor per iteration; conditional_block_op.cc:43;
Python builders python/paddle/fluid/layers/control_flow.py:1214
while_loop, python/paddle/static/nn/control_flow.py:874 cond).

Two execution regimes:
- Eager: predicates are concrete, so `cond`/`case`/`switch_case` just
  evaluate the chosen Python branch and `while_loop` runs a Python loop.
  Every op inside lands on the autograd tape — grad-through-while works
  exactly like the reference's dygraph control flow.
- Under `jit.to_static` tracing (or any jax trace): predicates are
  tracers; the same calls lower to `lax.cond` / `lax.while_loop` /
  `lax.switch`, producing ONE compiled XLA program with native control
  flow — no AST rewriting (the reference's dy2static machinery) needed.
`scan` always lowers to `lax.scan` (differentiable in both regimes; the
TPU-idiomatic replacement for the reference's static RNN / TensorArray
loops at operators/controlflow/recurrent_op.cc).
"""
from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..core.dispatch import OpDef
from ..core.pytree import (flatten_tensors as _flatten,
                           unflatten_tensors as _unflatten)

__all__ = ["cond", "case", "switch_case", "while_loop", "scan"]


def _is_tracer(v):
    return isinstance(v, jax.core.Tracer)


def _wrap_branch(fn, operands_spec):
    """(leaf-value list) -> (leaf-value tuple) adapter around a Python
    branch fn taking/returning Tensors. Captures the output structure in
    the returned state dict when traced (lax traces every branch, so it
    is always populated before use)."""
    state: dict = {}

    def run(vals):
        wrapped = [Tensor(v, stop_gradient=True) for v in vals]
        args = _unflatten(operands_spec, wrapped)
        out = fn(*args)
        leaves: list[Tensor] = []
        state["spec"] = _flatten(out, leaves)
        return tuple(t._value for t in leaves)

    return run, state


def _pred_value(pred):
    return pred._value if isinstance(pred, Tensor) else pred


def _as_pred_tensor(pred):
    return pred if isinstance(pred, Tensor) else Tensor(_pred_value(pred))


def cond(pred, true_fn=None, false_fn=None, operands=(), name=None,
         return_names=None):
    """paddle.static.nn.cond parity. Eager: Python branch; traced:
    lax.cond (both branches compiled into the program)."""
    operands = tuple(operands)
    pv = _pred_value(pred)
    if not _is_tracer(pv):
        return true_fn(*operands) if bool(pv) else false_fn(*operands)

    leaves: list[Tensor] = []
    op_spec = _flatten(list(operands), leaves)
    true_run, t_state = _wrap_branch(true_fn, op_spec)
    false_run, f_state = _wrap_branch(false_fn, op_spec)

    def fwd(pred_val, *op_vals):
        return jax.lax.cond(
            jnp.asarray(pred_val).astype(bool).reshape(()),
            lambda vs: true_run(list(vs)),
            lambda vs: false_run(list(vs)),
            tuple(op_vals))

    out = apply_op(OpDef(f"cond::{getattr(true_fn, '__name__', 'fn')}",
                         fwd), _as_pred_tensor(pred), *leaves)
    outs = out if isinstance(out, tuple) else (out,)
    spec = t_state.get("spec") or f_state.get("spec")
    return _unflatten(spec, list(outs))


def case(pred_fn_pairs, default=None, name=None):
    """paddle.static.nn.case parity: first true predicate wins; default
    (or the last branch) when none is true."""
    pairs = list(pred_fn_pairs)
    if not pairs:
        raise ValueError("pred_fn_pairs must not be empty")
    preds = [_pred_value(p) for p, _ in pairs]
    if not any(_is_tracer(p) for p in preds):
        for p, fn in pairs:
            if bool(_pred_value(p)):
                return fn()
        return default() if default is not None else pairs[-1][1]()
    # traced: chain of lax.cond
    (p0, fn0), rest = pairs[0], pairs[1:]

    def else_fn():
        if rest:
            return case(rest, default)
        return default() if default is not None else fn0()

    return cond(_as_pred_tensor(p0), lambda: fn0(), else_fn)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """paddle.static.nn.switch_case parity. Traced: lax.switch."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = list(enumerate(branch_fns))
    iv = _pred_value(branch_index)
    if not _is_tracer(iv):
        table = dict(items)
        i = int(iv)
        if i in table:
            return table[i]()
        return default() if default is not None else items[-1][1]()

    keys = [k for k, _ in items]
    fns = [fn for _, fn in items]
    if default is not None:
        fns.append(default)
    def_pos = len(fns) - 1  # unmatched -> default (or last branch)
    runs, states = [], []
    for fn in fns:
        run, st = _wrap_branch(lambda _fn=fn: _fn(), ("L", []))
        runs.append(lambda vs, _r=run: _r([]))
        states.append(st)

    def fwd(idx_val):
        sel = jnp.full((), def_pos, dtype=jnp.int32)
        for j, k in enumerate(keys):
            sel = jnp.where(jnp.asarray(idx_val).reshape(()) == k, j, sel)
        return jax.lax.switch(sel, runs, ())

    out = apply_op(OpDef("switch_case", fwd),
                   _as_pred_tensor(branch_index))
    outs = out if isinstance(out, tuple) else (out,)
    spec = next(s["spec"] for s in states if "spec" in s)
    return _unflatten(spec, list(outs))


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """paddle.static.nn.while_loop parity (reference:
    python/paddle/fluid/layers/control_flow.py:1214).

    Eager: Python loop — every iteration's ops are tape-recorded, so
    gradients flow through the unrolled loop like the reference's
    dygraph `while`. Traced: lax.while_loop (forward-only, matching
    XLA's while semantics)."""
    loop_vars = list(loop_vars)
    leaves: list[Tensor] = []
    spec = _flatten(loop_vars, leaves)

    first = _pred_value(cond_fn(*loop_vars))
    if not _is_tracer(first):
        keep = bool(first)
        while keep:
            out = body_fn(*loop_vars)
            loop_vars = list(out) if isinstance(out, (list, tuple)) \
                else [out]
            keep = bool(_pred_value(cond_fn(*loop_vars)))
        return loop_vars

    def fwd(*vals):
        def c(vs):
            wrapped = [Tensor(v, stop_gradient=True) for v in vs]
            args = _unflatten(spec, wrapped)
            return jnp.asarray(
                _pred_value(cond_fn(*args))).astype(bool).reshape(())

        def b(vs):
            wrapped = [Tensor(v, stop_gradient=True) for v in vs]
            args = _unflatten(spec, wrapped)
            out = body_fn(*args)
            out = list(out) if isinstance(out, (list, tuple)) else [out]
            out_leaves: list[Tensor] = []
            _flatten(out, out_leaves)
            return tuple(t._value for t in out_leaves)

        return jax.lax.while_loop(c, b, tuple(vals))

    out = apply_op(OpDef("while_loop", fwd, nondiff=True), *leaves)
    outs = out if isinstance(out, tuple) else (out,)
    return _unflatten(spec, list(outs))


def scan(fn, init, xs=None, length=None, reverse=False, name=None):
    """lax.scan exposed at the paddle level — the TPU-idiomatic
    replacement for the reference's static RNN (recurrent_op.cc) and
    TensorArray loops. fn(carry, x) -> (carry, y). Differentiable in
    both eager (tape backward runs the jax.vjp of the whole scan) and
    traced modes. In eager mode only init/xs are differentiated inputs —
    tensors merely closed over by fn are baked as constants; thread them
    through the carry instead."""
    carry_leaves: list[Tensor] = []
    carry_spec = _flatten(init, carry_leaves)
    xs_leaves: list[Tensor] = []
    xs_spec = _flatten(xs, xs_leaves)
    n_carry = len(carry_leaves)
    state: dict = {}

    def fwd(*vals):
        c_vals = vals[:n_carry]
        x_vals = vals[n_carry:]

        def body(c, x):
            cw = [Tensor(v, stop_gradient=True) for v in c]
            xw = [Tensor(v, stop_gradient=True) for v in (x or ())]
            carry = _unflatten(carry_spec, cw)
            xarg = _unflatten(xs_spec, xw)
            nc, y = fn(carry, xarg)
            ncl: list[Tensor] = []
            state["carry_spec"] = _flatten(nc, ncl)
            yl: list[Tensor] = []
            state["y_spec"] = _flatten(y, yl)
            state["n_y"] = len(yl)
            return (tuple(t._value for t in ncl),
                    tuple(t._value for t in yl))

        final, ys = jax.lax.scan(body, tuple(c_vals), tuple(x_vals)
                                 if x_vals else None,
                                 length=length, reverse=reverse)
        return tuple(final) + tuple(ys)

    out = apply_op(OpDef(f"scan::{getattr(fn, '__name__', 'fn')}", fwd),
                   *carry_leaves, *xs_leaves)
    outs = out if isinstance(out, tuple) else (out,)
    n_final = len(outs) - state["n_y"]
    final = _unflatten(state["carry_spec"], list(outs[:n_final]))
    ys = _unflatten(state["y_spec"], list(outs[n_final:]))
    return final, ys
