"""Single-XLA-program training step.

This is SURVEY.md §7's north star made concrete: forward + backward +
optimizer update compiled into ONE XLA computation with donated
parameter/state buffers. The reference needs InterpreterCore + eager
autograd + per-param optimizer ops; here the whole step is one
`PjRtLoadedExecutable` — XLA fuses, schedules collectives over the mesh
axes, and reuses parameter memory in place.

Used by bench.py, __graft_entry__.dryrun_multichip, and available as
`paddle_tpu.jit.compile_train_step` for users.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from ..core import random as random_mod
from ..core import dtype as dtypes

__all__ = ["compile_train_step", "CompiledTrainStep"]


class CompiledTrainStep:
    """Owns the functionalized (params, opt-state) pytree and the jitted
    step(params, states, gstate, key, *batch) -> (loss, new_params,
    new_states, new_gstate)."""

    def __init__(self, loss_fn, model, optimizer, donate=True,
                 in_shardings=None, accumulate_steps=None):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        # gradient merge (reference distributed_strategy.proto:81
        # GradientMergeConfig): k micro-batches scanned INSIDE the one
        # compiled step, optimizer applied once on the averaged grads.
        # Explicit arg wins; else the fleet strategy tag on the optimizer
        self.accumulate_steps = int(
            accumulate_steps
            if accumulate_steps is not None
            else getattr(optimizer, "_gradient_merge_k", 1) or 1)
        self.accumulate_avg = bool(
            getattr(optimizer, "_gradient_merge_avg", True))
        self.params = [p for p in model.parameters()
                       if (p.trainable if isinstance(p, Parameter)
                           else not p.stop_gradient)]
        self.buffers = [b for _, b in model.named_buffers()]
        self.state_tensors = self.params + self.buffers
        self.n_params = len(self.params)
        self.states = [dict(optimizer._state_for(p)) for p in self.params]
        # live global state (beta-pow counters etc.) when the optimizer
        # already has one — a rebuild mid-training (or after a
        # checkpoint load) must not reset bias correction to step 0
        live_g = getattr(optimizer, "_gstate", None)
        self.gstate = (dict(live_g) if live_g else
                       {k: jnp.asarray(v) for k, v in
                        optimizer._global_state_spec().items()})
        self._grad_clip = optimizer._grad_clip
        decay = optimizer._decay if not getattr(optimizer, "_decoupled",
                                                False) else 0.0
        extras = optimizer._per_param_extra(self.params)
        rule = optimizer._apply_rule
        advance = optimizer._advance_global
        n_p = self.n_params
        n_b = len(self.buffers)
        state_tensors = self.state_tensors
        loss_fn_ = loss_fn

        accum = self.accumulate_steps

        def step(param_vals, buffer_vals, states, gstate, lr, key,
                 *batch_vals):
            def loss_of(pvals, bufs, mb_vals, mb_key):
                originals = [t._value for t in state_tensors]
                random_mod.push_trace_key(mb_key)
                try:
                    for t, v in zip(state_tensors,
                                    list(pvals) + list(bufs)):
                        t._value = v
                    batch = [Tensor(b) for b in mb_vals]
                    out = loss_fn_(*batch)
                    loss_val = out._value if isinstance(out, Tensor) \
                        else out
                    new_buf = tuple(t._value
                                    for t in state_tensors[n_p:])
                    return loss_val.astype(jnp.float32), new_buf
                finally:
                    random_mod.pop_trace_key()
                    for t, v in zip(state_tensors, originals):
                        t._value = v

            if accum > 1:
                # micro-batch scan: leading batch dim splits into
                # (accum, per_micro); f32 grad accumulators; one
                # optimizer application on the merged grads. Positional
                # batch args must lead with the batch dim; 0-d scalars
                # are broadcast to every micro-batch unchanged
                split = []
                for ai, b in enumerate(batch_vals):
                    if b.ndim == 0:
                        split.append(None)
                        continue
                    if b.shape[0] % accum:
                        raise ValueError(
                            f"batch arg {ai}: leading dim {b.shape[0]} "
                            f"not divisible by accumulate_steps={accum}")
                    split.append(b.reshape(
                        (accum, b.shape[0] // accum) + b.shape[1:]))

                def micro(carry, xs):
                    acc, bufs = carry
                    idx, mb = xs
                    full = [b if s is None else m
                            for b, s, m in zip(batch_vals, split, mb)]
                    mb_key = jax.random.fold_in(key, idx)
                    (l, nb), g = jax.value_and_grad(
                        loss_of, has_aux=True)(
                            list(param_vals), bufs, full, mb_key)
                    acc = [a + gi.astype(jnp.float32)
                           for a, gi in zip(acc, g)]
                    return (acc, nb), l

                acc0 = [jnp.zeros(p.shape, jnp.float32)
                        for p in param_vals]
                mb_xs = [jnp.zeros((accum,)) if s is None else s
                         for s in split]
                (gsum, new_bufs), losses = jax.lax.scan(
                    micro, (acc0, tuple(buffer_vals)),
                    (jnp.arange(accum), mb_xs))
                # avg=True (default): mean over micro-batches == the
                # full-batch grad; avg=False keeps the reference's sum
                # semantics (GradientMergeConfig.avg)
                denom = accum if self.accumulate_avg else 1
                grads = [(g / denom).astype(p.dtype)
                         for g, p in zip(gsum, param_vals)]
                loss = jnp.mean(losses)
            else:
                (loss, new_bufs), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(
                        list(param_vals), list(buffer_vals),
                        list(batch_vals), key)
            if self._grad_clip is not None:
                from ..nn.clip import apply_grad_clip_values
                grads = apply_grad_clip_values(self._grad_clip, grads)
            new_params, new_states = [], []
            g2 = dict(gstate)
            for i, (p, g, s) in enumerate(zip(param_vals, grads, states)):
                if decay:
                    g = g + decay * p
                optimizer._cur_extra = (extras[i] if extras is not None
                                        else None)
                np_, ns = rule(p, g, s, g2, lr)
                new_params.append(np_)
                new_states.append(ns)
            g2 = advance(g2)
            return loss, new_params, list(new_bufs), new_states, g2

        # ZeRO offload: donated pinned_host state buffers trip
        # unimplemented hbm-to-hbm DMAs in the TPU AOT path — keep
        # params/buffers donated but not the host-resident states
        if getattr(optimizer, "_offload", False):
            donate_args = (0, 1) if donate else ()
        else:
            donate_args = (0, 1, 2, 3) if donate else ()
        self._step = jax.jit(step, donate_argnums=donate_args)
        self._target_mesh = self._harmonize_placements()

    def _harmonize_placements(self):
        """Co-locate params/buffers/optimizer state on one device set.

        One jitted program cannot consume arrays committed to different
        device sets (a model built while a mesh was active mixes 8-device
        and 1-device arrays the moment the mesh context ends). Target:
        the active mesh if set, else the mesh the parameters already live
        on, else the default device. Values already holding a
        NamedSharding on the target mesh keep their layout (TP shards
        survive); stragglers are replicated onto it."""
        from jax.sharding import NamedSharding, PartitionSpec
        from ..distributed.mesh import get_mesh
        pm = get_mesh()
        target = pm.jax_mesh if pm is not None else None
        if target is None:
            for t in self.state_tensors:
                sh = getattr(t._value, "sharding", None)
                if isinstance(sh, NamedSharding) and sh.mesh.size > 1:
                    target = sh.mesh
                    break
        if target is None:
            dev = jax.devices()[0]

            def place(v):
                devs = getattr(getattr(v, "sharding", None),
                               "device_set", None)
                if devs is not None and devs != {dev}:
                    return jax.device_put(v, dev)
                return v
        else:
            rep = NamedSharding(target, PartitionSpec())

            def place(v):
                sh = getattr(v, "sharding", None)
                if isinstance(sh, NamedSharding) and sh.mesh == target:
                    return v
                return jax.device_put(v, rep)

        for t in self.state_tensors:
            t._rebind(place(t._value))
        self.states = [{k: place(v) for k, v in s.items()}
                       for s in self.states]
        self.gstate = {k: place(v) for k, v in self.gstate.items()}
        return target

    def _place_batch(self, v):
        """Batch values must join the step's device set too; anything the
        caller didn't shard (via dist.shard_batch) gets replicated."""
        from jax.sharding import NamedSharding, PartitionSpec
        target = self._target_mesh
        if target is None:
            return v
        sh = getattr(v, "sharding", None)
        if isinstance(sh, NamedSharding) and sh.mesh == target:
            return v
        return jax.device_put(v, NamedSharding(target, PartitionSpec()))

    def __call__(self, *batch):
        batch_vals = [self._place_batch(
            b._value if isinstance(b, Tensor) else jnp.asarray(b))
            for b in batch]
        # host-side scalars/keys: jit transfers them with the call; an
        # eager jnp.asarray here would cost a tunnel round-trip per step
        lr = np.float32(self.optimizer.get_lr())
        key = random_mod.next_key_host()
        p_vals = [p._value for p in self.params]
        b_vals = [b._value for b in self.buffers]
        loss, new_p, new_b, new_s, new_g = self._step(
            p_vals, b_vals, self.states, self.gstate, lr, key,
            *batch_vals)
        for p, v in zip(self.params, new_p):
            p._rebind(v)
        for b, v in zip(self.buffers, new_b):
            b._rebind(v)
        off = getattr(self.optimizer, "_offload_put", None)
        if off is not None:  # ZeRO offload: states back to host memory
            new_s = [off(s) for s in new_s]
        self.states = new_s
        self.gstate = new_g
        # keep the eager optimizer's view coherent for state_dict()
        for p, s in zip(self.params, self.states):
            self.optimizer._accumulators[id(p)] = s
        self.optimizer._gstate = self.gstate
        if self.optimizer._lr_scheduler is not None:
            pass  # scheduler stepping stays the caller's choice
        return Tensor(loss)

    def compile_info(self, *batch):
        """Lower + return the compiled HLO text (for inspection)."""
        batch_vals = [self._place_batch(
            b._value if isinstance(b, Tensor) else jnp.asarray(b))
            for b in batch]
        lr = jnp.asarray(0.0, jnp.float32)
        key = random_mod.next_key()
        p_vals = [p._value for p in self.params]
        b_vals = [b._value for b in self.buffers]
        return self._step.lower(p_vals, b_vals, self.states, self.gstate,
                                lr, key, *batch_vals)


def compile_train_step(loss_fn, model, optimizer, donate=True,
                       accumulate_steps=None):
    """loss_fn(*batch_tensors) -> scalar loss Tensor, closing over
    `model`. Returns a callable: step(*batch) -> loss.

    accumulate_steps=k scans k micro-batches (leading batch dim split
    k ways) inside the one compiled program — gradient merge, reference
    distributed_strategy.proto:81."""
    return CompiledTrainStep(loss_fn, model, optimizer, donate=donate,
                             accumulate_steps=accumulate_steps)
