"""Hybrid-parallel topology.

TPU-native replacement for CommunicateTopology/HybridCommunicateGroup
(reference: python/paddle/distributed/fleet/base/topology.py:53,139).
The reference builds per-axis NCCL groups over process ranks; here the
axes are dimensions of ONE jax Mesh — ["data", "pipe", "sharding",
"sep", "model"], adding the "sep" sequence axis the reference lacks —
and a "group" is a named mesh axis handle.
"""
from __future__ import annotations

import collections
import itertools

import numpy as np
import jax

from ..mesh import ProcessMesh, set_mesh
from ..collective import Group, new_group

__all__ = ["CommunicateTopology", "HybridCommunicateGroup"]

_AXIS_ALIAS = {"data": "dp", "pipe": "pp", "sharding": "sharding",
               "model": "mp", "sep": "sep", "expert": "ep"}


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("data", "pipe", "sharding",
                                           "sep", "model"),
                 dims=(1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = collections.namedtuple(
            "Coordinate", self._parallel_names)
        ranges = [range(d) for d in self._dims]
        all_coords = [self.coordinate(*c) for c in
                      itertools.product(*ranges)]
        self._coord2rank = {c: i for i, c in enumerate(all_coords)}
        self._rank2coord = {i: c for c, i in self._coord2rank.items()}

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return int(np.prod(self._dims))

    def get_rank(self, **args):
        return self._coord2rank[self.coordinate(**args)]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        return [r for c, r in self._coord2rank.items() if c[axis] == index]

    def get_comm_list(self, axis_name):
        axis = self._parallel_names.index(axis_name)
        other_ranges = [range(d) for i, d in enumerate(self._dims)
                        if i != axis]
        lists = []
        for other in itertools.product(*other_ranges):
            ranks = []
            for v in range(self._dims[axis]):
                coord = list(other)
                coord.insert(axis, v)
                ranks.append(self._coord2rank[self.coordinate(*coord)])
            lists.append(ranks)
        return lists


class HybridCommunicateGroup:
    """reference: fleet/base/topology.py:139. Owns the global Mesh."""

    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        self._dp_degree = topology.get_dim("data")
        self._pp_degree = topology.get_dim("pipe")
        self._sharding_degree = topology.get_dim("sharding")
        self._mp_degree = topology.get_dim("model")
        self._sep_degree = (topology.get_dim("sep")
                            if "sep" in topology.get_hybrid_group_names()
                            else 1)
        self._ep_degree = (topology.get_dim("expert")
                           if "expert" in topology.get_hybrid_group_names()
                           else 1)
        self.global_rank = 0
        world = topology.world_size()
        n_dev = len(jax.devices())
        if world > n_dev:
            raise ValueError(
                f"topology needs {world} devices, only {n_dev} visible; "
                f"set XLA_FLAGS=--xla_force_host_platform_device_count "
                f"for virtual-device testing")
        dims = [self._dp_degree, self._pp_degree, self._sharding_degree,
                self._sep_degree, self._mp_degree, self._ep_degree]
        self._mesh = ProcessMesh(
            shape=dims,
            dim_names=["dp", "pp", "sharding", "sep", "mp", "ep"])
        set_mesh(self._mesh)
        self._dp_group = new_group(axis_name="dp")
        self._pp_group = new_group(axis_name="pp")
        self._sharding_group = new_group(axis_name="sharding")
        self._sep_group = new_group(axis_name="sep")
        self._mp_group = new_group(axis_name="mp")
        self._ep_group = new_group(axis_name="ep")

    @property
    def mesh(self):
        return self._mesh

    def get_parallel_mode(self):
        if self._pp_degree > 1:
            return "pipeline"
        if self._mp_degree > 1 or self._sep_degree > 1:
            return "model"
        if self._sharding_degree > 1:
            return "sharding"
        return "data"

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return 0

    # data parallel
    def get_data_parallel_rank(self):
        return 0

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._dp_group

    def get_data_parallel_group_src_rank(self):
        return 0

    # model (tensor) parallel
    def get_model_parallel_rank(self):
        return 0

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._mp_group

    def get_model_parallel_group_src_rank(self):
        return 0

    # pipeline
    @property
    def stage_id(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_pipe_parallel_rank(self):
        return 0

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._pp_group

    def get_p2p_groups(self):
        return None

    # sharding
    def get_sharding_parallel_rank(self):
        return 0

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sharding_parallel_group_src_rank(self):
        return 0

    # sequence (new)
    def get_sep_parallel_rank(self):
        return 0

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_group(self):
        return self._sep_group

    # expert parallel
    def get_expert_parallel_rank(self):
        return 0

    def get_expert_parallel_world_size(self):
        return self._ep_degree

    def get_expert_parallel_group(self):
        return self._ep_group

    def get_check_parallel_group(self, *a, **kw):
        return self._mp_group

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank(
            data=0, pipe=stage_id, sharding=0, sep=0, model=0)
