"""paddle.metric parity: streaming eval metrics.

Reference: python/paddle/metric/metrics.py (Metric base :33, Accuracy
:187, Precision :338, Recall :468, Auc) — numpy accumulators on host,
tensor `compute` stages that can run inside the compiled eval step.
"""
from __future__ import annotations

import abc

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _to_np(x):
    if isinstance(x, Tensor):
        return x.numpy()
    return np.asarray(x)


class Metric(metaclass=abc.ABCMeta):
    """Base class (reference: metrics.py:33). Lifecycle:
    compute(pred, label) -> per-batch tensor stats (device side),
    update(stats) -> host accumulation, accumulate() -> scalar(s),
    reset() between epochs."""

    def __init__(self):
        pass

    @abc.abstractmethod
    def reset(self):
        raise NotImplementedError

    @abc.abstractmethod
    def update(self, *args):
        raise NotImplementedError

    @abc.abstractmethod
    def accumulate(self):
        raise NotImplementedError

    @abc.abstractmethod
    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        """Default: pass predictions/labels straight to update."""
        return args


class Accuracy(Metric):
    """Top-k accuracy (reference: metrics.py:187)."""

    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._init_name(name)
        self.reset()

    def _init_name(self, name):
        name = name or "acc"
        if self.maxk != 1:
            self._name = [f"{name}_top{k}" for k in self.topk]
        else:
            self._name = [name]

    def compute(self, pred, label, *args):
        """pred: [N, C] scores; label: [N] or [N, 1] int, or [N, C]
        one-hot. Returns [N, maxk] float 'correct' mask."""
        pred_np = _to_np(pred)
        label_np = _to_np(label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] > 1:
            label_np = np.argmax(label_np, axis=-1)
        label_np = label_np.reshape(label_np.shape[0], -1)[:, 0]
        idx = np.argsort(-pred_np, axis=-1)[:, :self.maxk]   # [N, maxk]
        correct = (idx == label_np[:, None]).astype("float32")
        return correct

    def update(self, correct, *args):
        correct = _to_np(correct)
        accs = []
        for k in self.topk:
            num = float(correct[:, :k].sum())
            self.total[self.topk.index(k)] += num
            self.count[self.topk.index(k)] += correct.shape[0]
            accs.append(num / correct.shape[0])
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / c if c > 0 else 0.0
               for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


def _binary_preds(preds):
    """[N], [N,1] sigmoid or [N,2] softmax -> positive-class prob [N]."""
    preds = _to_np(preds).astype("float64")
    if preds.ndim > 1 and preds.shape[-1] == 2:
        return preds[:, 1]
    return preds.reshape(-1)


class Precision(Metric):
    """Binary precision = tp / (tp + fp) (reference: metrics.py:338)."""

    def __init__(self, name="precision", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _binary_preds(preds)
        labels = _to_np(labels).reshape(-1)
        pos = preds > 0.5
        self.tp += int(np.sum(pos & (labels == 1)))
        self.fp += int(np.sum(pos & (labels != 1)))

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap != 0 else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall = tp / (tp + fn) (reference: metrics.py:468)."""

    def __init__(self, name="recall", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _binary_preds(preds)
        labels = _to_np(labels).reshape(-1)
        pos = preds > 0.5
        self.tp += int(np.sum(pos & (labels == 1)))
        self.fn += int(np.sum(~pos & (labels == 1)))

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        ar = self.tp + self.fn
        return float(self.tp) / ar if ar != 0 else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via threshold histogram (reference: metrics.py Auc;
    same bucketed streaming algorithm as the auc op)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc",
                 *args, **kwargs):
        super().__init__()
        self._curve = curve
        self._num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _binary_preds(preds)
        labels = _to_np(labels).reshape(-1)
        buckets = np.clip((preds * self._num_thresholds).astype("int64"),
                          0, self._num_thresholds)
        pos = labels == 1
        n = self._num_thresholds + 1
        self._stat_pos += np.bincount(buckets[pos], minlength=n)
        self._stat_neg += np.bincount(buckets[~pos], minlength=n)

    def reset(self):
        self._stat_pos = np.zeros(self._num_thresholds + 1, dtype="int64")
        self._stat_neg = np.zeros(self._num_thresholds + 1, dtype="int64")

    @staticmethod
    def trapezoid_area(x1, x2, y1, y2):
        return abs(x1 - x2) * (y1 + y2) / 2.0

    def accumulate(self):
        # vectorized trapezoid sum over descending thresholds
        tp = np.cumsum(self._stat_pos[::-1].astype("float64"))
        fp = np.cumsum(self._stat_neg[::-1].astype("float64"))
        tot_pos, tot_neg = tp[-1], fp[-1]
        if tot_pos == 0.0 or tot_neg == 0.0:
            return 0.0
        prev_tp = np.concatenate([[0.0], tp[:-1]])
        prev_fp = np.concatenate([[0.0], fp[:-1]])
        auc = float(np.sum((fp - prev_fp) * (tp + prev_tp) / 2.0))
        return auc / tot_pos / tot_neg

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (reference:
    python/paddle/metric/metrics.py accuracy op wrapper)."""
    from ..ops import creation
    pred = _to_np(input)
    lab = _to_np(label).reshape(pred.shape[0], -1)[:, 0]
    idx = np.argsort(-pred, axis=-1)[:, :k]
    acc = float((idx == lab[:, None]).any(axis=1).mean())
    return creation.to_tensor(np.asarray([acc], dtype="float32"))
