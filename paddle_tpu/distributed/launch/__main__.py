"""CLI: python -m paddle_tpu.distributed.launch [opts] script.py [args].

Reference: python/paddle/distributed/launch/main.py:18 /
__main__.py — same flag names where they still make sense on TPU
(--nnodes, --nproc_per_node, --master, --log_dir); --devices and
--gpus are accepted for compatibility and ignored (device assignment is
PJRT's job on TPU hosts).
"""
from __future__ import annotations

import argparse
import sys

from . import launch


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch")
    ap.add_argument("--nnodes", type=int, default=1)
    ap.add_argument("--node_rank", type=int, default=0)
    ap.add_argument("--nproc_per_node", type=int, default=1)
    ap.add_argument("--master", default=None,
                    help="coordinator host:port (default: auto local)")
    ap.add_argument("--log_dir", default=None)
    ap.add_argument("--devices", "--gpus", "--xpus", default=None,
                    help="accepted for reference compatibility; ignored")
    ap.add_argument("--job_id", default="default")
    ap.add_argument("script")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    rc = launch(args.script, args.script_args,
                nproc_per_node=args.nproc_per_node, nnodes=args.nnodes,
                node_rank=args.node_rank, master=args.master,
                log_dir=args.log_dir)
    sys.exit(rc)


if __name__ == "__main__":
    main()
