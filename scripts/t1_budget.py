"""Tier-1 wall-clock budget check over a pytest log.

The tier-1 gate runs `pytest tests/ -q -m 'not slow'` under a hard
`timeout 870` — a suite that creeps past it is killed mid-run and
every test after the cut silently stops counting. This script makes
the creep VISIBLE before it kills a run: point it at a tier-1 log
(`/tmp/_t1.log`, the `tee` target in ROADMAP.md's verify line) and it

- reads the pytest trailer (`... in 806.42s`) as the measured suite
  time, failing (exit 1) when it exceeds the budget (default 840s —
  30s of headroom under the 870s kill);
- aggregates any `--durations=N` lines (`12.34s call
  tests/test_x.py::test_y`) into per-FILE totals and prints the top
  offenders, so "which lane do I trim" has an answer;
- with `--new-lane S` adds a projected new test lane on top of the
  measured time (the pre-merge question: "does my PR's lane still
  fit?").

    python scripts/t1_budget.py /tmp/_t1.log
    python scripts/t1_budget.py /tmp/_t1.log --budget 840 --top 10
    python scripts/t1_budget.py /tmp/_t1.log --new-lane 25

Exit codes: 0 within budget, 1 over budget, 2 unparseable log.
Pure text parsing — safe to run anywhere, wired into tier-1 itself
as a fast unit lane (tests/test_t1_budget.py) over synthetic logs.
"""
from __future__ import annotations

import argparse
import re
import sys
from typing import Dict, List, Optional, Tuple

# `== 123 passed, 4 failed, 1 skipped in 806.42s (0:13:26) ==` and the
# bare `no tests ran in 0.01s` both end with "in <seconds>s"
TRAILER_RE = re.compile(
    r"\bin (\d+(?:\.\d+)?)s(?: \(\d+:\d+:\d+\))?\s*=*\s*$")
# `12.34s call     tests/test_x.py::TestY::test_z` (--durations=N)
DURATION_RE = re.compile(
    r"^\s*(\d+(?:\.\d+)?)s\s+(call|setup|teardown)\s+"
    r"([^\s:]+)::(\S+)")


def parse_log(text: str) -> Tuple[Optional[float], Dict[str, float]]:
    """-> (trailer seconds or None, per-file duration totals)."""
    total: Optional[float] = None
    per_file: Dict[str, float] = {}
    for line in text.splitlines():
        m = DURATION_RE.match(line)
        if m:
            secs, _phase, path = float(m[1]), m[2], m[3]
            per_file[path] = per_file.get(path, 0.0) + secs
            continue
        m = TRAILER_RE.search(line)
        if m:
            total = float(m[1])     # last trailer wins (reruns)
    return total, per_file


def top_offenders(per_file: Dict[str, float], n: int
                  ) -> List[Tuple[str, float]]:
    return sorted(per_file.items(), key=lambda kv: -kv[1])[:n]


def check_budget(text: str, budget: float, new_lane: float = 0.0,
                 top: int = 8) -> Tuple[int, str]:
    """-> (exit code, human report)."""
    total, per_file = parse_log(text)
    lines: List[str] = []
    if total is None:
        return 2, ("t1_budget: no pytest trailer ('in <N>s') found — "
                   "is this a tier-1 log?")
    projected = total + new_lane
    verdict = "OK" if projected <= budget else "OVER BUDGET"
    lines.append(
        f"t1_budget: measured {total:.1f}s"
        + (f" + new lane {new_lane:.1f}s = {projected:.1f}s"
           if new_lane else "")
        + f" vs budget {budget:.0f}s -> {verdict}"
        + (f" ({budget - projected:+.1f}s headroom)"))
    if per_file:
        lines.append(f"  slowest files (of {len(per_file)} timed):")
        for path, secs in top_offenders(per_file, top):
            lines.append(f"    {secs:8.1f}s  {path}")
        accounted = sum(per_file.values())
        lines.append(f"  durations account for {accounted:.1f}s "
                     f"({100.0 * accounted / max(total, 1e-9):.0f}% "
                     "of the trailer; run with --durations=0 for "
                     "full attribution)")
    return (0 if projected <= budget else 1), "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="fail when the tier-1 suite outgrows its "
        "wall-clock budget")
    ap.add_argument("log", help="tier-1 pytest log (the verify "
                    "line tees /tmp/_t1.log)")
    ap.add_argument("--budget", type=float, default=840.0,
                    metavar="S", help="suite budget in seconds "
                    "(default 840 = 870s kill minus headroom)")
    ap.add_argument("--new-lane", type=float, default=0.0,
                    metavar="S", help="projected seconds a new test "
                    "lane adds on top of the measured time")
    ap.add_argument("--top", type=int, default=8, metavar="N",
                    help="slowest files to list (default 8)")
    args = ap.parse_args(argv)
    try:
        with open(args.log) as f:
            text = f.read()
    except OSError as exc:
        print(f"t1_budget: cannot read {args.log}: {exc}",
              file=sys.stderr)
        return 2
    code, report = check_budget(text, args.budget, args.new_lane,
                                args.top)
    print(report)
    return code


if __name__ == "__main__":
    sys.exit(main())
