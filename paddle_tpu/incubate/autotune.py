"""paddle.incubate.autotune parity: runtime kernel autotuning.

Reference: python/paddle/incubate/autotune.py set_config over
phi/kernels/autotune/ (cached conv-algo search + switch_autotune.cc)
and imperative/layout_autotune.cc.

TPU mapping:
- kernel: REAL — the Pallas flash-attention kernel's (block_q, block_k)
  tiling is swept per input signature on its first eager call and the
  winner is cached (the analogue of the reference's per-shape conv-algo
  cache). Compiled programs reuse whatever the cache holds at trace
  time.
- layout: accepted, no-op — XLA's layout assignment already picks
  MXU-friendly layouts (the reference flips NCHW/NHWC for tensor cores
  by hand).
- dataloader: accepted, no-op — worker-count tuning is a host-side CPU
  heuristic; set num_workers explicitly.
"""
from __future__ import annotations

import time

__all__ = ["set_config", "get_config", "kernel_blocks_for"]

_config = {
    "kernel": {"enable": False, "tuning_range": [1, 3]},
    "layout": {"enable": False},
    "dataloader": {"enable": False},
}
_kernel_cache: dict = {}


def set_config(config=None):
    """reference: incubate/autotune.py:24 set_config(config=None).
    config: dict (or path to a json file) with optional "kernel",
    "layout", "dataloader" sections; None enables everything."""
    global _config
    if config is None:
        for sec in _config.values():
            sec["enable"] = True
        return
    if isinstance(config, str):
        import json
        with open(config) as f:
            config = json.load(f)
    for key in ("kernel", "layout", "dataloader"):
        if key in config:
            _config[key].update(config[key])


def get_config():
    return {k: dict(v) for k, v in _config.items()}


def _candidates(lq, lk):
    """Tiling sweep, capped at the padded sequence lengths."""
    cands = [(256, 512), (512, 512), (512, 1024), (1024, 1024),
             (256, 1024)]
    out = []
    for bq, bk in cands:
        pair = (min(bq, max(128, -(-lq // 128) * 128)),
                min(bk, max(128, -(-lk // 128) * 128)))
        if pair not in out:
            out.append(pair)
    return out


def kernel_blocks_for(sig, measure=None):
    """Best (block_q, block_k) for an attention signature, or None when
    autotune is off / nothing cached. `measure(bq, bk) -> seconds`
    runs one timed call; only eager callers pass it (a traced call
    cannot time, it just reuses the cache)."""
    if not _config["kernel"]["enable"]:
        return None
    if sig in _kernel_cache or measure is None:
        # a failed sweep caches None — fail over once, don't re-sweep
        return _kernel_cache.get(sig)
    lq, lk = sig[1], sig[2]
    reps = max(1, int(_config["kernel"].get("tuning_range",
                                            [1, 3])[-1]) - 1)
    best, best_dt = None, float("inf")
    for bq, bk in _candidates(lq, lk):
        try:
            measure(bq, bk)  # compile + warm
            dt = min(measure(bq, bk) for _ in range(reps))
        except Exception:
            continue
        if dt < best_dt:
            best, best_dt = (bq, bk), dt
    _kernel_cache[sig] = best
    return best
