"""Admission + continuous-batching policy.

The scheduler owns WHICH request occupies WHICH slot; the engine owns
the device state. All membership changes (admit into a free slot, evict
on EOS / max-tokens / timeout / cancel) happen here, between compiled
steps, so the compiled decode step itself never changes shape — the
slot-based analogue of Ragged Paged Attention's "requests of uneven
lengths share one kernel invocation" (PAPERS.md).

Policy: plain FIFO fairness by arrival order. A freed slot is refilled
by the longest-waiting queued request at the next step boundary —
subject to the engine's resource check (`assign(reserve=...)`): with a
paged KV pool a free slot alone is not admission, the request's whole
page budget must be free too. With the prefix cache the reserve
callback is MATCH-THEN-RESERVE: it longest-prefix-matches the prompt
against the radix tree (shared pages need no fresh allocation) and
evicts LRU unreferenced cached pages before refusing — so head-of-line
backpressure only engages once genuinely referenced pages exhaust the
pool, and a cold cache degrades to exactly the cache-off admission
order. Backpressure stays head-of-line: when the oldest queued
request's pages don't fit, nothing behind it is admitted either, so a
large request can't be starved by a stream of small ones.
"""
from __future__ import annotations

from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from .errors import QueueFull
from .request import Request, RequestState

__all__ = ["Scheduler"]


class Scheduler:
    def __init__(self, num_slots: int, max_queue: Optional[int] = None):
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        self.num_slots = num_slots
        self.max_queue = max_queue
        self._queue: deque = deque()        # FIFO arrival order
        self.running: Dict[int, Request] = {}   # slot -> request

    # -- queue side -------------------------------------------------------
    def submit(self, req: Request):
        if self.max_queue is not None and len(self._queue) >= self.max_queue:
            raise QueueFull(
                f"admission queue full ({self.max_queue}); shed load or "
                "raise max_queue")
        self._queue.append(req)

    def drop_queued(self, req: Request) -> bool:
        try:
            self._queue.remove(req)
            return True
        except ValueError:
            return False

    def pop_queued(self) -> List[Request]:
        """Remove and return every queued (not yet admitted) request —
        the drain/abort path: the engine decides their finish reason."""
        out = list(self._queue)
        self._queue.clear()
        return out

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def occupancy(self) -> float:
        return len(self.running) / self.num_slots

    def free_slots(self) -> List[int]:
        return [s for s in range(self.num_slots) if s not in self.running]

    # -- membership changes (between compiled steps only) -----------------
    def assign(self, reserve: Optional[Callable[[Request], bool]] = None
               ) -> List[Tuple[int, Request]]:
        """Join policy: fill free slots from the queue in arrival order.
        `reserve(req)` (optional) must claim the request's resources
        (KV pages) and return True, or refuse without side effects —
        a refusal stops admission at the queue head (FIFO
        backpressure). Returns the (slot, request) pairs granted this
        boundary; the engine prefills each one across the following
        steps."""
        grants = []
        for slot in self.free_slots():
            while self._queue and \
                    self._queue[0].state is RequestState.CANCELLED:
                # cancel raced admission (marked between the boundary's
                # evict pass and this assign): never grant it resources
                self._queue.popleft()
            if not self._queue:
                break
            req = self._queue[0]
            if reserve is not None and not reserve(req):
                break
            self._queue.popleft()
            req.slot = slot
            self.running[slot] = req
            grants.append((slot, req))
        return grants

    def pack_tokens(self, budget: int, width: int,
                    prefill_remaining: Dict[int, int],
                    draft_wanted: Optional[Dict[int, int]] = None
                    ) -> Tuple[List[int], Dict[int, int],
                               Dict[int, int]]:
        """Unified-step token packing (the PACK-instead-of-ALTERNATE
        policy): every DECODE slot gets its one token — a resident
        decoder is never stalled by prefill work — then mid-PREFILL
        slots split the SPARE budget (`budget` minus decode tokens) in
        slot order, each taking at most `width` prompt tokens this
        step, and finally DRAFT tokens (speculative decoding's verify
        rows, `draft_wanted` maps decode slots to proposed draft
        counts) take whatever spare remains, at most `width - 1` per
        slot so the row's `q_len = 1 + drafts` fits the step shape.
        Prefill outranks drafts deliberately: a prompt token is
        guaranteed work, a draft is a bet the verify pass may reject.
        `prefill_remaining` maps mid-prefill slots to their
        unprefilled prompt token counts. Returns (decode_slots,
        {slot: prefill tokens}, {slot: draft tokens}); a prefill slot
        that gets no grant simply idles one step (its q_len is 0 — no
        state changes, no retrace), a decode slot granted no drafts
        just runs its plain q_len-1 step."""
        decode_slots = [s for s, r in sorted(self.running.items())
                        if r.state is RequestState.DECODE]
        spare = max(0, budget - len(decode_slots))
        grants: Dict[int, int] = {}
        for slot in sorted(prefill_remaining):
            if spare <= 0:
                break
            take = min(prefill_remaining[slot], width, spare)
            if take > 0:
                grants[slot] = take
                spare -= take
        draft_grants: Dict[int, int] = {}
        if draft_wanted:
            decode = set(decode_slots)
            for slot in sorted(draft_wanted):
                if spare <= 0:
                    break
                if slot not in decode:
                    continue
                take = min(draft_wanted[slot], width - 1, spare)
                if take > 0:
                    draft_grants[slot] = take
                    spare -= take
        return decode_slots, grants, draft_grants

    def retire(self, slot: int) -> Optional[Request]:
        """Evict policy endpoint: free a slot (EOS / max-tokens /
        timeout / cancel all land here, decided by the engine)."""
        req = self.running.pop(slot, None)
        if req is not None:
            req.slot = None
        return req

    def expired(self, now: float) -> List[Request]:
        """Queued or running requests past their deadline."""
        out = [r for r in self._queue
               if r.deadline is not None and now >= r.deadline]
        out += [r for r in self.running.values()
                if r.deadline is not None and now >= r.deadline]
        return out

    def cancelled_running(self) -> List[Request]:
        return [r for r in self.running.values()
                if r.state is RequestState.CANCELLED]

    @property
    def has_work(self) -> bool:
        return bool(self._queue) or bool(self.running)
