"""paddle.text.datasets parity — parsers exercised on tiny synthetic
archives in the EXACT reference formats (aclImdb tar, PTB
simple-examples tar, ml-1m zip, 14-col housing text, conll05st tar,
wmt14/wmt16 tars). Reference: python/paddle/text/datasets/*.py.
"""
import gzip
import io
import os
import tarfile
import zipfile

import numpy as np
import pytest

from paddle_tpu.text.datasets import (Imdb, Imikolov, Movielens,
                                      UCIHousing, Conll05st, WMT14,
                                      WMT16)


def _add_bytes(tf, name, data):
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tf.addfile(info, io.BytesIO(data))


@pytest.fixture()
def imdb_tar(tmp_path):
    p = tmp_path / "aclImdb_v1.tar.gz"
    with tarfile.open(p, "w:gz") as tf:
        docs = {
            "aclImdb/train/pos/0.txt": b"great movie great fun",
            "aclImdb/train/pos/1.txt": b"great acting, great plot!",
            "aclImdb/train/neg/0.txt": b"bad movie bad bad",
            "aclImdb/test/pos/0.txt": b"great fun",
            "aclImdb/test/neg/0.txt": b"bad plot",
        }
        for name, data in docs.items():
            _add_bytes(tf, name, data)
    return str(p)


class TestImdb:
    def test_vocab_and_samples(self, imdb_tar):
        ds = Imdb(data_file=imdb_tar, mode="train", cutoff=1)
        # words with freq > 1 across the whole corpus (punctuation
        # stripped, lowercased): great(6), bad(5), movie(2), fun(2),
        # plot(2)
        assert set(ds.word_idx) == {b"great", b"bad", b"movie", b"fun",
                                    b"plot", b"<unk>"}
        assert len(ds) == 3  # 2 pos + 1 neg train docs
        doc, label = ds[0]
        assert doc.dtype.kind == "i" and label.shape == (1,)
        labels = sorted(int(ds[i][1][0]) for i in range(len(ds)))
        assert labels == [0, 0, 1]

    def test_no_download_raises(self):
        with pytest.raises(RuntimeError, match="no network egress"):
            Imdb()


@pytest.fixture()
def ptb_tar(tmp_path):
    p = tmp_path / "simple-examples.tgz"
    train = b"the cat sat\nthe dog sat\nthe cat ran\n"
    valid = b"the dog ran\n"
    with tarfile.open(p, "w:gz") as tf:
        _add_bytes(tf, "./simple-examples/data/ptb.train.txt", train)
        _add_bytes(tf, "./simple-examples/data/ptb.valid.txt", valid)
    return str(p)


class TestImikolov:
    def test_ngram_windows(self, ptb_tar):
        ds = Imikolov(data_file=ptb_tar, data_type="NGRAM",
                      window_size=2, mode="train", min_word_freq=1)
        # freq>1: the(4), cat(2), sat(2), dog(2), ran(2), <s>(4), <e>(4)
        assert b"the" in ds.word_idx and b"<unk>" in ds.word_idx
        assert len(ds) > 0
        sample = ds[0]
        assert len(sample) == 2  # bigram window
        assert all(s.dtype.kind == "i" for s in sample)

    def test_seq_mode(self, ptb_tar):
        ds = Imikolov(data_file=ptb_tar, data_type="SEQ", mode="valid",
                      min_word_freq=1)
        src, trg = ds[0]
        assert src[0] == ds.word_idx[b"<s>"]
        assert trg[-1] == ds.word_idx[b"<e>"]
        np.testing.assert_array_equal(src[1:], trg[:-1])


@pytest.fixture()
def ml1m_zip(tmp_path):
    p = tmp_path / "ml-1m.zip"
    movies = ("1::Toy Story (1995)::Animation|Comedy\n"
              "2::Jumanji (1995)::Adventure\n").encode("latin-1")
    users = ("1::M::25::12::55117\n"
             "2::F::30::7::02139\n").encode("latin-1")
    ratings = ("1::1::5::978300760\n"
               "1::2::3::978302109\n"
               "2::1::4::978301968\n").encode("latin-1")
    with zipfile.ZipFile(p, "w") as z:
        z.writestr("ml-1m/movies.dat", movies)
        z.writestr("ml-1m/users.dat", users)
        z.writestr("ml-1m/ratings.dat", ratings)
    return str(p)


class TestMovielens:
    def test_fields(self, ml1m_zip):
        ds = Movielens(data_file=ml1m_zip, mode="train",
                       test_ratio=0.0)
        assert len(ds) == 3
        s = ds[0]
        # usr(4 fields) + mov(3 fields) + rating
        assert len(s) == 8
        uid, gender, age, job = s[0], s[1], s[2], s[3]
        assert uid.shape == (1,) and gender[0] in (0, 1)
        rating = s[-1]
        assert -5.0 <= float(rating[0]) <= 5.0


class TestUCIHousing:
    def test_split_and_normalization(self, tmp_path):
        rs = np.random.RandomState(0)
        data = rs.rand(20, 14) * 10
        f = tmp_path / "housing.data"
        with open(f, "w") as fh:
            for row in data:
                fh.write(" ".join(f"{v:.6f}" for v in row) + "\n")
        train = UCIHousing(data_file=str(f), mode="train")
        test = UCIHousing(data_file=str(f), mode="test")
        assert len(train) == 16 and len(test) == 4
        feat, target = train[0]
        assert feat.shape == (13,) and target.shape == (1,)
        # features normalized, target untouched
        assert np.abs(feat).max() <= 1.0
        np.testing.assert_allclose(float(target[0]), data[0, -1],
                                   rtol=1e-5)


@pytest.fixture()
def conll_fixture(tmp_path):
    words = b"The\ncat\nsat\n\n"
    props = b"-   (A0*\n-   *)\nsit (V*V)\n\n"
    wbuf, pbuf = io.BytesIO(), io.BytesIO()
    with gzip.GzipFile(fileobj=wbuf, mode="w") as g:
        g.write(words)
    with gzip.GzipFile(fileobj=pbuf, mode="w") as g:
        g.write(props)
    p = tmp_path / "conll05st-tests.tar.gz"
    with tarfile.open(p, "w:gz") as tf:
        _add_bytes(tf,
                   "conll05st-release/test.wsj/words/test.wsj.words.gz",
                   wbuf.getvalue())
        _add_bytes(tf,
                   "conll05st-release/test.wsj/props/test.wsj.props.gz",
                   pbuf.getvalue())
    wd = tmp_path / "words.dict"
    wd.write_text("The\ncat\nsat\n")
    vd = tmp_path / "verbs.dict"
    vd.write_text("sit\n")
    td = tmp_path / "targets.dict"
    td.write_text("B-A0\nI-A0\nB-V\nI-V\nO\n")
    return str(p), str(wd), str(vd), str(td)


class TestConll05st:
    def test_srl_fields(self, conll_fixture):
        data, wd, vd, td = conll_fixture
        ds = Conll05st(data_file=data, word_dict_file=wd,
                       verb_dict_file=vd, target_dict_file=td)
        assert len(ds) == 1
        s = ds[0]
        assert len(s) == 9  # word,5xctx,pred,mark,label
        word_idx, mark, label_idx = s[0], s[7], s[8]
        assert word_idx.shape == (3,)
        assert mark.tolist().count(1) >= 1
        wdict, vdict, ldict = ds.get_dict()
        assert "B-V" in ldict and "O" in ldict


@pytest.fixture()
def wmt14_tar(tmp_path):
    p = tmp_path / "wmt14.tgz"
    src_dict = b"<s>\n<e>\n<unk>\nhello\nworld\n"
    trg_dict = b"<s>\n<e>\n<unk>\nbonjour\nmonde\n"
    pairs = b"hello world\tbonjour monde\nhello\tbonjour\n"
    with tarfile.open(p, "w:gz") as tf:
        _add_bytes(tf, "data/src.dict", src_dict)
        _add_bytes(tf, "data/trg.dict", trg_dict)
        _add_bytes(tf, "train/train", pairs)
    return str(p)


class TestWMT14:
    def test_ids(self, wmt14_tar):
        ds = WMT14(data_file=wmt14_tar, mode="train", dict_size=5)
        assert len(ds) == 2
        src, trg, trg_next = ds[0]
        assert src[0] == ds.src_dict["<s>"]
        assert src[-1] == ds.src_dict["<e>"]
        assert trg[0] == ds.trg_dict["<s>"]
        assert trg_next[-1] == ds.trg_dict["<e>"]
        np.testing.assert_array_equal(trg[1:], trg_next[:-1])

    def test_oov_maps_to_unk_not_start(self, wmt14_tar):
        """code-review regression: reference UNK_IDX is 2 (<unk>)."""
        ds = WMT14(data_file=wmt14_tar, mode="train", dict_size=4)
        # dict_size=4 drops 'world'/'monde' -> OOV must be id 2
        src, trg, _ = ds[0]
        assert ds.src_dict["<unk>"] == 2
        assert 2 in src.tolist()
        assert 0 not in src.tolist()[1:-1]  # no spurious <s> ids


@pytest.fixture()
def wmt16_tar(tmp_path):
    p = tmp_path / "wmt16.tar.gz"
    train = b"hello world\thallo welt\nhello\thallo\n"
    test = b"world\twelt\n"
    with tarfile.open(p, "w:gz") as tf:
        _add_bytes(tf, "wmt16/train", train)
        _add_bytes(tf, "wmt16/test", test)
        _add_bytes(tf, "wmt16/val", test)
    return str(p)


class TestWMT16:
    def test_dict_built_from_train(self, wmt16_tar):
        ds = WMT16(data_file=wmt16_tar, mode="test", src_dict_size=6,
                   trg_dict_size=6, lang="en")
        assert ds.src_dict["<s>"] == 0 and ds.src_dict["<e>"] == 1
        assert "hello" in ds.src_dict and "hallo" in ds.trg_dict
        src, trg, trg_next = ds[0]
        assert src[0] == 0 and src[-1] == 1
        rev = ds.get_dict("en", reverse=True)
        assert rev[0] == "<s>"
