"""Flash attention (Pallas, TPU) — fused forward AND backward, with
additive bias / key-padding masks and in-kernel dropout.

TPU-native replacement for the reference's fused FMHA CUDA
(paddle/fluid/operators/fused/fused_attention_op.cu, fmha_ref.h — whose
grad kernel is fused too; mask+dropout semantics per fmha_ref.h's
softmax-then-dropout). Online softmax over K/V blocks: running
(m, l, acc) scratch in VMEM, one MXU dot per (q-block, k-block) pair, no
[L, L] logits materialized in HBM.

Mask operands (both optional, combinable with causal):
  * ``bias``  — additive float bias [Bb, Hb, Lq, Lk] with Bb in {1, B}
    and Hb in {1, H}; streamed block-by-block (never materialized at
    [B, H, L, L] in HBM unless the caller already did).
  * ``kvec``  — additive per-key vector [B, Lk]: the padding-mask fast
    path (BERT finetune); O(L) HBM traffic.

Dropout (softmax-then-dropout, normalizer uses the UNDROPPED row sum,
matching the reference) uses a position-keyed counter hash: the keep
decision for (bh, q_pos, k_pos) depends only on the seed and the
position, so forward and the two backward kernels — whose grids
iterate in different orders — regenerate identical masks by
construction, and the plain-jnp hash doubles as the test oracle.

Forward stores per-row logsumexp; backward is two Pallas kernels
(structure mirrors jax.experimental.pallas.ops.tpu.flash_attention
without importing it):
  dq : grid (BH, nQ, nK), accumulates ds @ K over k-blocks in VMEM
  dkv: grid (BH, nK, nQ), accumulates p^T @ dO and ds^T @ Q over q-blocks
Both recompute p = exp(s - lse) from q/k (flash recompute trade), so
nothing O(L^2) ever hits HBM.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.experimental import disable_x64 as _disable_x64

import os

# interpret mode: run kernels on CPU for testing (conftest sets this)
_INTERPRET = os.environ.get("PADDLE_TPU_PALLAS_INTERPRET", "0") == "1"

def _prec(dt):
    # 'highest' (the package-wide default) is invalid for bf16 operands
    # under Mosaic; bf16 x bf16 -> f32 on the MXU is exact at DEFAULT.
    return (jax.lax.Precision.DEFAULT if jnp.dtype(dt) == jnp.bfloat16
            else jax.lax.Precision.HIGHEST)


# Large blocks amortize per-grid-step overhead (the kernel is VPU-bound
# on softmax bookkeeping; profiled on v5e: 128->512 blocks cut the GPT
# step's attention time 4x). Shrunk automatically for short sequences.
DEFAULT_BLOCK_Q = int(os.environ.get("PADDLE_TPU_FA_BLOCK_Q", "512"))
DEFAULT_BLOCK_K = int(os.environ.get("PADDLE_TPU_FA_BLOCK_K", "1024"))


def _fit_block(block, length):
    """Cap the block at the 128-padded sequence length."""
    return max(128, min(block, -(-length // 128) * 128))
_NEG_INF = -1e30
_LANES = 128


def _fmix32(h):
    """murmur3 finalizer: full-avalanche 32-bit mix (VPU int ops only —
    runs identically under Mosaic, interpret mode, and plain jnp)."""
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def dropout_keep(seed0, seed1, bh, q_pos, k_pos, thresh):
    """Position-keyed keep mask: True where the attention weight at
    (bh, q_pos, k_pos) survives dropout. Pure jnp — the same function
    is the kernel's mask generator and the test oracle."""
    hq = _fmix32(jnp.uint32(seed0)
                 + q_pos.astype(jnp.uint32) * jnp.uint32(2654435761))
    hk = _fmix32(jnp.uint32(seed1)
                 + k_pos.astype(jnp.uint32) * jnp.uint32(0x27D4EB2F))
    h = _fmix32(hq + hk
                + jnp.uint32(bh) * jnp.uint32(0x9E3779B9))
    return h >= jnp.uint32(thresh)


def _drop_thresh(p):
    """uint32 threshold: hash < thresh <=> dropped (prob p)."""
    return min(int(p * 4294967296.0), 4294967295)


def _block_keep(seed_ref, bh_id, qb, kb, block_q, block_k, thresh):
    """Keep-mask for the (qb, kb) block — THE single definition of the
    position arithmetic all three kernels share. Separability makes it
    cheap: hq depends only on the row and hk only on the column, so
    feeding the oracle (block_q,1)/(1,block_k) position VECTORS runs
    the first two fmix32 rounds on vectors; only the final mix touches
    the full block (5 int ops/element instead of 15 — the hash was the
    kernel's VPU hot spot)."""
    q_pos = qb * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0)
    k_pos = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_k), 1)
    # same single definition as the test oracle — the hq/hk fmix rounds
    # run on the (block_q,1)/(1,block_k) vectors and broadcast at the
    # final mix, bit- and formula-identical to full-matrix positions
    return dropout_keep(seed_ref[0], seed_ref[1], bh_id, q_pos, k_pos,
                        thresh)


def _biased_logits(q_ref, k_ref, R, scale32, prec):
    """Scaled q k^T for the current block, plus the optional streamed
    additive bias / key-vector operands."""
    s = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=prec) * scale32      # [bq, bk]
    if R.bias is not None:
        s = s + R.bias[0, 0].astype(jnp.float32)
    if R.kvec is not None:
        s = s + R.kvec[0].astype(jnp.float32)
    return s


class _Refs:
    """Positional-ref parser shared by the three kernels."""

    def __init__(self, refs, *, drop, has_bias, has_kvec, n_main):
        i = 0
        self.seed = None
        if drop:
            self.seed = refs[0]
            i = 1
        self.main = refs[i:i + n_main]
        i += n_main
        self.bias = None
        if has_bias:
            self.bias = refs[i]
            i += 1
        self.kvec = None
        if has_kvec:
            self.kvec = refs[i]
            i += 1
        self.rest = refs[i:]


def _fa_kernel(*refs, scale, causal, block_q, block_k, q_len, kv_len,
               drop_thresh, inv_keep, has_bias, has_kvec):
    drop = drop_thresh is not None
    R = _Refs(refs, drop=drop, has_bias=has_bias, has_kvec=has_kvec,
              n_main=3)
    q_ref, k_ref, v_ref = R.main
    o_ref, lse_ref, m_ref, l_ref, acc_ref = R.rest
    prec = _prec(q_ref.dtype)
    bh_id = pl.program_id(0)
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_kv = pl.num_programs(2)

    neg_inf = jnp.float32(_NEG_INF)
    scale32 = jnp.float32(scale)

    @pl.when(kj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, neg_inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # bottom-right causal alignment (matches the XLA reference: query i may
    # see keys j <= i + (kv_len - q_len)); whole k-blocks past the last
    # query of this q-block are predicated away.
    offset = kv_len - q_len
    run = True
    if causal:
        run = kj * block_k <= qi * block_q + block_q - 1 + offset

    # Mask generation (two iotas + compares + where) is pure VPU cost;
    # with d=64 the MXU work per block pair is tiny, so interior blocks
    # take a mask-free fast path and only diagonal/ragged-edge blocks
    # pay for the mask.
    ragged = (kv_len % block_k) != 0
    edge = (kj == pl.num_programs(2) - 1) if ragged else False
    if causal:
        full = kj * block_k + block_k - 1 <= qi * block_q + offset
        need_mask = jnp.logical_and(
            run, jnp.logical_or(jnp.logical_not(full), edge)) \
            if ragged else jnp.logical_and(run, jnp.logical_not(full))
        no_mask = jnp.logical_and(run, jnp.logical_and(
            full, jnp.logical_not(edge)) if ragged else full)
    else:
        need_mask = edge
        no_mask = jnp.logical_not(edge) if ragged else True

    def _accum(s):
        m_prev = m_ref[:, :1]              # [bq, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        # normalizer tracks the FULL softmax sum (dropout applies after
        # the softmax in the reference, so l never sees the mask)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        p_eff = p
        if drop:
            keep = _block_keep(R.seed, bh_id, qi, kj, block_q, block_k,
                               drop_thresh)
            p_eff = jnp.where(keep, p * jnp.float32(inv_keep), 0.0)
        v = v_ref[0]
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p_eff.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    def _logits():
        return _biased_logits(q_ref, k_ref, R, scale32, prec)

    @pl.when(no_mask)
    def _compute_fast():
        _accum(_logits())

    @pl.when(need_mask)
    def _compute_masked():
        s = _logits()
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_pos < kv_len
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = jnp.logical_and(valid, q_pos + offset >= k_pos)
        _accum(jnp.where(valid, s, neg_inf))

    @pl.when(kj == n_kv - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], jnp.float32(1e-30))
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse = m_ref[:, :1] + jnp.log(l)
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _fa_dq_kernel(*refs, scale, causal, block_q, block_k, q_len,
                  kv_len, drop_thresh, inv_keep, has_bias, has_kvec):
    drop = drop_thresh is not None
    R = _Refs(refs, drop=drop, has_bias=has_bias, has_kvec=has_kvec,
              n_main=6)
    q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref = R.main
    dq_ref, acc_ref = R.rest
    prec = _prec(q_ref.dtype)
    bh_id = pl.program_id(0)
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_kv = pl.num_programs(2)
    scale32 = jnp.float32(scale)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    offset = kv_len - q_len
    run = True
    if causal:
        run = kj * block_k <= qi * block_q + block_q - 1 + offset

    ragged = (kv_len % block_k) != 0
    edge = (kj == pl.num_programs(2) - 1) if ragged else False
    if causal:
        full = kj * block_k + block_k - 1 <= qi * block_q + offset
        base = jnp.logical_or(jnp.logical_not(full), edge) if ragged \
            else jnp.logical_not(full)
        need_mask = jnp.logical_and(run, base)
        no_mask = jnp.logical_and(run, jnp.logical_and(
            full, jnp.logical_not(edge)) if ragged else full)
    else:
        need_mask = edge
        no_mask = jnp.logical_not(edge) if ragged else True

    def _accum(s):
        k = k_ref[0]                       # [bk, d]
        v = v_ref[0]                       # [bk, d]
        do = do_ref[0]                     # [bq, d]
        lse = lse_ref[:, :, :1][0]         # [bq, 1]
        di = di_ref[:, :, :1][0]           # [bq, 1]
        p = jnp.exp(s - lse)    # masked s = -1e30 underflows to p = 0
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec)                # [bq, bk]
        if drop:
            keep = _block_keep(R.seed, bh_id, qi, kj, block_q, block_k,
                               drop_thresh)
            dp = jnp.where(keep, dp * jnp.float32(inv_keep), 0.0)
        ds = p * (dp - di) * scale32
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec)

    def _logits():
        return _biased_logits(q_ref, k_ref, R, scale32, prec)

    @pl.when(no_mask)
    def _compute_fast():
        _accum(_logits())

    @pl.when(need_mask)
    def _compute_masked():
        s = _logits()
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_pos < kv_len
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = jnp.logical_and(valid, q_pos + offset >= k_pos)
        _accum(jnp.where(valid, s, jnp.float32(_NEG_INF)))

    @pl.when(kj == n_kv - 1)
    def _finalize():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _fa_dkv_kernel(*refs, scale, causal, block_q, block_k, q_len,
                   kv_len, drop_thresh, inv_keep, has_bias, has_kvec):
    drop = drop_thresh is not None
    R = _Refs(refs, drop=drop, has_bias=has_bias, has_kvec=has_kvec,
              n_main=6)
    k_ref, v_ref, q_ref, do_ref, lse_ref, di_ref = R.main
    dk_ref, dv_ref, dk_acc, dv_acc = R.rest
    prec = _prec(q_ref.dtype)
    bh_id = pl.program_id(0)
    ki = pl.program_id(1)
    qj = pl.program_id(2)
    n_q = pl.num_programs(2)
    scale32 = jnp.float32(scale)

    @pl.when(qj == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    offset = kv_len - q_len
    run = True
    if causal:
        run = ki * block_k <= qj * block_q + block_q - 1 + offset

    ragged = (kv_len % block_k) != 0
    edge = (ki == pl.num_programs(1) - 1) if ragged else False
    if causal:
        full = ki * block_k + block_k - 1 <= qj * block_q + offset
        base = jnp.logical_or(jnp.logical_not(full), edge) if ragged \
            else jnp.logical_not(full)
        need_mask = jnp.logical_and(run, base)
        no_mask = jnp.logical_and(run, jnp.logical_and(
            full, jnp.logical_not(edge)) if ragged else full)
    else:
        need_mask = edge
        no_mask = jnp.logical_not(edge) if ragged else True

    def _accum(s):
        v = v_ref[0]                       # [bk, d]
        q = q_ref[0]                       # [bq, d]
        do = do_ref[0]                     # [bq, d]
        lse = lse_ref[:, :, :1][0]         # [bq, 1]
        di = di_ref[:, :, :1][0]           # [bq, 1]
        p = jnp.exp(s - lse)    # masked s = -1e30 underflows to p = 0
        if drop:
            keep = _block_keep(R.seed, bh_id, qj, ki, block_q, block_k,
                               drop_thresh)
            p_eff = jnp.where(keep, p * jnp.float32(inv_keep), 0.0)
        else:
            p_eff = p
        dv_acc[:] += jax.lax.dot_general(
            p_eff.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec)                # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec)                # [bq, bk]
        if drop:
            dp = jnp.where(keep, dp * jnp.float32(inv_keep), 0.0)
        ds = p * (dp - di) * scale32
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec)                # [bk, d]

    def _logits():
        return _biased_logits(q_ref, k_ref, R, scale32, prec)

    @pl.when(no_mask)
    def _compute_fast():
        _accum(_logits())

    @pl.when(need_mask)
    def _compute_masked():
        s = _logits()
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_pos < kv_len
        if causal:
            q_pos = qj * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = jnp.logical_and(valid, q_pos + offset >= k_pos)
        _accum(jnp.where(valid, s, jnp.float32(_NEG_INF)))

    @pl.when(qj == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _pad_to(x, axis, mult):
    n = x.shape[axis]
    rem = (-n) % mult
    if rem == 0:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, rem)
    return jnp.pad(x, pads)


def _mask_specs(bias, kvec, h, block_q, block_k, transpose=False):
    """(padded operands, in_specs) for the optional mask inputs.
    transpose=True is the dkv grid, where program_id(1) walks k-blocks
    and program_id(2) walks q-blocks."""
    ops, specs = [], []
    if bias is not None:
        Bb, Hb = bias.shape[0], bias.shape[1]
        bp = _pad_to(_pad_to(bias, 2, block_q), 3, block_k)

        def bias_idx(b, i, j):
            bi = 0 if Bb == 1 else b // h
            hi = 0 if Hb == 1 else b % h
            return ((bi, hi, j, i) if transpose else (bi, hi, i, j))
        ops.append(bp)
        specs.append(pl.BlockSpec((1, 1, block_q, block_k), bias_idx))
    if kvec is not None:
        B = kvec.shape[0]
        # [B, 1, Lk]: Mosaic needs the last-two block dims (sublane,
        # lane) to divide (8, 128) or equal the array dims — a middle
        # singleton satisfies the sublane rule
        kp = _pad_to(kvec, 1, block_k)[:, None, :]

        def kvec_idx(b, i, j):
            bi = 0 if B == 1 else b // h
            return ((bi, 0, i) if transpose else (bi, 0, j))
        ops.append(kp)
        specs.append(pl.BlockSpec((1, 1, block_k), kvec_idx))
    return ops, specs


def _seed_ops(seeds, drop):
    if not drop:
        return [], []
    return ([jnp.asarray(seeds, jnp.int32)],
            [pl.BlockSpec(memory_space=pltpu.SMEM)])


def _flash_fwd_bhld(q, k, v, bias, kvec, seeds, h, causal, scale,
                    dropout_p, block_q, block_k):
    """q: [BH, Lq, D], k/v: [BH, Lk, D] -> ([BH, Lq, D], lse)."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    block_q = _fit_block(block_q, lq)
    block_k = _fit_block(block_k, lk)
    qp = _pad_to(q, 1, block_q)
    kp = _pad_to(k, 1, block_k)
    vp = _pad_to(v, 1, block_k)
    n_q = qp.shape[1] // block_q
    n_k = kp.shape[1] // block_k
    drop = dropout_p > 0.0

    kernel = functools.partial(
        _fa_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, q_len=lq, kv_len=lk,
        drop_thresh=_drop_thresh(dropout_p) if drop else None,
        inv_keep=1.0 / (1.0 - dropout_p) if drop else 1.0,
        has_bias=bias is not None, has_kvec=kvec is not None)
    seed_ops, seed_specs = _seed_ops(seeds, drop)
    mask_ops, mask_specs = _mask_specs(bias, kvec, h, block_q, block_k)
    # Mosaic rejects i64 index arithmetic; trace the kernel in 32-bit
    # mode regardless of the global jax_enable_x64 (paddle int64 parity)
    with _disable_x64():
        out, lse = pl.pallas_call(
            kernel,
            grid=(bh, n_q, n_k),
            in_specs=seed_specs + [
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            ] + mask_specs,
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, _LANES),
                             lambda b, i, j: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct(qp.shape, q.dtype),
                jax.ShapeDtypeStruct((bh, qp.shape[1], _LANES),
                                     jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, _LANES), jnp.float32),
                pltpu.VMEM((block_q, _LANES), jnp.float32),
                pltpu.VMEM((block_q, d), jnp.float32),
            ],
            compiler_params=pltpu.TPUCompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=_INTERPRET,
        )(*seed_ops, qp, kp, vp, *mask_ops)
    return out[:, :lq], lse


def _flash_bwd_bhld(q, k, v, o, lse, do, bias, kvec, seeds, h, causal,
                    scale, dropout_p, block_q, block_k):
    """All [BH, L, D] (lse [BH, Lqp, 128]) -> (dq, dk, dv)."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    block_q = _fit_block(block_q, lq)
    block_k = _fit_block(block_k, lk)
    qp = _pad_to(q, 1, block_q)
    kp = _pad_to(k, 1, block_k)
    vp = _pad_to(v, 1, block_k)
    dop = _pad_to(do, 1, block_q)
    lqp, lkp = qp.shape[1], kp.shape[1]
    n_q, n_k = lqp // block_q, lkp // block_k
    offset = lk - lq
    drop = dropout_p > 0.0
    statics = dict(
        scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        q_len=lq, kv_len=lk,
        drop_thresh=_drop_thresh(dropout_p) if drop else None,
        inv_keep=1.0 / (1.0 - dropout_p) if drop else 1.0,
        has_bias=bias is not None, has_kvec=kvec is not None)

    di = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32),
                 axis=-1)                                    # [bh, lq]
    di = _pad_to(di, 1, block_q)
    di = jnp.broadcast_to(di[..., None], (bh, lqp, _LANES))

    qspec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    lmspec = pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0))

    if causal:
        def kv_idx(b, i, j):
            # skipped kv blocks prefetch block 0 (they are predicated off)
            ok = j * block_k <= i * block_q + block_q - 1 + offset
            return (b, jax.lax.select(ok, j, 0), 0)
    else:
        def kv_idx(b, i, j):
            return (b, j, 0)
    kvspec = pl.BlockSpec((1, block_k, d), kv_idx)

    seed_ops, seed_specs = _seed_ops(seeds, drop)
    mask_ops, mask_specs = _mask_specs(bias, kvec, h, block_q, block_k)

    dq_kernel = functools.partial(_fa_dq_kernel, **statics)
    with _disable_x64():
        dq = pl.pallas_call(
            dq_kernel,
            grid=(bh, n_q, n_k),
            in_specs=seed_specs
            + [qspec, kvspec, kvspec, qspec, lmspec, lmspec]
            + mask_specs,
            out_specs=pl.BlockSpec((1, block_q, d),
                                   lambda b, i, j: (b, i, 0)),
            out_shape=jax.ShapeDtypeStruct(qp.shape, q.dtype),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            compiler_params=pltpu.TPUCompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=_INTERPRET,
        )(*seed_ops, qp, kp, vp, dop, lse, di, *mask_ops)

    # dkv grid: (bh, n_k, n_q) — q is the sequential (accumulated) axis
    kspec2 = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0))
    if causal:
        def q_idx(b, i, j):
            # q blocks strictly above the diagonal band are predicated
            # off; prefetch the first contributing q block instead
            ok = i * block_k <= j * block_q + block_q - 1 + offset
            first = jnp.maximum((i * block_k - offset) // block_q, 0)
            return (b, jax.lax.select(ok, j, first), 0)
    else:
        def q_idx(b, i, j):
            return (b, j, 0)
    qspec2 = pl.BlockSpec((1, block_q, d), q_idx)
    lmspec2 = pl.BlockSpec((1, block_q, _LANES),
                           lambda b, i, j: q_idx(b, i, j))
    mask_ops2, mask_specs2 = _mask_specs(bias, kvec, h, block_q,
                                         block_k, transpose=True)

    dkv_kernel = functools.partial(_fa_dkv_kernel, **statics)
    with _disable_x64():
        dk, dv = pl.pallas_call(
            dkv_kernel,
            grid=(bh, n_k, n_q),
            in_specs=seed_specs
            + [kspec2, kspec2, qspec2, qspec2, lmspec2, lmspec2]
            + mask_specs2,
            out_specs=[
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct(kp.shape, k.dtype),
                jax.ShapeDtypeStruct(vp.shape, v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, d), jnp.float32),
                pltpu.VMEM((block_k, d), jnp.float32),
            ],
            compiler_params=pltpu.TPUCompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=_INTERPRET,
        )(*seed_ops, kp, vp, qp, dop, lse, di, *mask_ops2)

    return dq[:, :lq], dk[:, :lk], dv[:, :lk]


def _ref_blhd(q, k, v, causal, scale):
    logits = jnp.einsum("blhd,bmhd->bhlm", q, k).astype(jnp.float32) * scale
    if causal:
        lq, lk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((lq, lk), dtype=bool), lk - lq)
        logits = jnp.where(cm, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhlm,bmhd->blhd", probs, v)


def _to_bhld(x):
    b, l, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, l, d)


def _from_bhld(x, b, h):
    bh, l, d = x.shape
    return x.reshape(b, h, l, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10))
def flash_attention_blhd(q, k, v, bias=None, kvec=None, seeds=None,
                         causal=False, scale=None, dropout_p=0.0,
                         block_q=DEFAULT_BLOCK_Q,
                         block_k=DEFAULT_BLOCK_K):
    """Flash attention over [batch, seq, heads, head_dim] inputs.

    bias: optional additive [Bb, Hb, Lq, Lk] (Bb in {1,B}, Hb in {1,H});
    kvec: optional additive per-key vector [B, Lk] (padding masks);
    seeds: int32[2] dropout seed (required when dropout_p > 0)."""
    return _fa_fwd(q, k, v, bias, kvec, seeds, causal, scale,
                   dropout_p, block_q, block_k)[0]


def _fa_fwd(q, k, v, bias, kvec, seeds, causal, scale, dropout_p,
            block_q, block_k):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    b, lq, h, d = q.shape
    out, lse = _flash_fwd_bhld(
        _to_bhld(q), _to_bhld(k), _to_bhld(v), bias, kvec, seeds, h,
        causal, scale, dropout_p, block_q, block_k)
    out = _from_bhld(out, b, h)
    return out, (q, k, v, bias, kvec, seeds, out, lse)


def _fa_bwd(causal, scale, dropout_p, block_q, block_k, res, g):
    q, k, v, bias, kvec, seeds, o, lse = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    b, lq, h, d = q.shape
    dq, dk, dv = _flash_bwd_bhld(
        _to_bhld(q), _to_bhld(k), _to_bhld(v), _to_bhld(o), lse,
        _to_bhld(g), bias, kvec, seeds, h, causal, scale, dropout_p,
        block_q, block_k)
    dbias = None if bias is None else jnp.zeros_like(bias)
    dkvec = None if kvec is None else jnp.zeros_like(kvec)
    dseeds = None if seeds is None else jnp.zeros_like(seeds)
    return (_from_bhld(dq, b, h).astype(q.dtype),
            _from_bhld(dk, b, h).astype(k.dtype),
            _from_bhld(dv, b, h).astype(v.dtype),
            dbias, dkvec, dseeds)


flash_attention_blhd.defvjp(_fa_fwd, _fa_bwd)
