"""Multi-tenant LoRA adapter serving: a paged adapter pool.

One fleet serving thousands of cheap fine-tunes is the actual shape of
"millions of users": most tenants share one base model and differ only
by a low-rank delta on the attention projections (LoRA — per layer a
pair A [in, r], B [r, out] per projection, applied as
`y = x @ W + (x @ A) @ B * scale`). Serving them as separate models
would cost a full weight set per tenant; serving them as per-request
weight SWAPS would retrace or reload on every tenant switch. This
module makes tenant identity pure OPERAND DATA instead:

- **AdapterStore** — the registry plus a PAGED ADAPTER POOL holding
  device-resident A/B weights with exactly the `PagePool` discipline
  the KV pages already live under. One pool page = one adapter's
  whole per-layer A/B block (all layers, q/k/v/o projections, padded
  to the pool rank); page 0 is the reserved ZERO page — all-zero A/B,
  so `adapter_id 0` (the base model) degenerates to a bit-exact
  no-op delta. An adapter is REFCOUNTED while any resident slot uses
  it (eviction can never touch it), PARKS hot (cache-resident) when
  its last user retires, and under page pressure is SPILLED
  whole-page to a host-RAM tier (`HostPagePool`) or EVICTED LRU —
  either way it restores on demand (from the host copy, else
  re-uploaded from the registry: adapter weights are immutable, so
  eviction loses residency, never data).

- **Rank buckets** — registered ranks are rounded UP to a fixed small
  set (`rank_buckets`, default (2, 4, 8)) and zero-padded; the device
  pool itself carries ONE fixed rank (the largest bucket), so the
  per-row gathered A/B shapes never change and the ONE unified step
  never retraces across tenants, ranks, loads, evictions or
  restores. Zero padding is exact: padded rows/columns contribute
  exactly 0 to `x @ A @ B`.

- **Batched multi-adapter execution** — the engine rides a per-slot
  `adapter_page` vector (plus a per-slot `scale`) next to
  `pos`/`q_len` as step operands; inside the one compiled step each
  layer gathers its rows' A/B pages from the pool and the attention
  modules fuse the low-rank delta into the q/k/v (and o) projections
  (`lora_delta` op, nlp/generation.py). A batch mixing N tenants and
  base-model rows compiles to the SAME single program.

Upload/restore run through ONE jitted write program over a traced
page id (the COW-copy discipline of serving/engine.py), so adapter
churn never adds a trace either.

Correctness contract (tests/test_serving_adapters.py): a request
served under adapter `i` in a mixed-tenant batch emits tokens
bit-identical to serving it alone on a DENSE-MERGED model
(`W + B·A·scale` folded into the projection weights) — through prefix
caching (tenant-namespaced), eviction/spill churn, preemption,
speculation and tensor-parallel meshes.
"""
from __future__ import annotations

import itertools
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax.numpy as jnp

from .paging import HostPagePool, PagePool

__all__ = ["AdapterStore", "LoRAWeights", "resolve_adapters_flag",
           "make_random_lora", "BASE_ADAPTER", "ADAPTER_PROJS"]

# adapter_id 0 IS the base model: its pool page is the reserved
# all-zero page 0, so base rows ride the same gathered-delta path and
# degenerate exactly (x @ 0 @ 0 * 0 == 0)
BASE_ADAPTER = 0

# the projections an adapter patches, in pool order (A then B each)
ADAPTER_PROJS = ("q", "k", "v", "o")

ADAPTER_MODES = ("on", "off")


def resolve_adapters_flag(override=None) -> bool:
    """Whether the engine builds the multi-tenant adapter subsystem
    (default off: engines that never see a `model=`/adapter_id keep
    their exact pre-adapter trace — zero extra operands or compute).
    An explicit override wins (None defers; True/False/an
    AdapterStore-shaped config forces); otherwise
    PADDLE_TPU_ADAPTERS=on|off, read at engine construction like
    every other serving gate."""
    if override is not None:
        return bool(override)
    v = os.environ.get("PADDLE_TPU_ADAPTERS", "off")
    if v not in ADAPTER_MODES:
        raise ValueError(
            f"PADDLE_TPU_ADAPTERS must be one of {ADAPTER_MODES}, "
            f"got {v!r}")
    return v == "on"


class LoRAWeights:
    """One adapter's host-side weights: per layer, per projection
    (q/k/v/o) a pair (A [in, r], B [r, out]). `layers` is a list of
    dicts `{"q": (A, B), "k": ..., "v": ..., "o": ...}`; missing
    projections mean "no delta" (all-zero)."""

    def __init__(self, layers: Sequence[Dict[str, Tuple]], rank: int,
                 alpha: Optional[float] = None):
        self.layers = list(layers)
        self.rank = int(rank)
        if self.rank < 1:
            raise ValueError("LoRA rank must be >= 1")
        # the standard LoRA scaling alpha / r (alpha defaults to r:
        # scale 1.0 — the delta as registered)
        self.alpha = float(alpha) if alpha is not None else float(rank)

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def make_random_lora(n_layers: int, hidden: int, q_out: int,
                     kv_out: int, rank: int, rng, amp: float = 0.05
                     ) -> LoRAWeights:
    """A random adapter for tests/benches: every projection patched,
    N(0, amp) entries — big enough to change greedy argmax on tiny
    models, small enough to keep logits finite."""
    def pair(in_f, out_f):
        return (rng.normal(0.0, amp, size=(in_f, rank)),
                rng.normal(0.0, amp, size=(rank, out_f)))
    layers = []
    for _ in range(n_layers):
        layers.append({"q": pair(hidden, q_out),
                       "k": pair(hidden, kv_out),
                       "v": pair(hidden, kv_out),
                       "o": pair(q_out, hidden)})
    return LoRAWeights(layers, rank=rank)


class _Adapter:
    """One registered adapter's lifecycle record."""

    __slots__ = ("adapter_id", "name", "rank", "bucket", "scale",
                 "payload", "page", "host_slot", "last_used")

    def __init__(self, adapter_id: int, name: str, rank: int,
                 bucket: int, scale: float, payload: List[np.ndarray]):
        self.adapter_id = adapter_id
        self.name = name
        self.rank = rank            # registered rank
        self.bucket = bucket        # rank bucket it was padded to
        self.scale = scale
        # pool-shaped (padded to the POOL rank) per-layer arrays, in
        # pool order [Aq, Bq, Ak, Bk, Av, Bv, Ao, Bo] x n_layers —
        # the upload source AND (immutable) the restore-of-last-resort
        self.payload = payload
        self.page: Optional[int] = None       # device pool page
        self.host_slot: Optional[int] = None  # host tier slot
        self.last_used = 0

    @property
    def state(self) -> str:
        if self.page is not None:
            return "resident"
        if self.host_slot is not None:
            return "spilled"
        return "registered"


class AdapterStore:
    """Registry + paged device pool of LoRA adapters for ONE engine.

    Device state: per layer a tuple of eight pool tensors
    (Aq [P, hidden, R], Bq [P, R, q_out], Ak/Av [P, hidden, R],
    Bk/Bv [P, R, kv_out], Ao [P, q_out, R], Bo [P, R, hidden]) — page
    p of every tensor holds one adapter's block for that layer, R is
    the pool rank (max rank bucket). Page 0 is the reserved zero page
    (the base model / idle rows). The pools are STEP ARGUMENTS of the
    engine's one compiled program, never closed-over constants, so
    uploads and evictions swap data, not traces.

    Host state: `PagePool` bookkeeping (FREE/USED/CACHED/SWAPPED — an
    adapter referenced by a resident slot can never be evicted), a
    `HostPagePool` spill tier, and the registry of host payloads.

    Thread-safety: mutations happen on the engine's pump thread
    between compiled steps, like the KV pool; `stats()`/`debug()`
    take a lock only against torn scrape reads.
    """

    def __init__(self, n_layers: int, hidden: int, q_out: int,
                 kv_out: int, *, num_pages: int = 9,
                 rank_buckets: Sequence[int] = (2, 4, 8),
                 dtype=np.float32, host_pages: Optional[int] = None,
                 tp=None):
        self.n_layers = int(n_layers)
        self.hidden = int(hidden)
        self.q_out = int(q_out)
        self.kv_out = int(kv_out)
        self.rank_buckets = tuple(sorted(int(b) for b in rank_buckets))
        if not self.rank_buckets or self.rank_buckets[0] < 1:
            raise ValueError("rank_buckets must be >= 1")
        self.rank = self.rank_buckets[-1]      # the pool rank R
        self.num_pages = int(num_pages)
        self.dtype = dtype
        self.tp = tp
        self.pool = PagePool(self.num_pages)
        self.host_pool = HostPagePool(
            self.num_pages - 1 if host_pages is None else int(host_pages))
        self._lock = threading.Lock()
        self._ids = itertools.count(1)         # 0 is the base model
        self._by_name: Dict[str, int] = {}
        self._recs: Dict[int, _Adapter] = {}
        self._tick = itertools.count(1)
        # traffic counters (mirrored into ServingMetrics each step)
        self.loads_total = 0          # registry -> device uploads
        self.evictions_total = 0      # device copy dropped outright
        self.spills_total = 0         # device -> host tier moves
        self.restores_total = 0       # host tier -> device moves
        self._write_fn = None         # ONE jitted upload per store
        # device pools: page 0 all zeros = the base model's "delta"
        P, R = self.num_pages, self.rank
        self.pools = tuple(
            (jnp.zeros((P, self.hidden, R), dtype),
             jnp.zeros((P, R, self.q_out), dtype),
             jnp.zeros((P, self.hidden, R), dtype),
             jnp.zeros((P, R, self.kv_out), dtype),
             jnp.zeros((P, self.hidden, R), dtype),
             jnp.zeros((P, R, self.kv_out), dtype),
             jnp.zeros((P, self.q_out, R), dtype),
             jnp.zeros((P, R, self.hidden), dtype))
            for _ in range(self.n_layers))
        if tp is not None:
            # mesh placement mirrors the engine's column-parallel head
            # sharding: the B matrices feeding q/k/v shard over their
            # head-grouped OUTPUT dim (their delta adds to a sharded
            # projection — no collective), everything else replicates
            # (the o-side delta applies after the output all-gather)
            self.pools = tuple(
                (tp.replicate(aq), tp.place_adapter_col(bq),
                 tp.replicate(ak), tp.place_adapter_col(bk),
                 tp.replicate(av), tp.place_adapter_col(bv),
                 tp.replicate(ao), tp.replicate(bo))
                for (aq, bq, ak, bk, av, bv, ao, bo) in self.pools)

    # -- registry ----------------------------------------------------------
    def bucket_for(self, rank: int) -> int:
        """Smallest rank bucket >= rank; a rank above every bucket is
        a registration error (the pool's compiled shapes cap it)."""
        for b in self.rank_buckets:
            if rank <= b:
                return b
        raise ValueError(
            f"LoRA rank {rank} exceeds the largest rank bucket "
            f"{self.rank_buckets[-1]}; legal buckets: "
            f"{self.rank_buckets} (grow rank_buckets at engine "
            "construction)")

    def _pad_payload(self, w: LoRAWeights) -> List[np.ndarray]:
        """Registered (A, B) pairs -> pool-shaped arrays: zero-padded
        from the registered rank to the POOL rank R (exact — padded
        rows/cols contribute 0 to x @ A @ B), missing projections
        all-zero."""
        if len(w.layers) != self.n_layers:
            raise ValueError(
                f"adapter patches {len(w.layers)} layers but the "
                f"model has {self.n_layers}")
        R = self.rank
        shapes = {"q": (self.hidden, self.q_out),
                  "k": (self.hidden, self.kv_out),
                  "v": (self.hidden, self.kv_out),
                  "o": (self.q_out, self.hidden)}
        out: List[np.ndarray] = []
        for li, layer in enumerate(w.layers):
            for proj in ADAPTER_PROJS:
                in_f, out_f = shapes[proj]
                a_pad = np.zeros((in_f, R), np.float64)
                b_pad = np.zeros((R, out_f), np.float64)
                pair = layer.get(proj)
                if pair is not None:
                    a, b = (np.asarray(pair[0]), np.asarray(pair[1]))
                    if a.shape != (in_f, w.rank) or \
                            b.shape != (w.rank, out_f):
                        raise ValueError(
                            f"layer {li} proj {proj!r}: A/B shapes "
                            f"{a.shape}/{b.shape} do not match "
                            f"(in={in_f}, rank={w.rank}, out={out_f})")
                    a_pad[:, :w.rank] = a
                    b_pad[:w.rank, :] = b
                out.append(a_pad.astype(self.dtype))
                out.append(b_pad.astype(self.dtype))
        return out

    def register(self, name: str, weights: LoRAWeights) -> int:
        """Register one adapter under `name`; returns its adapter_id
        (stable for the store's lifetime — replicas registering the
        same adapters in the same order agree on ids). Registration is
        host-side only: nothing touches the device until a request
        under this id is admitted."""
        with self._lock:
            if name in self._by_name:
                raise ValueError(f"adapter {name!r} already registered")
            bucket = self.bucket_for(weights.rank)
            # ids are unbounded — PAGES are the bounded resource; a
            # fleet may register far more adapters than fit resident
            aid = next(self._ids)
            rec = _Adapter(aid, name, weights.rank, bucket,
                           weights.scale, self._pad_payload(weights))
            self._recs[aid] = rec
            self._by_name[name] = aid
        return aid

    def id_for(self, name: str) -> Optional[int]:
        """adapter_id registered under `name`; None if unknown."""
        with self._lock:
            return self._by_name.get(name)

    def name_of(self, adapter_id: int) -> str:
        if adapter_id == BASE_ADAPTER:
            return "base"
        return self._recs[adapter_id].name

    def known(self, adapter_id: int) -> bool:
        return adapter_id == BASE_ADAPTER or adapter_id in self._recs

    @property
    def registered(self) -> int:
        return len(self._recs)

    def scale_of(self, adapter_id: int) -> float:
        if adapter_id == BASE_ADAPTER:
            return 0.0
        return self._recs[adapter_id].scale

    # -- device upload (ONE trace) -----------------------------------------
    def _build_write(self):
        import jax

        def wr(pools, page, payload):
            out = []
            i = 0
            for layer in pools:
                out.append(tuple(
                    t.at[page].set(payload[i + j].astype(t.dtype))
                    for j, t in enumerate(layer)))
                i += len(layer)
            return tuple(out)
        return jax.jit(wr)

    def _upload(self, rec: _Adapter, page: int):
        if self._write_fn is None:
            self._write_fn = self._build_write()
        payload = [jnp.asarray(a) for a in rec.payload]
        self.pools = self._write_fn(self.pools, jnp.int32(page),
                                    payload)

    # -- residency (the paged-pool lifecycle) ------------------------------
    def _free_one_page(self) -> bool:
        """Make room: SPILL the LRU parked adapter to the host tier
        (device page frees, host copy restores cheaper than a
        re-upload accounting-wise), else EVICT it outright (the
        registry still holds the weights — eviction loses residency,
        never data). Returns False when every resident adapter is
        referenced by a running slot (nothing may be touched)."""
        victim = None
        for rec in self._recs.values():
            if rec.page is None or self.pool.refcount(rec.page) != 0:
                continue
            if victim is None or rec.last_used < victim.last_used:
                victim = rec
        if victim is None:
            return False
        slot = self.host_pool.store(victim.payload)
        if slot is not None:
            self.pool.swap_out([victim.page], spill=True)
            victim.host_slot = slot
            self.spills_total += 1
        else:
            self.pool.free([victim.page])
            self.evictions_total += 1
        victim.page = None
        return True

    def acquire(self, adapter_id: int
                ) -> Optional[Tuple[int, float]]:
        """Admission-side residency claim: make `adapter_id` device-
        resident (upload / restore, spilling or evicting LRU parked
        adapters under pressure) and take one reference on its page —
        a referenced adapter can never be evicted from under a
        resident slot. Returns (pool page, LoRA scale), or None when
        the pool is full of REFERENCED adapters (the engine's
        admission backpressure: the request waits). adapter_id 0 (the
        base model) is always (page 0, 0.0) at zero cost."""
        if adapter_id == BASE_ADAPTER:
            return (0, 0.0)
        rec = self._recs.get(adapter_id)
        if rec is None:
            raise ValueError(f"unknown adapter_id {adapter_id}")
        if rec.page is None:
            pages = self.pool.alloc(1)
            if pages is None and self._free_one_page():
                pages = self.pool.alloc(1)
            if pages is None:
                return None
            page = pages[0]
            if rec.host_slot is not None:
                # restore the spilled copy (and close the host-tier
                # obligation the spill opened)
                self._upload(rec, page)
                self.host_pool.free(rec.host_slot)
                self.pool.swapped_restored(1, spill=True)
                rec.host_slot = None
                self.restores_total += 1
            else:
                self._upload(rec, page)
                self.loads_total += 1
            rec.page = page
            # alloc() granted the first reference — no retain needed
        else:
            # already resident (parked or shared): take one more ref;
            # a parked page leaves the cache-resident state here
            self.pool.retain([rec.page])
        rec.last_used = next(self._tick)
        return (rec.page, rec.scale)

    def release(self, adapter_id: int):
        """A resident slot retired: drop one reference; an adapter
        nobody uses PARKS hot (cache-resident — the next request
        under it pays nothing) instead of freeing."""
        if adapter_id == BASE_ADAPTER:
            return
        rec = self._recs[adapter_id]
        zeroed = self.pool.release([rec.page])
        if zeroed:
            self.pool.park(zeroed)

    def hot_ids(self) -> List[int]:
        """Adapter ids currently device-resident (referenced or
        parked) — the router's affinity signal."""
        return [aid for aid, rec in self._recs.items()
                if rec.page is not None]

    def is_hot(self, adapter_id: int) -> bool:
        if adapter_id == BASE_ADAPTER:
            return True
        rec = self._recs.get(adapter_id)
        return rec is not None and rec.page is not None

    # -- introspection ------------------------------------------------------
    def assert_quiesced(self):
        """Engine-shutdown leak check (rides the KV pool's): every
        adapter page FREE or parked CACHED — no slot reference
        survived retirement. Spilled pages are legitimate long-lived
        state (PagePool's spill kind)."""
        self.pool.assert_quiesced()

    def stats(self) -> dict:
        with self._lock:
            states = [r.state for r in self._recs.values()]
            return {
                "registered": len(self._recs),
                "resident": states.count("resident"),
                "spilled": states.count("spilled"),
                "pages_used": self.pool.used_pages,
                "pages_cached": self.pool.cached_pages,
                "pages_swapped": self.pool.swapped_pages,
                "pages_total": self.num_pages - 1,
                "host_pages_used": self.host_pool.used_pages,
                "loads_total": self.loads_total,
                "evictions_total": self.evictions_total,
                "spills_total": self.spills_total,
                "restores_total": self.restores_total,
            }

    def debug(self) -> List[dict]:
        """Per-adapter rows for `GET /debug/state`: id, name, rank
        (registered and bucket), refcount, residency state."""
        with self._lock:
            out = []
            for aid in sorted(self._recs):
                rec = self._recs[aid]
                out.append({
                    "adapter_id": aid, "name": rec.name,
                    "rank": rec.rank, "rank_bucket": rec.bucket,
                    "scale": rec.scale, "state": rec.state,
                    "page": rec.page,
                    "refcount": (0 if rec.page is None
                                 else self.pool.refcount(rec.page))})
            return out
