"""paddle.static compatibility tests.

Reference test model: the fluid static-graph unittests
(test_executor_and_use_program_cache, book/test_fit_a_line) — build a
program once, run it many times with feed/fetch, train via
optimizer.minimize appended to the program.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu import static


@pytest.fixture(autouse=True)
def _static_mode():
    """Each test gets fresh default programs and leaves dynamic mode on."""
    main, startup = static.Program(), static.Program()
    paddle.enable_static()
    with static.program_guard(main, startup):
        yield (main, startup)
    paddle.disable_static()


class TestProgramBuild:
    def test_data_and_record(self, _static_mode):
        main, _ = _static_mode
        x = static.data("x", [None, 4], "float32")
        y = x * 2.0 + 1.0
        assert len(main._nodes) >= 1
        assert "x" in main._feed_names

    def test_program_guard_isolation(self, _static_mode):
        main, _ = _static_mode
        other = static.Program()
        x = static.data("x", [None, 4], "float32")
        with static.program_guard(other):
            z = static.data("z", [2, 2], "float32")
            _ = z + 1.0
        assert "z" in other._feed_names
        assert "z" not in main._feed_names
        _ = x + 1.0  # back on main
        assert len(main._nodes) >= 1


class TestExecutorRun:
    def test_feed_fetch_roundtrip(self, _static_mode):
        x = static.data("x", [None, 4], "float32")
        y = x * 3.0 + 1.0
        exe = static.Executor()
        exe.run(static.default_startup_program())
        arr = np.arange(8, dtype="float32").reshape(2, 4)
        out, = exe.run(feed={"x": arr}, fetch_list=[y])
        np.testing.assert_allclose(out, arr * 3 + 1)
        # different batch size: re-jit, same program
        arr2 = np.ones((5, 4), "float32")
        out2, = exe.run(feed={"x": arr2}, fetch_list=[y])
        np.testing.assert_allclose(out2, arr2 * 3 + 1)

    def test_layers_in_program(self, _static_mode):
        paddle.seed(0)
        x = static.data("x", [None, 8], "float32")
        lin = nn.Linear(8, 3)
        out = lin(x)
        exe = static.Executor()
        arr = np.random.RandomState(0).randn(4, 8).astype("float32")
        got, = exe.run(feed={"x": arr}, fetch_list=[out])
        want = arr @ lin.weight.numpy() + lin.bias.numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_fetch_by_name(self, _static_mode):
        x = static.data("x", [2, 2], "float32")
        y = x + 5.0
        y.name = "y_out"
        exe = static.Executor()
        out, = exe.run(feed={"x": np.zeros((2, 2), "float32")},
                       fetch_list=["y_out"])
        np.testing.assert_allclose(out, 5.0)


class TestStaticTraining:
    def test_fit_a_line(self, _static_mode):
        """The reference's canonical static example (book/fit_a_line):
        linear regression via sgd.minimize + exe.run loop."""
        paddle.seed(0)
        x = static.data("x", [None, 13], "float32")
        y = static.data("y", [None, 1], "float32")
        pred = static.nn.fc(x, size=1)
        loss = ((pred - y) ** 2).mean()
        # the canonical static idiom: no parameters= — minimize trains
        # every trainable Parameter leaf of the program
        sgd = opt.SGD(learning_rate=0.05)
        sgd.minimize(loss)

        exe = static.Executor()
        exe.run(static.default_startup_program())
        rs = np.random.RandomState(0)
        true_w = rs.randn(13, 1).astype("float32")
        losses = []
        for i in range(60):
            xb = rs.randn(16, 13).astype("float32")
            yb = xb @ true_w
            lv, = exe.run(feed={"x": xb, "y": yb}, fetch_list=[loss])
            losses.append(float(lv))
        assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])

    def test_adam_minimize_with_states(self, _static_mode):
        paddle.seed(1)
        x = static.data("x", [None, 6], "float32")
        y = static.data("y", [None, 1], "float32")
        lin = nn.Linear(6, 1)
        loss = ((lin(x) - y) ** 2).mean()
        adam = opt.Adam(learning_rate=0.05,
                        parameters=lin.parameters())
        adam.minimize(loss)
        exe = static.Executor()
        rs = np.random.RandomState(1)
        w = rs.randn(6, 1).astype("float32")
        first = last = None
        for i in range(40):
            xb = rs.randn(8, 6).astype("float32")
            lv, = exe.run(feed={"x": xb, "y": xb @ w},
                          fetch_list=[loss])
            first = first if first is not None else float(lv)
            last = float(lv)
        assert last < first * 0.3
        # adam moments materialized
        assert len(adam._accumulators) == 2


class TestStaticNN:
    def test_fc_conv_bn(self, _static_mode):
        paddle.seed(0)
        img = static.data("img", [None, 3, 8, 8], "float32")
        conv = static.nn.conv2d(img, num_filters=4, filter_size=3,
                                padding=1, act="relu")
        bn = static.nn.batch_norm(conv, is_test=True)
        feat = static.nn.fc(bn, size=10, num_flatten_dims=1)
        exe = static.Executor()
        out, = exe.run(feed={"img": np.random.RandomState(0).randn(
            2, 3, 8, 8).astype("float32")}, fetch_list=[feat])
        assert out.shape == (2, 10)
        assert np.isfinite(out).all()


class TestSaveLoadInference:
    def test_roundtrip(self, _static_mode, tmp_path):
        paddle.seed(0)
        x = static.data("x", [4, 8], "float32")
        lin = nn.Linear(8, 2)
        out = lin(x)
        exe = static.Executor()
        arr = np.random.RandomState(0).randn(4, 8).astype("float32")
        want, = exe.run(feed={"x": arr}, fetch_list=[out])

        prefix = str(tmp_path / "model" / "infer")
        static.save_inference_model(prefix, [x], [out], exe)

        paddle.disable_static()
        prog, feed_names, fetch_targets = static.load_inference_model(
            prefix, exe)
        assert feed_names == ["x"]
        got, = exe.run(prog, feed={"x": arr})
        np.testing.assert_allclose(got, want, rtol=1e-5)
        paddle.enable_static()

    def test_polymorphic_batch_roundtrip(self, _static_mode, tmp_path):
        # None batch dim -> shape-polymorphic export: load and run with
        # a batch size never seen at save time
        paddle.seed(0)
        x = static.data("xp", [None, 8], "float32")
        lin = nn.Linear(8, 2)
        out = lin(x)
        exe = static.Executor()
        prefix = str(tmp_path / "poly" / "infer")
        static.save_inference_model(prefix, [x], [out], exe)
        paddle.disable_static()
        prog, feed_names, _ = static.load_inference_model(prefix, exe)
        arr = np.random.RandomState(1).randn(7, 8).astype("float32")
        got, = exe.run(prog, feed={"xp": arr})
        want = arr @ lin.weight.numpy() + lin.bias.numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5)
        paddle.enable_static()


class TestRecordingHygiene:
    def test_disconnected_eager_ops_not_recorded(self, _static_mode):
        main, _ = _static_mode
        x = static.data("x", [None, 4], "float32")
        y = x * 2.0
        n = len(main._nodes)
        v = main._version
        # eager side computation between runs: disconnected from the
        # program -> not recorded, no version bump, no re-jit
        t = paddle.to_tensor(np.ones((3, 3), "float32"))
        _ = (t + 1.0).mean()
        assert len(main._nodes) == n
        assert main._version == v

    def test_runner_cache_stable_across_runs(self, _static_mode):
        main, _ = _static_mode
        x = static.data("x", [None, 4], "float32")
        y = x + 1.0
        exe = static.Executor()
        arr = np.zeros((2, 4), "float32")
        exe.run(feed={"x": arr}, fetch_list=[y])
        n_cache = len(main._runner_cache)
        for _i in range(3):
            t = paddle.to_tensor(np.ones((2, 2), "float32"))
            _ = t * 2.0  # interleaved eager work
            exe.run(feed={"x": arr}, fetch_list=[y])
        assert len(main._runner_cache) == n_cache  # all cache hits


class TestBufferWriteBack:
    """BN running stats must advance across Executor.run calls and feed
    the eval program — the reference's BN variable semantics
    (python/paddle/nn/layer/norm.py running_mean/running_variance)."""

    def test_bn_stats_match_eager_train_then_infer(self, _static_mode):
        paddle.seed(0)
        batches = [np.random.RandomState(i).randn(8, 3).astype("float32")
                   * (1.0 + i) + i for i in range(3)]

        # -- static: train program (records the stat update), then eval
        x = static.data("x", [None, 3], "float32")
        bn_s = nn.BatchNorm1D(3)
        y = bn_s(x)
        loss = (y ** 2).mean()
        sgd = opt.SGD(learning_rate=0.0, parameters=bn_s.parameters())
        sgd.minimize(loss)
        exe = static.Executor()
        exe.run(static.default_startup_program())
        for b in batches:
            exe.run(feed={"x": b}, fetch_list=[loss])

        eval_prog = static.Program()
        bn_s.eval()
        with static.program_guard(eval_prog):
            xe = static.data("xe", [None, 3], "float32")
            ye = bn_s(xe)
        out_s, = exe.run(eval_prog, feed={"xe": batches[0]},
                         fetch_list=[ye])

        # -- eager oracle: identical init (mean=0, var=1, w=1, b=0)
        paddle.disable_static()
        bn_e = nn.BatchNorm1D(3)
        bn_e.train()
        for b in batches:
            bn_e(paddle.to_tensor(b))
        bn_e.eval()
        out_e = bn_e(paddle.to_tensor(batches[0])).numpy()

        np.testing.assert_allclose(bn_s._mean.numpy(),
                                   bn_e._mean.numpy(), rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(bn_s._variance.numpy(),
                                   bn_e._variance.numpy(), rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(out_s, out_e, rtol=1e-5, atol=1e-5)
        # stats actually moved (the silent-staleness regression guard)
        assert not np.allclose(bn_s._mean.numpy(), np.zeros(3))

    def test_infer_only_program_stats_also_advance(self, _static_mode):
        """No optimizer attached: the _run_infer path must write back
        too (train-mode BN forward without minimize)."""
        x = static.data("x", [None, 3], "float32")
        bn = nn.BatchNorm1D(3)
        y = bn(x)  # training=True branch recorded
        exe = static.Executor()
        b = np.random.RandomState(0).randn(16, 3).astype("float32") + 5.0
        exe.run(feed={"x": b}, fetch_list=[y])
        m1 = bn._mean.numpy().copy()
        exe.run(feed={"x": b}, fetch_list=[y])
        m2 = bn._mean.numpy()
        assert not np.allclose(m1, np.zeros(3))
        assert not np.allclose(m1, m2)  # second run advances further


class TestProgramIntrospection:
    """Program inspection/prune/serialization (reference:
    program.global_block().ops OpDesc views, framework/prune.cc,
    ProgramDesc serialize_to_string)."""

    def test_ops_views(self, _static_mode):
        x = static.data("x", [None, 4], "float32")
        y = (x * 2.0 + 1.0).sum()
        main = static.default_main_program()
        types = [op.type for op in main.global_block().ops]
        assert len(types) >= 3
        assert any("mul" in t or "scale" in t or "multiply" in t
                   for t in types)
        op0 = main.global_block().ops[0]
        assert isinstance(op0.all_attrs(), dict)
        assert isinstance(op0.input_arg_names, list)

    def test_prune_drops_dead_ops(self, _static_mode):
        x = static.data("x", [None, 4], "float32")
        y = x * 2.0
        dead = x - 123.0  # not needed for y
        dead2 = dead * 7.0  # noqa: F841
        main = static.default_main_program()
        pruned = main.prune([y])
        assert len(pruned._nodes) < len(main._nodes)
        exe = static.Executor()
        arr = np.ones((2, 4), "float32")
        out, = exe.run(pruned, feed={"x": arr}, fetch_list=[y])
        np.testing.assert_allclose(out, arr * 2)

    def test_serialize_round_trip(self, _static_mode, tmp_path):
        paddle.seed(0)
        x = static.data("x", [None, 6], "float32")
        lin = nn.Linear(6, 3)
        y = lin(x) * 2.0
        main = static.default_main_program()
        exe = static.Executor()
        arr = np.random.RandomState(0).randn(4, 6).astype("float32")
        want, = exe.run(main, feed={"x": arr}, fetch_list=[y])

        main.serialize(str(tmp_path / "prog"))
        loaded = static.Program.deserialize(str(tmp_path / "prog"))
        # fetch by NAME in the rebuilt program
        y_id = main._leaf_alias.get(id(y), id(y))
        # the output tensor has no user name; fetch via the rebuilt
        # tensor object mapped from the same node position
        got_t = loaded._tensors[loaded._nodes[-1].out_ids[-1]]
        got, = exe.run(loaded, feed={"x": arr}, fetch_list=[got_t])
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


class TestStaticNNLongTail:
    """static.nn builders beyond fc/conv2d/batch_norm (reference:
    static/nn/__init__.py __all__)."""

    def test_norms_and_convs(self, _static_mode):
        paddle.seed(0)
        img = static.data("img", [None, 4, 8, 8], "float32")
        y = static.nn.conv2d_transpose(img, 4, 3, stride=2, padding=1)
        y = static.nn.group_norm(y, groups=2)
        y = static.nn.layer_norm(y, begin_norm_axis=1)
        y = static.nn.instance_norm(y)
        y = static.nn.prelu(y, mode="channel")
        exe = static.Executor()
        out, = exe.run(feed={"img": np.random.RandomState(0).randn(
            2, 4, 8, 8).astype("float32")}, fetch_list=[y])
        assert out.shape[0] == 2 and np.isfinite(out).all()

    def test_conv3d_and_bilinear(self, _static_mode):
        paddle.seed(1)
        vol = static.data("vol", [None, 2, 4, 4, 4], "float32")
        y3 = static.nn.conv3d(vol, 3, 3, padding=1)
        a = static.data("a", [None, 5], "float32")
        b = static.data("b", [None, 4], "float32")
        z = static.nn.bilinear_tensor_product(a, b, 6)
        exe = static.Executor()
        rs = np.random.RandomState(1)
        o1, o2 = exe.run(
            feed={"vol": rs.randn(2, 2, 4, 4, 4).astype("float32"),
                  "a": rs.randn(2, 5).astype("float32"),
                  "b": rs.randn(2, 4).astype("float32")},
            fetch_list=[y3, z])
        assert o1.shape == (2, 3, 4, 4, 4)
        assert o2.shape == (2, 6)

    def test_py_func_and_spectral_norm(self, _static_mode):
        x = static.data("x", [None, 3], "float32")
        doubled = static.nn.py_func(lambda t: t * 2.0, x, None)
        w = paddle.to_tensor(np.random.RandomState(0).randn(
            4, 3).astype("float32"))
        wn = static.nn.spectral_norm(w, power_iters=2)
        exe = static.Executor()
        out, = exe.run(feed={"x": np.ones((2, 3), "float32")},
                       fetch_list=[doubled])
        np.testing.assert_allclose(out, 2 * np.ones((2, 3)))
        # spectral norm of the returned weight ~ 1
        s = np.linalg.svd(wn.numpy(), compute_uv=False)[0]
        assert s < 2.0

    def test_prelu_element_mode(self, _static_mode):
        """code-review regression: mode='element' must apply a per-
        element slope, not a broadcast-incompatible channel weight."""
        x = static.data("x", [None, 2, 3, 3], "float32")
        y = static.nn.prelu(x, mode="element")
        exe = static.Executor()
        arr = -np.ones((2, 2, 3, 3), "float32")
        out, = exe.run(feed={"x": arr}, fetch_list=[y])
        np.testing.assert_allclose(out, arr * 0.25, rtol=1e-6)


class TestStaticGradClip:
    def test_static_clip_matches_dygraph(self):
        """ClipGradByGlobalNorm on the optimizer must bite on the
        Program/Executor path exactly as on the compiled dygraph step
        (was an admitted v1 delta; reference python/paddle/nn/clip.py)."""
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        import paddle_tpu.optimizer as opt

        rng = np.random.RandomState(0)
        x = (rng.randn(8, 4) * 50).astype(np.float32)  # big grads
        y = (rng.randn(8, 2) * 50).astype(np.float32)

        def build():
            paddle.seed(11)
            m = nn.Linear(4, 2)
            o = opt.SGD(learning_rate=0.1, parameters=m.parameters(),
                        grad_clip=nn.ClipGradByGlobalNorm(0.5))
            return m, o

        # dygraph compiled step
        from paddle_tpu import jit
        m1, o1 = build()
        step = jit.compile_train_step(
            lambda a, b: F.mse_loss(m1(a), b), m1, o1)
        step(paddle.to_tensor(x), paddle.to_tensor(y))

        # static program
        m2, o2 = build()
        prog = static.Program()
        with static.program_guard(prog):
            xin = static.data("x", shape=[None, 4], dtype="float32")
            yin = static.data("y", shape=[None, 2], dtype="float32")
            loss = F.mse_loss(m2(xin), yin)
            o2.minimize(loss)
        exe = static.Executor()
        exe.run(prog, feed={"x": x, "y": y}, fetch_list=[loss])

        for p1, p2 in zip(m1.parameters(), m2.parameters()):
            np.testing.assert_allclose(p1.numpy(), p2.numpy(),
                                       rtol=2e-5, atol=2e-6)
        # and clipping actually bit: unclipped run diverges
        m3, _ = build()
        o3 = opt.SGD(learning_rate=0.1, parameters=m3.parameters())
        step3 = jit.compile_train_step(
            lambda a, b: F.mse_loss(m3(a), b), m3, o3)
        step3(paddle.to_tensor(x), paddle.to_tensor(y))
        diff = max(np.abs(p1.numpy() - p3.numpy()).max()
                   for p1, p3 in zip(m1.parameters(), m3.parameters()))
        assert diff > 1e-3

    def test_startup_rerun_warns(self):
        import warnings
        exe = static.Executor()
        sp = static.default_startup_program()
        exe.run(sp)  # first: silent no-op
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            exe.run(sp)
        assert any("re-initialize" in str(x.message) for x in w)

    def test_static_per_param_and_value_clip_match_eager(self):
        """ClipGradByNorm (per-parameter) and ClipGradByValue must keep
        their OWN semantics on the static path — not be duck-typed into
        global-norm clipping (code-review regression)."""
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        import paddle_tpu.optimizer as opt

        rng = np.random.RandomState(2)
        x = (rng.randn(8, 4) * 50).astype(np.float32)
        y = (rng.randn(8, 2) * 50).astype(np.float32)
        for clip in (nn.ClipGradByNorm(0.5),
                     nn.ClipGradByValue(0.01)):
            paddle.seed(5)
            m1 = nn.Linear(4, 2)
            o1 = opt.SGD(learning_rate=0.1,
                         parameters=m1.parameters(), grad_clip=clip)
            loss = F.mse_loss(m1(paddle.to_tensor(x)),
                              paddle.to_tensor(y))
            loss.backward()
            o1.step()  # eager reference path (per-class _dygraph_clip)

            paddle.seed(5)
            m2 = nn.Linear(4, 2)
            o2 = opt.SGD(learning_rate=0.1,
                         parameters=m2.parameters(), grad_clip=clip)
            prog = static.Program()
            with static.program_guard(prog):
                xin = static.data("x", shape=[None, 4], dtype="float32")
                yin = static.data("y", shape=[None, 2], dtype="float32")
                sloss = F.mse_loss(m2(xin), yin)
                o2.minimize(sloss)
            static.Executor().run(prog, feed={"x": x, "y": y},
                                  fetch_list=[sloss])
            for p1, p2 in zip(m1.parameters(), m2.parameters()):
                np.testing.assert_allclose(p1.numpy(), p2.numpy(),
                                           rtol=2e-5, atol=2e-6)
