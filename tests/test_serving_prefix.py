"""Radix-tree automatic prefix cache over the paged KV pool.

The load-bearing properties (ISSUE acceptance):

- Greedy outputs with PADDLE_TPU_PREFIX_CACHE=on are TOKEN-IDENTICAL
  to the cache-off path — through full-page sharing, copy-on-write of
  partial pages, multi-turn reinsertion, and LRU eviction under page
  pressure — and no compiled program retraces across cache
  hit/miss/eviction transitions.
- Page accounting closes: after drain, free + cache-resident pages
  equals the pool size, refcount invariants hold, and PagePool raises
  on double free / free-while-referenced (hardening satellite).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
from paddle_tpu.serving import (HostPagePool, PagePool,
                                RadixPrefixCache,
                                RequestState, SamplingParams,
                                ServingEngine, chunk_bucket,
                                resolve_prefix_cache_flag)

_MODELS = {}


def tiny_gpt():
    m = _MODELS.get("gpt")
    if m is None:
        paddle.seed(7)
        cfg = GPTConfig(vocab_size=97, hidden_size=32,
                        num_hidden_layers=2, num_attention_heads=4,
                        intermediate_size=64,
                        max_position_embeddings=128,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
        m = _MODELS["gpt"] = GPTForCausalLM(cfg)
        m.eval()
    return m


def oracle_greedy(model, prompt, n_new):
    out = model.generate(paddle.to_tensor(np.asarray(prompt)[None]),
                         max_new_tokens=n_new).numpy()
    return out[0, len(prompt):]


def accounting_closes(eng):
    """Free + cache-resident == pool size and nothing referenced."""
    eng.pool.assert_quiesced()
    return (eng.pool.used_pages == 0
            and eng.pool.free_pages + eng.pool.cached_pages
            == eng.num_pages - 1)


class TestPagePoolInvariants:
    """Satellite: refcount hardening — double free, free-while-
    referenced/shared, use-after-free and leak checks all raise."""

    def test_double_free_raises(self):
        pool = PagePool(4)
        pages = pool.alloc(2)
        pool.free(pages)
        with pytest.raises(ValueError, match="double free"):
            pool.free([pages[0]])

    def test_free_while_shared_raises(self):
        pool = PagePool(4)
        [p] = pool.alloc(1)
        pool.retain([p])                 # second holder
        with pytest.raises(ValueError, match="still referenced"):
            pool.free([p])
        assert pool.release([p]) == []   # first holder lets go
        pool.free([p])                   # now sole-owned: legal

    def test_retain_free_page_raises(self):
        pool = PagePool(4)
        [p] = pool.alloc(1)
        pool.free([p])
        with pytest.raises(ValueError, match="use-after-free"):
            pool.retain([p])

    def test_release_unreferenced_raises(self):
        pool = PagePool(4)
        [p] = pool.alloc(1)
        assert pool.release([p]) == [p]
        with pytest.raises(ValueError, match="unreferenced"):
            pool.release([p])

    def test_park_and_retain_roundtrip(self):
        pool = PagePool(4)
        [p] = pool.alloc(1)
        pool.release([p])
        pool.park([p])
        assert pool.cached_pages == 1 and pool.used_pages == 0
        with pytest.raises(ValueError, match="already cache-resident"):
            pool.park([p])
        pool.retain([p])                 # cache hit re-references it
        assert pool.cached_pages == 0 and pool.used_pages == 1
        pool.release([p])
        pool.free([p])                   # eviction path
        assert pool.free_pages == 3

    def test_park_referenced_raises(self):
        pool = PagePool(4)
        [p] = pool.alloc(1)
        with pytest.raises(ValueError, match="referenced"):
            pool.park([p])

    def test_assert_quiesced_detects_leak(self):
        pool = PagePool(4)
        pages = pool.alloc(2)
        with pytest.raises(RuntimeError, match="leak"):
            pool.assert_quiesced()
        pool.release(pages)
        pool.park([pages[0]])
        pool.free([pages[1]])
        pool.assert_quiesced()           # free + cached == pool size

    def test_alloc_refuses_without_side_effects(self):
        pool = PagePool(4)
        assert pool.alloc(4) is None     # only 3 allocatable
        assert pool.free_pages == 3
        assert pool.alloc(3) is not None


class TestChunkBucket:
    """Satellite: prefill-chunk bucketing boundaries — the compiled-
    program-count bound depends on the min-chunk clamp being exact."""

    def test_large_remainder_is_full_chunk(self):
        assert chunk_bucket(100, 32) == 32
        assert chunk_bucket(32, 32) == 32      # exact boundary

    def test_tail_rounds_to_power_of_two_bucket(self):
        assert chunk_bucket(9, 32) == 16
        assert chunk_bucket(16, 32) == 16      # exact bucket fit
        assert chunk_bucket(17, 32) == 32      # next bucket == chunk

    def test_min_chunk_boundary(self):
        """Everything at or below min_chunk clamps UP to min_chunk —
        including remaining == 1 and remaining == min_chunk exactly —
        and one past it doubles."""
        assert chunk_bucket(1, 32) == 8
        assert chunk_bucket(8, 32) == 8
        assert chunk_bucket(9, 32, min_chunk=8) == 16
        assert chunk_bucket(3, 32, min_chunk=4) == 4
        assert chunk_bucket(5, 32, min_chunk=4) == 8

    def test_min_chunk_never_exceeds_chunk_len(self):
        """A min_chunk above chunk_len clamps DOWN: the bucket set
        must stay inside [min_chunk, chunk_len]."""
        assert chunk_bucket(3, 8, min_chunk=16) == 8
        assert chunk_bucket(7, 8, min_chunk=8) == 8

    def test_bucket_set_is_logarithmic(self):
        """Distinct values over every prompt length: {chunk_len} ∪
        {min_chunk * 2**i} — the O(log chunk_len) program bound."""
        got = {chunk_bucket(r, 32) for r in range(1, 200)}
        assert got == {8, 16, 32}

    def test_zero_remaining_raises(self):
        with pytest.raises(ValueError, match="remaining"):
            chunk_bucket(0, 32)


class TestHostPagePool:
    """Satellite: host-RAM tier slot invariants at the edges."""

    def test_store_until_full_then_none(self):
        host = HostPagePool(2)
        a, b = host.store("pay-a"), host.store("pay-b")
        assert a is not None and b is not None and a != b
        assert host.store("pay-c") is None     # full: no side effects
        assert host.used_pages == 2 and host.free_pages == 0

    def test_slot_reuse_after_free(self):
        host = HostPagePool(1)
        slot = host.store("x")
        host.free(slot)
        assert host.free_pages == 1
        slot2 = host.store("y")
        assert host.load(slot2) == "y"         # reused slot, new data

    def test_load_dead_slot_raises(self):
        host = HostPagePool(2)
        slot = host.store("x")
        host.free(slot)
        with pytest.raises(ValueError, match="dead host page"):
            host.load(slot)
        with pytest.raises(ValueError, match="dead host page"):
            host.load(99)                      # never stored

    def test_double_free_raises(self):
        host = HostPagePool(2)
        slot = host.store("x")
        host.free(slot)
        with pytest.raises(ValueError, match="double free"):
            host.free(slot)

    def test_zero_capacity_tier(self):
        host = HostPagePool(0)
        assert host.store("x") is None         # spill path degrades
        with pytest.raises(ValueError):
            HostPagePool(-1)


class TestRadixTreeUnit:
    """Cache mechanics against a bare pool (no engine, no device)."""

    PS = 4

    def make(self, num_pages=16):
        pool = PagePool(num_pages)
        return pool, RadixPrefixCache(pool, self.PS)

    def insert_seq(self, pool, cache, tokens):
        """Simulate a finished request: alloc pages, insert, return
        the page ids it used."""
        tokens = np.asarray(tokens, np.int64)
        n = -(-tokens.size // self.PS)
        pages = pool.alloc(n)
        cache.insert(tokens, pages, tokens.size)
        return pages

    def test_full_page_match_shares_and_refcounts(self):
        pool, cache = self.make()
        seq = np.arange(100, 112)                 # 3 full pages
        pages = self.insert_seq(pool, cache, seq)
        assert pool.cached_pages == 3
        prompt = np.concatenate([seq, [7, 8, 9]])
        grant = cache.acquire(prompt, max_new_tokens=4)
        # all 3 full pages shared, cached_len == 12, fresh tail pages
        assert grant.cached_len == 12
        assert grant.pages[:3] == pages
        assert grant.cow_src is None
        assert all(pool.refcount(p) == 1 for p in pages)
        assert pool.cached_pages == 0             # re-referenced
        cache.release(grant.pages)                # request retires
        assert pool.cached_pages == 3             # parked again

    def test_partial_tail_match_is_copy_on_write(self):
        pool, cache = self.make()
        seq = np.arange(50, 56)                   # 1 full + partial 2
        self.insert_seq(pool, cache, seq)
        partial_page = cache.root.children[
            np.asarray(seq[:4], np.int64).tobytes()].partials[0].page
        prompt = np.asarray(list(seq[:6]) + [1, 2], np.int64)
        grant = cache.acquire(prompt, max_new_tokens=2)
        assert grant.cached_len == 6              # 4 full + 2 via COW
        assert grant.cow_src == partial_page
        assert grant.cow_dst == grant.pages[1]    # the private copy
        assert pool.refcount(grant.cow_src) == 1  # copy-protection ref
        cache.cow_done(grant)
        assert pool.refcount(partial_page) == 0   # parked again
        cache.release(grant.pages)

    def test_match_never_covers_whole_prompt(self):
        """At least one token always prefills (the sampler needs the
        last prompt token's logits)."""
        pool, cache = self.make()
        seq = np.arange(10, 18)                   # 2 full pages
        self.insert_seq(pool, cache, seq)
        grant = cache.acquire(seq, max_new_tokens=4)   # same 8 tokens
        assert grant.cached_len <= seq.size - 1
        cache.cow_done(grant)
        cache.release(grant.pages)

    def test_divergent_prompts_split_at_page_boundary(self):
        pool, cache = self.make()
        a = np.asarray([1, 2, 3, 4, 5, 6, 7, 8], np.int64)
        b = np.asarray([1, 2, 3, 4, 9, 9, 9, 9], np.int64)
        self.insert_seq(pool, cache, a)
        self.insert_seq(pool, cache, b)
        root_child = cache.root.children[a[:4].tobytes()]
        assert len(root_child.children) == 2      # both second pages
        grant = cache.acquire(np.concatenate([b, [1]]), 2)
        assert grant.cached_len == 8
        cache.release(grant.pages)

    def test_duplicate_insert_freed_not_double_indexed(self):
        pool, cache = self.make()
        seq = np.arange(30, 38)
        first = self.insert_seq(pool, cache, seq)
        before = pool.free_pages
        self.insert_seq(pool, cache, seq)         # same span again
        assert pool.free_pages == before          # dup pages freed
        assert cache.tree_pages == 2
        key = np.asarray(seq[:4], np.int64).tobytes()
        assert cache.root.children[key].page == first[0]

    def test_lru_eviction_leaf_to_root_skips_referenced(self):
        pool, cache = self.make(num_pages=9)      # 8 allocatable
        old = self.insert_seq(pool, cache, np.arange(0, 8))    # 2 pages
        new = self.insert_seq(pool, cache, np.arange(20, 28))  # 2 pages
        # touch the OLD path so "new" becomes the LRU victim
        grant = cache.acquire(np.asarray(list(range(0, 8)) + [1],
                                         np.int64), 3)
        assert grant.cached_len == 8              # holds refs on `old`
        # 3 free pages left; ask for more than free -> must evict,
        # and must NOT touch the referenced `old` chain
        assert pool.free_pages == 3
        freed = cache.evict(4)
        assert freed == 2                         # only `new` was free
        assert all(pool.refcount(p) == 1 for p in old)
        assert cache.evicted_pages_total == 2
        # leaf evicted before its parent existed-> chain fully gone
        assert np.asarray(np.arange(20, 24),
                          np.int64).tobytes() not in cache.root.children
        cache.release(grant.pages)

    def test_acquire_refusal_rolls_back_cleanly(self):
        pool, cache = self.make(num_pages=5)      # 4 allocatable
        shared = self.insert_seq(pool, cache, np.arange(0, 8))
        # prompt hits both cached pages but needs 3 fresh (8+4 tokens,
        # page 4 -> 5 total); only 2 exist even after evicting nothing
        # (the matched pages are protected)
        grant = cache.acquire(np.asarray(list(range(0, 8)) + [1, 2, 3],
                                         np.int64), 9)
        assert grant is None
        assert pool.cached_pages == 2             # match re-parked
        assert all(pool.refcount(p) == 0 for p in shared)
        pool.assert_quiesced()

    def test_restore_of_dropped_host_page_degrades_to_prefill(self):
        """Satellite: a spilled node whose host payload was dropped
        behind the cache's back. The acquire walk stops at the failed
        restore and the tail prefills — a shorter hit, never a stale
        or torn page."""
        pool, cache = self.make()
        host = HostPagePool(4)
        alive = {"load": True}
        cache.set_host_tier(
            store=lambda page: host.store(("kv", page)),
            load=lambda slot: (pool.alloc(1) or [None])[0]
            if alive["load"] else None,
            drop=host.free)
        seq = np.arange(100, 112)                 # 3 full pages
        self.insert_seq(pool, cache, seq)
        assert cache.spill(1) == 1                # LRU = root page
        assert cache.stats()["spilled_nodes"] == 1
        alive["load"] = False                     # tier lost the page
        prompt = np.concatenate([seq, [1, 2]])
        grant = cache.acquire(prompt, max_new_tokens=2)
        # the ROOT page was the spilled one: restore fails at depth 0
        assert grant.cached_len == 0
        assert cache.stats()["spilled_nodes"] == 1  # still marked
        cache.release(grant.pages)
        pool.assert_quiesced()


class TestEngineEquivalence:
    """Engine-level acceptance: token identity on/off, COW, multi-turn,
    eviction under pressure, no retraces."""

    def test_hit_skips_prefill_and_stays_token_identical(self):
        model = tiny_gpt()
        p = np.arange(1, 21, dtype=np.int64) % 90
        want = oracle_greedy(model, p, 8)
        eng = ServingEngine(model, num_slots=2, max_len=64,
                            page_size=8, chunk_len=8)
        r1 = eng.add_request(p, SamplingParams(max_new_tokens=8))
        eng.run()
        chunks_cold = eng.metrics.prefill_chunks
        r2 = eng.add_request(p, SamplingParams(max_new_tokens=8))
        eng.run()
        np.testing.assert_array_equal(np.asarray(r1.output_tokens), want)
        np.testing.assert_array_equal(np.asarray(r2.output_tokens), want)
        assert r1.cached_tokens == 0
        assert r2.cached_tokens == 19           # 2 full pages + COW 3
        # 20 tokens cold = 3 chunks; warm = 1 chunk for the 1 real token
        assert chunks_cold == 3
        assert eng.metrics.prefill_chunks - chunks_cold == 1
        assert eng.prefix_cache.cow_copies_total == 1
        assert accounting_closes(eng)

    def test_shared_prefix_trace_on_off_token_identical(self):
        """The acceptance A/B: same shared-prefix + disjoint trace
        through cache-on and cache-off engines — token streams match
        each other and the solo oracle."""
        model = tiny_gpt()
        sysp = (np.arange(1, 19, dtype=np.int64) * 3) % 90
        prompts = [
            np.concatenate([sysp, [5, 6]]),
            np.concatenate([sysp, [7]]),
            np.array([42, 17, 3], np.int64),          # disjoint
            np.concatenate([sysp, [5, 6]]),           # exact repeat
            np.array([9, 9, 9, 9, 9], np.int64),      # disjoint
        ]
        want = [oracle_greedy(model, p, 6) for p in prompts]
        outs = {}
        for flag in (True, False):
            eng = ServingEngine(model, num_slots=2, max_len=64,
                                page_size=8, chunk_len=8,
                                prefix_cache=flag)
            reqs = [eng.add_request(p, SamplingParams(max_new_tokens=6))
                    for p in prompts]
            eng.run()
            outs[flag] = [list(r.output_tokens) for r in reqs]
            if flag:
                assert any(r.cached_tokens > 0 for r in reqs)
                assert accounting_closes(eng)
            else:
                assert eng.prefix_cache is None
                assert eng.pool.free_pages == eng.num_pages - 1
        for i, w in enumerate(want):
            assert outs[True][i] == outs[False][i] == list(w), i

    def test_multi_turn_follow_up_hits_decoded_pages(self):
        """Turn 2 re-sends turn 1's prompt + completion: the decoded
        pages inserted at retirement serve the follow-up."""
        model = tiny_gpt()
        p1 = np.arange(1, 13, dtype=np.int64)
        eng = ServingEngine(model, num_slots=2, max_len=96,
                            page_size=8, chunk_len=8)
        r1 = eng.add_request(p1, SamplingParams(max_new_tokens=8))
        eng.run()
        p2 = np.concatenate([p1, np.asarray(r1.output_tokens, np.int64),
                             np.array([33, 34], np.int64)])
        want2 = oracle_greedy(model, p2, 6)
        r2 = eng.add_request(p2, SamplingParams(max_new_tokens=6))
        eng.run()
        np.testing.assert_array_equal(np.asarray(r2.output_tokens),
                                      want2)
        # the whole first turn (prompt + 8 decoded) is cached history
        assert r2.cached_tokens >= p1.size + 8 - eng.page_size
        assert accounting_closes(eng)

    def test_eviction_under_pressure_stays_token_identical(self):
        """Pool far too small to cache every retiree: disjoint waves
        force leaf-to-root eviction, outputs stay exact, accounting
        closes."""
        model = tiny_gpt()
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, 97, size=rng.randint(2, 12))
                   .astype(np.int64) for _ in range(8)]
        want = [oracle_greedy(model, p, 6) for p in prompts]
        eng = ServingEngine(model, num_slots=2, max_len=32,
                            page_size=8, num_pages=7, chunk_len=8)
        reqs = [eng.add_request(p, SamplingParams(max_new_tokens=6))
                for p in prompts]
        eng.run()
        for r, w in zip(reqs, want):
            np.testing.assert_array_equal(np.asarray(r.output_tokens), w)
        assert eng.prefix_cache.evicted_pages_total > 0
        assert accounting_closes(eng)

    def test_no_retrace_across_hit_miss_eviction(self):
        """The compiled decode step, each prefill bucket, and the COW
        copy stay ONE program each across hits, misses, COW admissions
        and evictions. (Pinned to the legacy alternating path; the
        unified step's single-program property is asserted in
        tests/test_serving_unified.py.)"""
        import math
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=3, max_len=32,
                            page_size=8, num_pages=9, chunk_len=16,
                            unified=False)
        base = np.arange(1, 10, dtype=np.int64)
        rng = np.random.RandomState(0)
        for i in range(6):
            eng.add_request(base, SamplingParams(max_new_tokens=4),
                            request_id=f"hit-{i}")
            eng.add_request(rng.randint(0, 97, size=rng.randint(1, 12))
                            .astype(np.int64),
                            SamplingParams(max_new_tokens=4),
                            request_id=f"miss-{i}")
            eng.run()
        assert eng.prefix_cache.hits > 0
        # page pressure fired: since the host tier (PR 9) parked pages
        # SPILL to host RAM before anything is dropped, pressure shows
        # up as spills first and evictions only once the tier is full
        assert (eng.prefix_cache.evicted_pages_total
                + eng.prefix_cache.spilled_pages_total) > 0
        assert eng._decode_fn._cache_size() == 1
        bound = int(math.log2(eng.chunk_len)) + 1
        assert len(eng._prefill_fns) <= bound
        assert all(fn._cache_size() == 1
                   for fn in eng._prefill_fns.values())
        if eng._copy_page_fn is not None:
            assert eng._copy_page_fn._cache_size() == 1
        for fn in (eng._swap_out_fn, eng._swap_in_fn):
            if fn is not None:      # spill/restore traffic happened
                assert fn._cache_size() == 1
        assert accounting_closes(eng)

    def test_cancel_while_holding_shared_pages(self):
        """Satellite edge case: cancelling a resident that shares tree
        pages releases its references without freeing the tree — later
        identical prompts still hit and match the oracle."""
        model = tiny_gpt()
        p = np.arange(1, 21, dtype=np.int64) % 90
        want = oracle_greedy(model, p, 8)
        eng = ServingEngine(model, num_slots=2, max_len=64,
                            page_size=8, chunk_len=8)
        eng.add_request(p, SamplingParams(max_new_tokens=8))
        eng.run()                                   # seeds the tree
        b = eng.add_request(p, SamplingParams(max_new_tokens=8))
        eng.step()
        eng.step()
        assert b.cached_tokens > 0 and b.state is RequestState.DECODE
        shared = b.pages[:2]
        assert all(eng.pool.refcount(pg) == 1 for pg in shared)
        eng.cancel(b.request_id)
        eng.run()
        assert b.finish_reason == "cancelled"
        assert all(eng.pool.refcount(pg) == 0 for pg in shared)
        assert all(eng.pool.is_cached(pg) for pg in shared)
        c = eng.add_request(p, SamplingParams(max_new_tokens=8))
        eng.run()
        assert c.cached_tokens > 0
        np.testing.assert_array_equal(np.asarray(c.output_tokens), want)
        assert accounting_closes(eng)

    def test_eviction_racing_admission_same_boundary(self):
        """Two admissions in one step boundary where the second's
        eviction runs while the first holds freshly matched pages: the
        first's match is refcount-protected, both outputs exact."""
        model = tiny_gpt()
        pa = np.arange(1, 9, dtype=np.int64)        # 8 tokens, 1 page
        pb = np.array([90, 91, 92, 93, 94, 95, 96, 1], np.int64)
        want_a = oracle_greedy(model, pa, 7)
        want_b = oracle_greedy(model, pb, 7)
        # 6 allocatable pages, page_size 8: each request needs 2
        eng = ServingEngine(model, num_slots=2, max_len=16,
                            page_size=8, num_pages=7, chunk_len=8)
        seed_a = eng.add_request(pa, SamplingParams(max_new_tokens=7))
        seed_b = eng.add_request(pb, SamplingParams(max_new_tokens=7))
        eng.run()          # tree: both prompts' pages resident
        assert eng.pool.cached_pages == 4
        # both admitted at the SAME boundary: a hits its cached page,
        # b's fresh allocation must evict — but never a's protected match
        ra = eng.add_request(pa, SamplingParams(max_new_tokens=7))
        rb = eng.add_request(pb, SamplingParams(max_new_tokens=7))
        eng.run()
        np.testing.assert_array_equal(np.asarray(ra.output_tokens),
                                      want_a)
        np.testing.assert_array_equal(np.asarray(rb.output_tokens),
                                      want_b)
        assert ra.cached_tokens > 0
        assert accounting_closes(eng)
        np.testing.assert_array_equal(
            np.asarray(seed_a.output_tokens), want_a)
        np.testing.assert_array_equal(
            np.asarray(seed_b.output_tokens), want_b)

    def test_flag_gating_env_and_ctor(self, monkeypatch):
        model = tiny_gpt()
        monkeypatch.setenv("PADDLE_TPU_PREFIX_CACHE", "off")
        eng = ServingEngine(model, num_slots=1, max_len=32)
        assert eng.prefix_cache is None
        eng = ServingEngine(model, num_slots=1, max_len=32,
                            prefix_cache=True)    # ctor overrides env
        assert eng.prefix_cache is not None
        monkeypatch.setenv("PADDLE_TPU_PREFIX_CACHE", "on")
        eng = ServingEngine(model, num_slots=1, max_len=32)
        assert eng.prefix_cache is not None
        assert resolve_prefix_cache_flag("off") is False
        with pytest.raises(ValueError, match="on\\|off"):
            resolve_prefix_cache_flag("sometimes")

    def test_metrics_and_usage_surface_hits(self):
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=2, max_len=64,
                            page_size=8, chunk_len=8)
        p = np.arange(1, 18, dtype=np.int64)
        eng.add_request(p, SamplingParams(max_new_tokens=4))
        eng.run()
        r2 = eng.add_request(p, SamplingParams(max_new_tokens=4))
        eng.run()
        snap = eng.metrics.snapshot()
        pf = snap["prefix"]
        assert pf["lookups"] == 2 and pf["hits"] == 1
        assert pf["hit_rate"] == 0.5
        assert pf["cached_tokens"] == r2.cached_tokens > 0
        assert pf["resident_pages"] == eng.pool.cached_pages > 0
        assert snap["pool"]["pages_cached"] == eng.pool.cached_pages
        assert pf["cached_tokens_per_request"]["count"] == 2
        out = r2.output()
        assert out.cached_tokens == r2.cached_tokens
