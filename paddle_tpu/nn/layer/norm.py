"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor
from .layers import Layer
from .. import functional as F
from ..initializer import Constant

__all__ = ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
           "SyncBatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm",
           "SpectralNorm", "RMSNorm"]


class _BatchNormBase(Layer):
    _n = 2

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False or bias_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=Constant(1.0))
            self.bias = self.create_parameter(
                shape=[num_features], attr=bias_attr, is_bias=True)
        dt = (self._dtype or None)
        np_dt = np.float32
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features], np_dt)))
        self.register_buffer("_variance",
                             Tensor(jnp.ones([num_features], np_dt)))

    def forward(self, x):
        if self.training:
            out = F.batch_norm(
                x, self._mean, self._variance, self.weight, self.bias,
                training=True, momentum=self._momentum,
                epsilon=self._epsilon, data_format=self._data_format,
                use_global_stats=self._use_global_stats)
            y, new_mean, new_var = out
            # functional running-stat update: rebind buffers, stay detached
            self._mean._rebind(new_mean._value)
            self._variance._rebind(new_var._value)
            return y
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=False, momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format,
            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return (f"num_features={self._num_features}, "
                f"momentum={self._momentum}, epsilon={self._epsilon}")


class BatchNorm1D(_BatchNormBase):
    _n = 1

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        fmt = "NCHW" if data_format in ("NCL", "NC", "NCHW") else "NHWC"
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, fmt, use_global_stats, name)


class BatchNorm2D(_BatchNormBase):
    _n = 2


class BatchNorm3D(_BatchNormBase):
    _n = 3

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        fmt = "NCHW" if data_format.startswith("NC") else "NHWC"
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, fmt, use_global_stats, name)


class BatchNorm(_BatchNormBase):
    """Legacy paddle.nn.BatchNorm (act + is_test API)."""

    def __init__(self, num_channels, act=None, is_test=False, momentum=0.9,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 dtype="float32", data_layout="NCHW", in_place=False,
                 moving_mean_name=None, moving_variance_name=None,
                 do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout,
                         use_global_stats or None)
        self._act = act

    def forward(self, x):
        y = super().forward(x)
        if self._act:
            y = getattr(F, self._act)(y)
        return y


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Under GSPMD data parallelism the batch axis is a
    mesh axis; XLA computes global batch stats automatically when the
    reduction spans the sharded axis, so this is BatchNorm + an axis tag
    (reference: python/paddle/nn/layer/norm.py SyncBatchNorm over
    c_sync_calc/comm custom CUDA)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon, None, None,
                                layer._data_format)
            if layer.weight is not None:
                out.weight.set_value(layer.weight)
                out.bias.set_value(layer.bias)
            out._mean._rebind(layer._mean._value)
            out._variance._rebind(layer._variance._value)
        else:
            for name, sub in layer._sub_layers.items():
                layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                shape=self._normalized_shape, attr=weight_attr,
                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                shape=self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        if self.weight is None or self.bias is None:
            return F.layer_norm(x, self._normalized_shape, None, None,
                                self._epsilon)
        return F.layer_norm(x, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)

    def extra_repr(self):
        return (f"normalized_shape={self._normalized_shape}, "
                f"epsilon={self._epsilon}")


class RMSNorm(Layer):
    """RMS norm (new capability; Llama family). Weight-only scale."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            shape=[hidden_size], attr=weight_attr,
            default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                shape=[num_channels], attr=weight_attr,
                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                shape=[num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False or bias_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=Constant(1.0))
            self.bias = self.create_parameter(
                shape=[num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon,
                               data_format=self._data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    """Spectral norm of a weight (power iteration, reference:
    python/paddle/nn/layer/norm.py SpectralNorm)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        self._shape = list(weight_shape)
        h = self._shape[dim]
        w = int(np.prod(self._shape)) // h
        from ..initializer import Normal
        self.weight_u = self.create_parameter(
            shape=[h], default_initializer=Normal(0.0, 1.0))
        self.weight_v = self.create_parameter(
            shape=[w], default_initializer=Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...ops import manipulation, linalg, math as math_ops
        dim = self._dim
        w = weight
        if dim != 0:
            perm = [dim] + [i for i in range(len(self._shape)) if i != dim]
            w = manipulation.transpose(w, perm)
        h = w.shape[0]
        w_mat = manipulation.reshape(w, [h, -1])
        u, v = self.weight_u, self.weight_v
        for _ in range(self._power_iters):
            v_new = linalg.matmul(w_mat, u, transpose_x=True)
            v = F.normalize(v_new, axis=0, epsilon=self._epsilon)
            u_new = linalg.matmul(w_mat, v)
            u = F.normalize(u_new, axis=0, epsilon=self._epsilon)
        self.weight_u._rebind(u.detach()._value)
        self.weight_v._rebind(v.detach()._value)
        sigma = linalg.matmul(linalg.matmul(u, w_mat, transpose_x=True), v)
        out = math_ops.divide(w, sigma)
        if dim != 0:
            inv = list(np.argsort(perm))
            out = manipulation.transpose(out, inv)
        return out
