"""dy2static: AST conversion of tensor-predicated Python control flow.

TPU-native counterpart of the reference's dy2static transformer stack
(/root/reference/python/paddle/jit/dy2static/program_translator.py:272,
ifelse_transformer.py / loop_transformer.py, convert_operators.py).
Trace-based `to_static` handles everything EXCEPT native Python
`if`/`while` on Tensor conditions (a tracer has no bool). This pass
rewrites exactly those statements into calls of the existing
`ops.cond` / `ops.while_loop` via runtime dispatchers that keep plain
Python semantics when the predicate is not a Tensor:

    if x.sum() > 0:            (out,) = __pt_ifelse(x.sum() > 0,
        y = x * 2        ->                         _true, _false, (y,))
    else:
        y = x - 1

The reference's transformer suite is ~13k LoC because it must build
ProgramDesc sub-blocks; under tracing the branches stay ordinary Python
functions, so the whole pass is variable-capture analysis:
- outputs  = names assigned in either branch (simple targets)
- params   = outputs already bound before the statement
- anything else is read through the closure unchanged.

Control transfers (reference break_continue_transformer.py:1,
return_transformer.py:1, early_return_transformer.py:1) are
functionalized with carried bool flags:

    while c:              __brk = False
        ...               while __pt_and(__pt_not(__brk), c):
        if p: break   ->      ...
        ...                   (__brk,) = __pt_ifelse(p, set_true, id, ...)
                              if __pt_not(__brk): ...rest...

`continue` sets a per-iteration flag that guards the remainder of the
body; a mid-loop `return X` sets the break flag plus a return flag and
a site index — X itself is re-evaluated AFTER the loop from the exited
carry state (guards guarantee the carried names still hold their values
from the breaking iteration), which avoids carrying a value whose
shape/dtype is unknown before the first iteration. Early-return chains
at function level (`if c: return a` ... `return b`) absorb the tail as
the else branch recursively.

Statements that still cannot be functionalized keep their original
form: yield, del/global/nonlocal, transfers inside with/try blocks,
assignments to names that are neither pre-bound nor assigned in both
branches. Those work eagerly; under tracing they raise the standard
tracer-bool error.
"""
from __future__ import annotations

import ast
import inspect
import textwrap

__all__ = ["convert_control_flow", "cfg_helpers"]

_TRUE = "__pt_true_{n}"
_FALSE = "__pt_false_{n}"
_WCOND = "__pt_wcond_{n}"
_WBODY = "__pt_wbody_{n}"
_IFELSE = "__pt_ifelse"
_WHILE = "__pt_while"


# -- runtime dispatchers ------------------------------------------------------

def _dispatch_ifelse(pred, true_fn, false_fn, args):
    from ..core.tensor import Tensor
    if isinstance(pred, Tensor):
        from ..ops import control_flow
        return control_flow.cond(pred, true_fn, false_fn,
                                 operands=tuple(args))
    return true_fn(*args) if pred else false_fn(*args)


def _dispatch_for_range(start, stop, step, body_fn, args,
                        target_default=None):
    """for <target> in range(start, stop, step): functionalized. Python
    ints run the real for loop; Tensor bounds lower to while_loop.
    Returns (last_target_value, *carried); on an EMPTY range the target
    keeps `target_default` (its pre-loop binding), matching Python."""
    from ..core.tensor import Tensor
    if not any(isinstance(v, Tensor) for v in (start, stop, step)):
        vars_ = list(args)
        i = target_default
        for i in range(start, stop, step):
            out = body_fn(i, *vars_)
            vars_ = list(out) if isinstance(out, (list, tuple)) \
                else [out]
        return (i,) + tuple(vars_)
    from ..ops import control_flow
    from ..ops.creation import to_tensor
    import numpy as _np

    def _t(v):
        return v if isinstance(v, Tensor) else \
            to_tensor(_np.asarray(v, _np.int64))

    start, stop = _t(start), _t(stop)
    step_is_pos = not isinstance(step, Tensor) and step > 0
    step_is_neg = not isinstance(step, Tensor) and step < 0
    step = _t(step)
    last0 = _t(target_default) if isinstance(
        target_default, (int, Tensor)) else start - step

    if step_is_pos:
        def cond_fn(i, last, *vs):
            return i < stop
    elif step_is_neg:
        def cond_fn(i, last, *vs):
            return i > stop
    else:
        def cond_fn(i, last, *vs):
            return ((step > 0) & (i < stop)) | \
                ((step < 0) & (i > stop))

    def loop_body(i, last, *vs):
        out = body_fn(i, *vs)
        out = list(out) if isinstance(out, (list, tuple)) else [out]
        return [i + step, i] + out

    final = control_flow.while_loop(cond_fn, loop_body,
                                    [start, last0] + list(args))
    return (final[1],) + tuple(final[2:])


def _dispatch_while(cond_fn, body_fn, args):
    from ..core.tensor import Tensor
    vars_ = list(args)
    first = cond_fn(*vars_)
    if isinstance(first, Tensor):
        from ..ops import control_flow
        return tuple(control_flow.while_loop(cond_fn, body_fn, vars_))
    while bool(first):
        out = body_fn(*vars_)
        vars_ = list(out) if isinstance(out, (list, tuple)) else [out]
        first = cond_fn(*vars_)
    return tuple(vars_)


_FORRANGE = "__pt_forrange"

cfg_helpers = {_IFELSE: _dispatch_ifelse, _WHILE: _dispatch_while,
               _FORRANGE: _dispatch_for_range}


# -- analysis helpers ---------------------------------------------------------

def _assigned_names(nodes):
    """Simple-Name assignment targets in a statement list (recursing into
    nested compound statements but NOT nested function/class defs)."""
    names: set[str] = set()

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_ClassDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

        def visit_Name(self, node):
            if isinstance(node.ctx, ast.Store):
                names.add(node.id)

    for n in nodes:
        V().visit(n)
    return names


def _has_unsupported(nodes):
    """Control transfers / scope statements the functionalization cannot
    express."""
    found = []

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_ClassDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

        def generic_visit(self, node):
            if isinstance(node, (ast.Return, ast.Break, ast.Continue,
                                 ast.Yield, ast.YieldFrom, ast.Global,
                                 ast.Nonlocal, ast.Delete)):
                found.append(node)
            ast.NodeVisitor.generic_visit(self, node)

    for n in nodes:
        V().visit(n)
    return bool(found)


def _returns_cleanly(stmts):
    """Block ends with a top-level `return` and everything before it is
    free of control transfers — convertible as a returning branch."""
    return (bool(stmts) and isinstance(stmts[-1], ast.Return)
            and not _has_unsupported(stmts[:-1]))


def _make_fn(name, params, body, returns):
    """def name(params): body; return (returns,)"""
    ret = ast.Return(value=ast.Tuple(
        elts=[ast.Name(id=o, ctx=ast.Load()) for o in returns],
        ctx=ast.Load()))
    args = ast.arguments(
        posonlyargs=[], args=[ast.arg(arg=p) for p in params],
        vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
        defaults=[])
    return ast.FunctionDef(name=name, args=args,
                           body=(body or [ast.Pass()]) + [ret],
                           decorator_list=[], returns=None,
                           type_params=[])


def _call_helper(helper, head_args, params):
    return ast.Call(
        func=ast.Name(id=helper, ctx=ast.Load()),
        args=head_args + [ast.Tuple(
            elts=[ast.Name(id=p, ctx=ast.Load()) for p in params],
            ctx=ast.Load())],
        keywords=[])


def _unpack_assign(outs, value):
    target = ast.Tuple(elts=[ast.Name(id=o, ctx=ast.Store())
                             for o in outs], ctx=ast.Store())
    return ast.Assign(targets=[target], value=value)


class _Converter:
    def __init__(self):
        self.n = 0
        self.changed = False

    def transform_function(self, fndef: ast.FunctionDef):
        bound = {a.arg for a in fndef.args.args +
                 fndef.args.posonlyargs + fndef.args.kwonlyargs}
        for extra in (fndef.args.vararg, fndef.args.kwarg):
            if extra is not None:
                bound.add(extra.arg)
        fndef.body = self._block(fndef.body, bound, top=True)
        return fndef

    def _block(self, stmts, bound, top=False):
        out = []
        i = 0
        while i < len(stmts):
            st = stmts[i]
            # `if c: return A` + trailing code ending in return: absorb
            # the tail as the else branch (both paths then return, so
            # nothing follows the converted statement)
            if isinstance(st, ast.If) and not st.orelse and \
                    _returns_cleanly(st.body):
                rest = stmts[i + 1:]
                if rest and _returns_cleanly(rest):
                    st = ast.If(test=st.test, body=st.body, orelse=rest)
                    res = self._stmt(st, bound)
                    out.extend(res if isinstance(res, list) else [res])
                    return out
                if not rest and top:
                    # ONLY at the function-body level is the implicit
                    # fall-through `return None` — in a nested block the
                    # enclosing scope's code still runs after it
                    st = ast.If(test=st.test, body=st.body,
                                orelse=[ast.Return(
                                    value=ast.Constant(value=None))])
                    res = self._stmt(st, bound)
                    out.extend(res if isinstance(res, list) else [res])
                    return out
            res = self._stmt(st, bound)
            out.extend(res if isinstance(res, list) else [res])
            bound |= _assigned_names([st])
            i += 1
        return out

    def _stmt(self, st, bound):
        if isinstance(st, ast.If):
            return self._if(st, bound)
        if isinstance(st, ast.While):
            return self._while(st, bound)
        if isinstance(st, ast.For):
            converted = self._for_range(st, bound)
            if converted is not None:
                return converted
        # recurse into other compound statements' blocks
        if isinstance(st, (ast.For, ast.With, ast.Try)):
            for field in ("body", "orelse", "finalbody"):
                blk = getattr(st, field, None)
                if blk:
                    setattr(st, field, self._block(blk, set(bound)))
            if isinstance(st, ast.Try):
                for h in st.handlers:
                    h.body = self._block(h.body, set(bound))
        return st

    def _if(self, node: ast.If, bound):
        node.body = self._block(node.body, set(bound))
        node.orelse = self._block(node.orelse, set(bound))
        if _has_unsupported(node.body) or _has_unsupported(node.orelse):
            # return-style: both branches end in `return <expr>` and are
            # otherwise clean — convert to `return dispatch(...)` (the
            # reference's ReturnTransformer case)
            if node.orelse and _returns_cleanly(node.body) and \
                    _returns_cleanly(node.orelse):
                return self._if_returns(node, bound)
            return node
        wt = _assigned_names(node.body)
        wf = _assigned_names(node.orelse)
        outs = sorted(wt | wf)
        if not outs:
            return node  # side-effect-only branches: nothing to thread
        for o in outs:
            if o not in bound and not (o in wt and o in wf):
                return node  # may be undefined on one path: keep python
        params = [o for o in outs if o in bound]
        i = self.n
        self.n += 1
        tfn = _make_fn(_TRUE.format(n=i), params, node.body, outs)
        ffn = _make_fn(_FALSE.format(n=i), params, node.orelse, outs)
        call = _call_helper(
            _IFELSE,
            [node.test,
             ast.Name(id=tfn.name, ctx=ast.Load()),
             ast.Name(id=ffn.name, ctx=ast.Load())], params)
        self.changed = True
        return [tfn, ffn, _unpack_assign(outs, call)]

    def _if_returns(self, node: ast.If, bound):
        """Both branches return: branch functions keep their Return, the
        If becomes `return __pt_ifelse(test, t, f, (params,))`."""
        wt = _assigned_names(node.body)
        wf = _assigned_names(node.orelse)
        params = sorted((wt | wf) & bound)
        i = self.n
        self.n += 1

        def branch(name, body):
            args = ast.arguments(
                posonlyargs=[], args=[ast.arg(arg=p) for p in params],
                vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
                defaults=[])
            return ast.FunctionDef(name=name, args=args, body=body,
                                   decorator_list=[], returns=None,
                                   type_params=[])

        tfn = branch(_TRUE.format(n=i), node.body)
        ffn = branch(_FALSE.format(n=i), node.orelse)
        call = _call_helper(
            _IFELSE,
            [node.test,
             ast.Name(id=tfn.name, ctx=ast.Load()),
             ast.Name(id=ffn.name, ctx=ast.Load())], params)
        self.changed = True
        return [tfn, ffn, ast.Return(value=call)]

    def _for_range(self, node: ast.For, bound):
        """`for <name> in range(...)` -> __pt_forrange dispatch (the
        reference's loop_transformer for-range case). Returns None to
        keep the original statement."""
        it = node.iter
        if not (isinstance(it, ast.Call)
                and isinstance(it.func, ast.Name)
                and it.func.id == "range" and not it.keywords
                and 1 <= len(it.args) <= 3):
            return None
        if not isinstance(node.target, ast.Name) or node.orelse:
            return None
        # eligibility checks on the RAW body — bailing after conversion
        # would hand an already-converted body to the generic recursion
        if _has_unsupported(node.body):
            return None
        carried = sorted(_assigned_names(node.body) -
                         {node.target.id})
        if not carried or any(c not in bound for c in carried):
            # side-effect-only bodies cannot be functionalized (under
            # tracing the body would run once); keep python semantics
            return None
        node.body = self._block(node.body, set(bound))
        a = it.args
        start = a[0] if len(a) > 1 else ast.Constant(value=0)
        stop = a[1] if len(a) > 1 else a[0]
        step = a[2] if len(a) > 2 else ast.Constant(value=1)
        i = self.n
        self.n += 1
        bfn = _make_fn(_WBODY.format(n=i), [node.target.id] + carried,
                       node.body, carried)
        tdefault = (ast.Name(id=node.target.id, ctx=ast.Load())
                    if node.target.id in bound
                    else ast.Constant(value=None))
        call = ast.Call(
            func=ast.Name(id=_FORRANGE, ctx=ast.Load()),
            args=[start, stop, step,
                  ast.Name(id=bfn.name, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Name(id=c, ctx=ast.Load())
                                  for c in carried], ctx=ast.Load()),
                  tdefault],
            keywords=[])
        assign = _unpack_assign([node.target.id] + carried, call)
        self.changed = True
        return [bfn, assign]

    def _while(self, node: ast.While, bound):
        node.body = self._block(node.body, set(bound))
        if node.orelse or _has_unsupported(node.body):
            return node
        carried = sorted(_assigned_names(node.body))
        if not carried or any(c not in bound for c in carried):
            return node
        i = self.n
        self.n += 1
        cfn = _make_fn(_WCOND.format(n=i), carried, [], [])
        cfn.body = [ast.Return(value=node.test)]
        bfn = _make_fn(_WBODY.format(n=i), carried, node.body, carried)
        call = _call_helper(
            _WHILE,
            [ast.Name(id=cfn.name, ctx=ast.Load()),
             ast.Name(id=bfn.name, ctx=ast.Load())], carried)
        self.changed = True
        return [cfn, bfn, _unpack_assign(carried, call)]


def convert_control_flow(fn):
    """Return fn with tensor-predicated if/while functionalized; fn
    unchanged when nothing applies (or source is unavailable)."""
    if inspect.ismethod(fn):
        conv = convert_control_flow(fn.__func__)
        return conv.__get__(fn.__self__) if conv is not fn.__func__ \
            else fn
    if not inspect.isfunction(fn):
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn
    fndef = tree.body[0]
    if not isinstance(fndef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fndef.decorator_list = []  # do not re-apply @to_static et al.
    conv = _Converter()
    conv.transform_function(fndef)
    if not conv.changed:
        return fn

    freevars = fn.__code__.co_freevars
    module = ast.Module(body=[fndef], type_ignores=[])
    if freevars:
        factory = ast.FunctionDef(
            name="__pt_factory",
            args=ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg=v) for v in freevars], vararg=None,
                kwonlyargs=[], kw_defaults=[], kwarg=None, defaults=[]),
            body=[fndef, ast.Return(value=ast.Name(id=fndef.name,
                                                   ctx=ast.Load()))],
            decorator_list=[], returns=None, type_params=[])
        module = ast.Module(body=[factory], type_ignores=[])
    ast.fix_missing_locations(module)
    try:
        code = compile(module, filename=f"<dy2static {fn.__qualname__}>",
                       mode="exec")
    except (ValueError, SyntaxError):
        return fn
    # exec against the REAL module globals (late-bound names defined or
    # monkeypatched after decoration must stay visible); the two
    # dispatchers use reserved __pt_* names
    ns = fn.__globals__
    for k, v in cfg_helpers.items():
        ns.setdefault(k, v)
    local: dict = {}
    exec(code, ns, local)
    if freevars:
        # share the ORIGINAL closure cells (a later rebind of an
        # enclosing-scope variable must stay visible, exactly as in the
        # unconverted function): rebuild from the inner code object when
        # its freevar ordering matches; otherwise snapshot the cells
        import types
        factory = local["__pt_factory"]
        inner_code = next(
            (c for c in factory.__code__.co_consts
             if isinstance(c, types.CodeType)
             and c.co_name == fndef.name), None)
        if inner_code is not None and \
                inner_code.co_freevars == fn.__code__.co_freevars:
            new_fn = types.FunctionType(inner_code, ns, fn.__name__,
                                        fn.__defaults__, fn.__closure__)
        else:
            try:
                cells = [c.cell_contents
                         for c in (fn.__closure__ or ())]
            except ValueError:
                return fn  # empty cell: keep the python original
            new_fn = factory(*cells)
    else:
        new_fn = local[fndef.name]
    new_fn.__defaults__ = fn.__defaults__
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    new_fn.__qualname__ = fn.__qualname__
    new_fn.__wrapped_original__ = fn
    return new_fn
