"""paddle.incubate.autograd: functional higher-order AD.

Reference: python/paddle/incubate/autograd/functional.py (jvp/vjp/
Jacobian/Hessian over the prim-op AD rules). The TPU build gets these
directly from jax's transforms over functionalized Tensor code.
"""
from __future__ import annotations

import numpy as np
import jax

from ..core.tensor import Tensor
from ..autograd import jacobian as _tape_jacobian, hessian as _tape_hessian

__all__ = ["jvp", "vjp", "Jacobian", "Hessian", "jacobian", "hessian"]


def _functionalize(func):
    def pure(*vals):
        args = [Tensor(v, stop_gradient=True) for v in vals]
        out = func(*args)
        if isinstance(out, (list, tuple)):
            return tuple(o._value for o in out)
        return out._value
    return pure


def _vals(xs):
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    return [x._value for x in xs]


def _wrap(out):
    if isinstance(out, (list, tuple)):
        return tuple(Tensor(o) for o in out)
    return Tensor(out)


def jvp(func, xs, v=None):
    """Forward-mode: returns (func(xs), J @ v) (reference:
    incubate/autograd/functional.py jvp)."""
    vals = _vals(xs)
    if v is None:
        tang = [np.ones_like(np.asarray(x)) for x in vals]
    else:
        tang = _vals(v)
    out, tangents = jax.jvp(_functionalize(func), tuple(vals),
                            tuple(tang))
    return _wrap(out), _wrap(tangents)


def vjp(func, xs, v=None):
    """Reverse-mode: returns (func(xs), v @ J) (reference: functional.py
    vjp)."""
    vals = _vals(xs)
    out, vjp_fn = jax.vjp(_functionalize(func), *vals)
    if v is None:
        ct = np.ones_like(np.asarray(out)) if not isinstance(out, tuple) \
            else tuple(np.ones_like(np.asarray(o)) for o in out)
    else:
        ct = v._value if isinstance(v, Tensor) else tuple(_vals(v))
    grads = vjp_fn(ct)
    return _wrap(out), _wrap(grads if len(grads) > 1 else grads[0])


class Jacobian:
    """Lazy Jacobian matrix (reference: functional.py Jacobian)."""

    def __init__(self, func, xs, is_batched=False):
        vals = _vals(xs)
        # argnums as a tuple ALWAYS yields a tuple of blocks (even for
        # one input) — no re-wrapping
        self._jac = jax.jacobian(
            _functionalize(func),
            argnums=tuple(range(len(vals))))(*vals)
        self._single = len(vals) == 1

    def __getitem__(self, idx):
        return Tensor(self._jac[0][idx]) if self._single else \
            Tensor(self._jac[idx[0]][idx[1:]])

    def _full(self):
        """All input blocks concatenated along the last (input) axis —
        multi-input Jacobians must not silently drop blocks."""
        blocks = [np.asarray(j) for j in self._jac]
        if len(blocks) == 1:
            return blocks[0]
        return np.concatenate(
            [b.reshape(b.shape[0] if b.ndim > 1 else 1, -1)
             for b in blocks], axis=-1)

    @property
    def shape(self):
        return list(self._full().shape)

    def numpy(self):
        return self._full()


class Hessian(Jacobian):
    """Lazy Hessian matrix (reference: functional.py Hessian). For
    multiple inputs the full block Hessian is assembled (d2f/dxi dxj
    for every input pair)."""

    def __init__(self, func, xs, is_batched=False):
        vals = _vals(xs)
        argnums = tuple(range(len(vals)))
        hes = jax.hessian(_functionalize(func), argnums=argnums)(*vals)
        if len(vals) == 1:
            # hes is a tuple-of-tuples of blocks: ((d2f/dx0^2,),)
            self._jac = (np.asarray(hes[0][0]),)
            self._single = True
            return
        sizes = [int(np.asarray(v).size) for v in vals]
        block = np.block([
            [np.asarray(hes[i][j]).reshape(sizes[i], sizes[j])
             for j in range(len(vals))]
            for i in range(len(vals))])
        self._jac = (block,)
        self._single = True


# tape-based variants re-exported for API parity
jacobian = _tape_jacobian
hessian = _tape_hessian
