"""End-to-end training slice: LeNet on synthetic MNIST-shaped data
(BASELINE config #1; reference: fluid/tests/book recognize_digits).

Asserts real learning (loss decreases, accuracy above chance on a
memorizable subset), save/load round-trip, and the optimizer+loader+model
stack working together.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
from paddle_tpu.io import Dataset, DataLoader
from paddle_tpu.vision.models import LeNet


class SyntheticMNIST(Dataset):
    """Class-separable images: class k lights up a distinct block."""

    def __init__(self, n=256, num_classes=10, seed=0):
        rng = np.random.RandomState(seed)
        self.images = rng.randn(n, 1, 28, 28).astype("float32") * 0.1
        self.labels = rng.randint(0, num_classes, size=n).astype("int64")
        for i, lbl in enumerate(self.labels):
            r, c = divmod(int(lbl), 4)
            self.images[i, 0, r * 7:(r + 1) * 7, c * 7:(c + 1) * 7] += 2.0

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        return self.images[idx], self.labels[idx]


def test_lenet_learns():
    paddle.seed(0)
    model = LeNet()
    optimizer = opt.Adam(learning_rate=2e-3, parameters=model.parameters())
    loader = DataLoader(SyntheticMNIST(), batch_size=64, shuffle=True)
    first_loss, last_loss = None, None
    model.train()
    for epoch in range(4):
        for x, y in loader:
            logits = model(x)
            loss = F.cross_entropy(logits, y)
            loss.backward()
            optimizer.step()
            optimizer.clear_grad()
            if first_loss is None:
                first_loss = float(loss)
            last_loss = float(loss)
    assert first_loss > last_loss, (first_loss, last_loss)
    assert last_loss < 1.0, last_loss

    # eval accuracy on the training set (memorization check)
    model.eval()
    correct = total = 0
    for x, y in DataLoader(SyntheticMNIST(), batch_size=64):
        pred = model(x).numpy().argmax(-1)
        correct += int((pred == y.numpy()).sum())
        total += len(pred)
    acc = correct / total
    assert acc > 0.7, acc


def test_save_load_roundtrip(tmp_path):
    model = LeNet()
    x = paddle.to_tensor(np.random.randn(2, 1, 28, 28).astype("float32"))
    want = model(x).numpy()
    path = str(tmp_path / "lenet.pdparams")
    paddle.save(model.state_dict(), path)
    model2 = LeNet()
    model2.set_state_dict(paddle.load(path))
    np.testing.assert_allclose(model2(x).numpy(), want, rtol=1e-5,
                               atol=1e-6)


def test_optimizer_checkpoint_resume(tmp_path):
    paddle.seed(1)
    model = LeNet()
    optimizer = opt.Adam(learning_rate=1e-3,
                         parameters=model.parameters())
    ds = SyntheticMNIST(n=64)
    loader = DataLoader(ds, batch_size=32)
    for x, y in loader:
        F.cross_entropy(model(x), y).backward()
        optimizer.step()
        optimizer.clear_grad()
    paddle.save(model.state_dict(), str(tmp_path / "m.pdparams"))
    paddle.save(optimizer.state_dict(), str(tmp_path / "o.pdopt"))
    opt_sd = paddle.load(str(tmp_path / "o.pdopt"))
    model2 = LeNet()
    model2.set_state_dict(paddle.load(str(tmp_path / "m.pdparams")))
    optimizer2 = opt.Adam(learning_rate=1e-3,
                          parameters=model2.parameters())
    # same param names map state over
    optimizer2.set_state_dict(opt_sd)
    assert optimizer2._gstate["beta1_pow"] < 1.0


def test_resnet18_forward_backward():
    model = paddle.vision.models.resnet18(num_classes=10)
    x = paddle.to_tensor(np.random.randn(2, 3, 64, 64).astype("float32"),
                         stop_gradient=False)
    y = model(x)
    assert y.shape == [2, 10]
    y.mean().backward()
    assert model.conv1.weight.grad is not None


@pytest.mark.slow
def test_mobilenet_vgg_forward():
    m1 = paddle.vision.models.mobilenet_v2(scale=0.25, num_classes=7)
    y = m1(paddle.to_tensor(
        np.random.randn(1, 3, 64, 64).astype("float32")))
    assert y.shape == [1, 7]
