"""paddle_tpu.serving — continuous-batching online inference.

Wraps the compiled decode path (nlp/generation.py) in a slot-based
scheduler over a PAGED KV pool: requests arriving at different times,
with different prompt lengths and sampling params, share ONE compiled
unified ragged prefill+decode step (PADDLE_TPU_UNIFIED_STEP, default
on) — decode rows next to mid-prefill rows at q_len up to chunk_len
in the same fixed-shape invocation, prefill tokens packed into spare
decode capacity — each holding only the KV pages its prompt + output
budget needs. A decode row is no longer pinned to one token per step:
with SPECULATIVE DECODING on (PADDLE_TPU_SPEC_DECODE=ngram[:k] /
ServingEngine(spec=...), serving/spec.py, default off) a model-free
per-request drafter proposes up to k next tokens, the row verifies
them at q_len 1+k through the SAME step, and the whole accepted burst
is emitted at once — still bit-token-identical to one-at-a-time
greedy decode:

    from paddle_tpu.serving import ServingEngine, SamplingParams

    eng = ServingEngine(model, num_slots=8, max_len=256,
                        page_size=16, chunk_len=32)
    req = eng.add_request(prompt_ids,
                          SamplingParams(max_new_tokens=32,
                                         eos_token_id=eos))
    while eng.has_work:
        for out in eng.step():
            print(out.request_id, out.token_ids, out.finish_reason)
    print(eng.metrics.snapshot()["pool"])

Greedy requests are bit-identical to offline CompiledGenerator decode
(tested); `scripts/serving_bench.py` drives a Poisson arrival trace and
reports TTFT/throughput/pool utilization into BENCH_serving.json.
"""
from .engine import ServingEngine, resolve_unified_flag  # noqa: F401
from .errors import (EngineClosed, PoisonedRequest,  # noqa: F401
                     QueueFull, RateLimited, ServingError)
from .faults import (FaultInjector, InjectedFault,  # noqa: F401
                     resolve_faults)
from .metrics import (Histogram, ServingMetrics,  # noqa: F401
                      prometheus_render)
from .paging import PagePool, chunk_bucket, pages_needed  # noqa: F401
from .prefix import (PrefixGrant, RadixPrefixCache,  # noqa: F401
                     resolve_prefix_cache_flag)
from .request import (Request, RequestOutput, RequestState,  # noqa: F401
                      SamplingParams)
from .scheduler import Scheduler  # noqa: F401
from .spec import (Drafter, NgramDrafter, SpecConfig,  # noqa: F401
                   resolve_spec_config)

__all__ = ["ServingEngine", "resolve_unified_flag", "Scheduler",
           "ServingMetrics", "Histogram",
           "prometheus_render", "PagePool", "pages_needed",
           "chunk_bucket", "RadixPrefixCache", "PrefixGrant",
           "resolve_prefix_cache_flag", "Request", "RequestOutput",
           "RequestState", "SamplingParams", "ServingError",
           "QueueFull", "EngineClosed", "RateLimited",
           "PoisonedRequest", "FaultInjector", "InjectedFault",
           "resolve_faults", "Drafter", "NgramDrafter", "SpecConfig",
           "resolve_spec_config"]
