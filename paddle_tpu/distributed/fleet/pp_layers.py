"""Pipeline-parallel layers.

TPU-native replacement for PipelineLayer + schedules (reference:
python/paddle/distributed/fleet/meta_parallel/parallel_layers/
pp_layers.py:209 PipelineLayer, :57 LayerDesc, :93 SegmentLayers;
schedules fleet/meta_parallel/pipeline_parallel.py:119 1F1B, :463
interleaved). The reference runs one stage per process with
partial_send/recv p2p and hand-scheduled 1F1B. Here all stages live in
ONE compiled program: stage boundaries are sharding constraints over the
"pp" mesh axis, and the microbatch loop is a lax.scan whose per-stage
compute XLA schedules across pp devices (GPipe-style fill/drain inside
one XLA program — collective-permute moves activations on ICI). This is
the SURVEY.md §7 decision: "give up cross-executable 1F1B for a compiled
collective_permute schedule".
"""
from __future__ import annotations

import math
import re

import numpy as np

from ...nn.layer.layers import Layer
from ...nn.layer.container import LayerList, Sequential
from ...core.tensor import Tensor

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer",
           "SegmentLayers", "PipelineParallel"]


class LayerDesc:
    """reference: pp_layers.py:57."""

    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    """reference: pp_layers.py:77 — layers shared between stages (e.g.
    embedding/unembedding weight tying)."""

    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """reference: pp_layers.py:93 — split N layers into S stages."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.layers_desc = layers_desc
        self.num_parts = num_parts
        self.method = method

    def do_segment(self):
        n = len(self.layers_desc)
        if self.method == "uniform":
            return self.uniform(n, self.num_parts)
        m = re.match(r"layer:(.+)", self.method)
        if m:
            name = m.group(1)
            hits = [i for i, d in enumerate(self.layers_desc)
                    if (d.layer_func.__name__ if isinstance(d, LayerDesc)
                        else type(d).__name__) == name]
            if len(hits) < self.num_parts:
                raise ValueError(
                    f"cannot split {len(hits)} x {name} into "
                    f"{self.num_parts} stages")
            per = len(hits) // self.num_parts
            extra = len(hits) % self.num_parts
            result = [0]
            idx = 0
            for p in range(self.num_parts):
                take = per + (1 if p < extra else 0)
                idx += take
                result.append(hits[idx - 1] + 1 if idx > 0 else 0)
            result[-1] = n
            return result
        raise ValueError(f"bad segment method {self.method}")

    @staticmethod
    def uniform(num_items, num_parts):
        result = [0] * (num_parts + 1)
        part_size = math.floor(num_items / num_parts)
        extra = num_items % num_parts
        for i in range(1, num_parts + 1):
            result[i] = result[i - 1] + part_size + \
                (1 if i <= extra else 0)
        return result


class PipelineLayer(Layer):
    """reference: pp_layers.py:209. Builds ALL stages (single-controller
    owns the whole mesh); stage index is carried per sublayer so the
    runtime can insert pp-axis sharding constraints at boundaries."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform",
                 recompute_interval=0, recompute_ctx=None,
                 num_virtual_pipeline_stages=None):
        super().__init__()
        self._layers_desc = list(layers)
        if topology is not None:
            self._num_stages = topology.get_dim("pipe")
        else:
            self._num_stages = num_stages or 1
        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        seg = SegmentLayers(self._layers_desc, self._num_stages,
                            seg_method)
        self.segment_parts = seg.do_segment()
        self.run_function = []
        self._stage_of = []
        self._shared = {}
        built = LayerList()
        for stage in range(self._num_stages):
            lo, hi = self.segment_parts[stage], self.segment_parts[stage + 1]
            for i in range(lo, hi):
                desc = self._layers_desc[i]
                if isinstance(desc, SharedLayerDesc):
                    if desc.layer_name not in self._shared:
                        self._shared[desc.layer_name] = desc.build_layer()
                    lyr = self._shared[desc.layer_name]
                    fwd = desc.forward_func
                    run = (lambda l=lyr, f=fwd:
                           (lambda *x: f(l, *x) if f else l(*x)))()
                elif isinstance(desc, LayerDesc):
                    lyr = desc.build_layer()
                    run = lyr
                elif isinstance(desc, Layer):
                    lyr = desc
                    run = lyr
                elif callable(desc):
                    lyr = None
                    run = desc
                else:
                    raise TypeError(f"bad pipeline entry {desc!r}")
                if lyr is not None:
                    built.append(lyr)
                self.run_function.append(run)
                self._stage_of.append(stage)
        self._built = built

    def get_num_stages(self):
        return self._num_stages

    @property
    def parameters_by_stage(self):
        out = {s: [] for s in range(self._num_stages)}
        li = 0
        for run, stage in zip(self.run_function, self._stage_of):
            if isinstance(run, Layer):
                out[stage] += run.parameters()
        return out

    def forward(self, args):
        """Sequential execution with pp-axis resharding at boundaries:
        inside jit, XLA turns the constraint changes into
        collective-permutes between stage device groups."""
        from ..mesh import get_mesh, shard_constraint
        from jax.sharding import PartitionSpec as P
        mesh = get_mesh()
        pp_on = (mesh is not None and "pp" in mesh.dim_names
                 and mesh.get_dim_size("pp") > 1)
        x = args
        prev_stage = self._stage_of[0] if self._stage_of else 0
        for run, stage in zip(self.run_function, self._stage_of):
            if pp_on and stage != prev_stage and isinstance(x, Tensor):
                x = shard_constraint(x, P())
                prev_stage = stage
            x = run(x) if not isinstance(x, tuple) else run(*x)
        return x


class PipelineParallel(Layer):
    """reference: fleet/meta_parallel/pipeline_parallel.py:119. Provides
    train_batch(): splits the batch into microbatches and runs the
    GPipe-style accumulation loop; grads accumulate across microbatches
    on the tape exactly like the reference's accumulate_steps."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = (strategy.pipeline_configs if strategy is not None else
               {"accumulate_steps": 1})
        self._acc_steps = cfg.get("accumulate_steps", 1)

    def forward(self, data):
        return self._layers(data)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        from ...ops import manipulation, math as math_ops
        inputs, labels = data
        micro = self._acc_steps
        total = None
        b = inputs.shape[0]
        mb = max(b // micro, 1)
        for i in range(micro):
            xi = manipulation.slice(inputs, [0], [i * mb],
                                    [min((i + 1) * mb, b)])
            yi = manipulation.slice(labels, [0], [i * mb],
                                    [min((i + 1) * mb, b)])
            out = self._layers(xi)
            loss = (self._layers._loss_fn(out, yi)
                    if getattr(self._layers, "_loss_fn", None)
                    else out)
            loss = math_ops.scale(loss, 1.0 / micro)
            if scaler is not None:
                scaler.scale(loss).backward()
            else:
                loss.backward()
            total = loss if total is None else math_ops.add(total, loss)
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        out = self._layers(inputs)
        if compute_loss and getattr(self._layers, "_loss_fn", None):
            return self._layers._loss_fn(out, labels)
        return out
