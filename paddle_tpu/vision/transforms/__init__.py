"""Image transforms on numpy arrays (reference: python/paddle/vision/
transforms/). Operate on HWC uint8/float numpy (or PIL if installed);
ToTensor produces CHW float32 scaled to [0,1] like the reference."""
from __future__ import annotations

import math
import numbers
import random as pyrandom

import numpy as np

from ...core.tensor import Tensor, to_tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "RandomCrop",
           "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "BrightnessTransform", "Pad", "RandomResizedCrop",
           "to_tensor_transform", "normalize", "resize", "hflip", "vflip",
           "crop", "center_crop", "pad"]


def _as_hwc(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def resize(img, size, interpolation="bilinear"):
    img = _as_hwc(img)
    if isinstance(size, int):
        h, w = img.shape[:2]
        if h < w:
            new_h, new_w = size, int(size * w / h)
        else:
            new_h, new_w = int(size * h / w), size
    else:
        new_h, new_w = size
    import jax
    import jax.numpy as jnp
    method = {"nearest": "nearest", "bilinear": "linear",
              "bicubic": "cubic"}[interpolation]
    out = jax.image.resize(jnp.asarray(img, jnp.float32),
                           (new_h, new_w, img.shape[2]), method=method)
    out = np.asarray(out)
    if np.issubdtype(img.dtype, np.integer):
        out = np.clip(np.round(out), 0, 255).astype(img.dtype)
    return out


def hflip(img):
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    return _as_hwc(img)[::-1]


def crop(img, top, left, height, width):
    return _as_hwc(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    img = _as_hwc(img)
    h, w = img.shape[:2]
    th, tw = output_size
    top = max((h - th) // 2, 0)
    left = max((w - tw) // 2, 0)
    return crop(img, top, left, th, tw)


def pad(img, padding, fill=0, padding_mode="constant"):
    img = _as_hwc(img)
    if isinstance(padding, int):
        pads = ((padding, padding), (padding, padding), (0, 0))
    elif len(padding) == 2:
        pads = ((padding[1], padding[1]), (padding[0], padding[0]), (0, 0))
    else:
        l, t, r, b = padding
        pads = ((t, b), (l, r), (0, 0))
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    if mode == "constant":
        return np.pad(img, pads, mode=mode, constant_values=fill)
    return np.pad(img, pads, mode=mode)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    img = np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == "CHW":
        return (img - mean[:, None, None]) / std[:, None, None]
    return (img - mean) / std


def to_tensor_transform(img, data_format="CHW"):
    img = _as_hwc(img)
    arr = np.asarray(img, dtype=np.float32)
    if np.issubdtype(np.asarray(img).dtype, np.integer):
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return to_tensor(arr)


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor_transform(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        if isinstance(img, Tensor):
            img = img.numpy()
        n_chan = img.shape[0] if self.data_format == "CHW" else img.shape[-1]
        mean = (self.mean * n_chan)[:n_chan] if len(self.mean) < n_chan \
            else self.mean[:n_chan]
        std = (self.std * n_chan)[:n_chan] if len(self.std) < n_chan \
            else self.std[:n_chan]
        return normalize(img, mean, std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        if isinstance(size, int):
            size = (size, size)
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        img = _as_hwc(img)
        if self.padding is not None:
            img = pad(img, self.padding, self.fill, self.padding_mode)
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            img = pad(img, (0, 0, max(tw - w, 0), max(th - h, 0)),
                      self.fill, self.padding_mode)
            h, w = img.shape[:2]
        top = pyrandom.randint(0, h - th)
        left = pyrandom.randint(0, w - tw)
        return crop(img, top, left, th, tw)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if pyrandom.random() < self.prob:
            return hflip(img)
        return _as_hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if pyrandom.random() < self.prob:
            return vflip(img)
        return _as_hwc(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4, 4.0 / 3),
                 interpolation="bilinear", keys=None):
        if isinstance(size, int):
            size = (size, size)
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = _as_hwc(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = area * pyrandom.uniform(*self.scale)
            aspect = pyrandom.uniform(*self.ratio)
            cw = int(round(np.sqrt(target_area * aspect)))
            ch = int(round(np.sqrt(target_area / aspect)))
            if 0 < cw <= w and 0 < ch <= h:
                top = pyrandom.randint(0, h - ch)
                left = pyrandom.randint(0, w - cw)
                return resize(crop(img, top, left, ch, cw), self.size,
                              self.interpolation)
        return resize(center_crop(img, min(h, w)), self.size,
                      self.interpolation)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        if isinstance(img, Tensor):
            img = img.numpy()
        return _as_hwc(img).transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return _as_hwc(img)
        img = _as_hwc(img)
        dtype = img.dtype
        alpha = 1 + pyrandom.uniform(-self.value, self.value)
        out = np.clip(img.astype(np.float32) * alpha, 0,
                      255 if np.issubdtype(dtype, np.integer) else None)
        return out.astype(dtype)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class ContrastTransform(BaseTransform):
    """reference: transforms.py:831 — blend with the grayscale mean."""

    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return _as_hwc(img)
        img = _as_hwc(img)
        dtype = img.dtype
        # reference draws from [max(0, 1-value), 1+value] — never negative
        alpha = pyrandom.uniform(max(0.0, 1 - self.value),
                                 1 + self.value)
        f = img.astype(np.float32)
        mean = _grayscale_np(f).mean()
        out = np.clip(f * alpha + mean * (1 - alpha), 0,
                      255 if np.issubdtype(dtype, np.integer) else None)
        return out.astype(dtype)


class SaturationTransform(BaseTransform):
    """reference: transforms.py:876 — blend with per-pixel grayscale."""

    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return _as_hwc(img)
        img = _as_hwc(img)
        dtype = img.dtype
        alpha = pyrandom.uniform(max(0.0, 1 - self.value),
                                 1 + self.value)
        f = img.astype(np.float32)
        gray = _grayscale_np(f)
        out = np.clip(f * alpha + gray * (1 - alpha), 0,
                      255 if np.issubdtype(dtype, np.integer) else None)
        return out.astype(dtype)


class HueTransform(BaseTransform):
    """reference: transforms.py:919 — rotate hue in HSV space;
    value in [0, 0.5]."""

    def __init__(self, value, keys=None):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value should be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return _as_hwc(img)
        img = _as_hwc(img)
        if img.shape[-1] == 1:
            return img  # L-mode images pass through (PIL semantics)
        dtype = img.dtype
        shift = pyrandom.uniform(-self.value, self.value)
        f = img.astype(np.float32)
        scale = 255.0 if np.issubdtype(dtype, np.integer) else 1.0
        h, s, v = _rgb_to_hsv_np(f / scale)
        h = (h + shift) % 1.0
        out = _hsv_to_rgb_np(h, s, v) * scale
        return np.clip(out, 0, scale if scale > 1 else None) \
            .astype(dtype)


class ColorJitter(BaseTransform):
    """reference: transforms.py:964 — random order of brightness/
    contrast/saturation/hue jitters."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def _apply_image(self, img):
        order = list(range(4))
        pyrandom.shuffle(order)
        for i in order:
            img = self.transforms[i]._apply_image(img)
        return img


class Grayscale(BaseTransform):
    """reference: transforms.py:1676 — ITU-R 601-2 luma transform."""

    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        img = _as_hwc(img)
        dtype = img.dtype
        gray = _grayscale_np(img.astype(np.float32))
        out = np.repeat(gray, self.num_output_channels, axis=-1)
        return out.astype(dtype)


class RandomRotation(BaseTransform):
    """reference: transforms.py:1441 — rotate by a random angle
    (nearest-neighbor inverse mapping, constant fill)."""

    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if interpolation != "nearest":
            raise NotImplementedError(
                "RandomRotation: nearest interpolation only")
        if isinstance(degrees, (int, float)):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = pyrandom.uniform(*self.degrees)
        img = _as_hwc(img)
        out_shape = None
        if self.expand:
            h, w = img.shape[:2]
            a = math.radians(angle)
            nw = int(round(abs(w * math.cos(a)) + abs(h * math.sin(a))))
            nh = int(round(abs(h * math.cos(a)) + abs(w * math.sin(a))))
            out_shape = (nh, nw)
        return _affine_np(img, angle=angle, fill=self.fill,
                          out_shape=out_shape, center=self.center)


class RandomAffine(BaseTransform):
    """reference: transforms.py:1277 — rotation + translate + scale +
    shear with nearest-neighbor inverse mapping."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None,
                 keys=None):
        if interpolation != "nearest":
            raise NotImplementedError(
                "RandomAffine: nearest interpolation only")
        if isinstance(degrees, (int, float)):
            degrees = (-abs(degrees), abs(degrees))
        self.center = center
        self.degrees = degrees
        self.translate = translate
        self.scale_range = scale
        self.shear = shear
        self.fill = fill

    def _apply_image(self, img):
        img = _as_hwc(img)
        h, w = img.shape[:2]
        angle = pyrandom.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = pyrandom.uniform(-self.translate[0],
                                  self.translate[0]) * w
            ty = pyrandom.uniform(-self.translate[1],
                                  self.translate[1]) * h
        sc = 1.0
        if self.scale_range is not None:
            sc = pyrandom.uniform(*self.scale_range)
        shx = shy = 0.0
        if self.shear is not None:
            shr = self.shear if isinstance(self.shear, (list, tuple)) \
                else (-abs(self.shear), abs(self.shear))
            shx = pyrandom.uniform(shr[0], shr[1])
            if len(shr) == 4:  # [min_x, max_x, min_y, max_y]
                shy = pyrandom.uniform(shr[2], shr[3])
        return _affine_np(img, angle=angle, translate=(tx, ty),
                          scale=sc, shear=(shx, shy), fill=self.fill,
                          center=self.center)


class RandomErasing(BaseTransform):
    """reference: transforms.py:1723 — erase a random rectangle."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        img = _as_hwc(img).copy()
        if pyrandom.random() > self.prob:
            return img
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = pyrandom.uniform(*self.scale) * area
            ar = math.exp(pyrandom.uniform(math.log(self.ratio[0]),
                                           math.log(self.ratio[1])))
            eh = int(round(math.sqrt(target * ar)))
            ew = int(round(math.sqrt(target / ar)))
            if eh < h and ew < w and eh > 0 and ew > 0:
                top = pyrandom.randint(0, h - eh)
                left = pyrandom.randint(0, w - ew)
                img[top:top + eh, left:left + ew] = self.value
                break
        return img


def _grayscale_np(f):
    """ITU-R 601-2 luma, keepdims (f float HWC)."""
    if f.shape[-1] == 1:
        return f
    return (0.299 * f[..., 0:1] + 0.587 * f[..., 1:2]
            + 0.114 * f[..., 2:3])


def _rgb_to_hsv_np(rgb):
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    mx = np.max(rgb, axis=-1)
    mn = np.min(rgb, axis=-1)
    diff = mx - mn + 1e-12
    h = np.zeros_like(mx)
    m = mx == r
    h[m] = ((g - b)[m] / diff[m]) % 6
    m = mx == g
    h[m] = (b - r)[m] / diff[m] + 2
    m = mx == b
    h[m] = (r - g)[m] / diff[m] + 4
    h = h / 6.0
    s = np.where(mx > 0, (mx - mn) / (mx + 1e-12), 0.0)
    return h, s, mx


def _hsv_to_rgb_np(h, s, v):
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = i.astype(np.int32) % 6
    out = np.zeros(h.shape + (3,), np.float32)
    for idx, (rr, gg, bb) in enumerate([(v, t, p), (q, v, p), (p, v, t),
                                        (p, q, v), (t, p, v),
                                        (v, p, q)]):
        m = i == idx
        out[m, 0] = rr[m]
        out[m, 1] = gg[m]
        out[m, 2] = bb[m]
    return out


def _affine_np(img, angle=0.0, translate=(0.0, 0.0), scale=1.0,
               shear=0.0, fill=0, out_shape=None, center=None):
    """Inverse-mapped nearest-neighbor affine about `center` (default:
    image center); out_shape (oh, ow) renders onto an expanded/shrunk
    canvas whose center maps to the source center (RandomRotation
    expand=True)."""
    h, w = img.shape[:2]
    if center is not None:
        cx, cy = float(center[0]), float(center[1])
    else:
        cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    # PIL/paddle convention: positive angle = counter-clockwise; image
    # y axis points down, so negate for the math-convention matrix
    a = -math.radians(angle)
    if isinstance(shear, (list, tuple)):
        shx, shy = (math.radians(shear[0]), math.radians(shear[1]))
    else:
        shx, shy = math.radians(shear), 0.0
    # forward matrix M = T(center) R S Sh T(-center) + translate
    m00 = (math.cos(a) - math.sin(a) * math.tan(shy)) * scale
    m01 = (-math.sin(a + shx) / max(math.cos(shx), 1e-9)) * scale
    m10 = (math.sin(a) + math.cos(a) * math.tan(shy)) * scale
    m11 = (math.cos(a + shx) / max(math.cos(shx), 1e-9)) * scale
    det = m00 * m11 - m01 * m10
    if abs(det) < 1e-12:
        return img
    i00, i01 = m11 / det, -m01 / det
    i10, i11 = -m10 / det, m00 / det
    oh, ow = out_shape if out_shape is not None else (h, w)
    if center is not None and out_shape is None:
        ocy, ocx = cy, cx
    else:
        ocy, ocx = (oh - 1) / 2.0, (ow - 1) / 2.0
    ys, xs = np.meshgrid(np.arange(oh), np.arange(ow), indexing="ij")
    dx = xs - ocx - translate[0]
    dy = ys - ocy - translate[1]
    sx = i00 * dx + i01 * dy + cx
    sy = i10 * dx + i11 * dy + cy
    sxr = np.round(sx).astype(np.int64)
    syr = np.round(sy).astype(np.int64)
    valid = (sxr >= 0) & (sxr < w) & (syr >= 0) & (syr < h)
    out = np.full((oh, ow) + img.shape[2:], fill, img.dtype)
    out[valid] = img[syr[valid], sxr[valid]]
    return out


__all__ += ["ContrastTransform", "SaturationTransform", "HueTransform",
            "ColorJitter", "Grayscale", "RandomRotation", "RandomAffine",
            "RandomErasing"]
