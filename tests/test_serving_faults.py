"""serving.faults + recovery layers: no request dies because a replica did.

The chaos oracle (ISSUE acceptance): with a FaultInjector killing or
hanging a replica after >= 1 token has streamed, every client receives
the EXACT greedy token sequence the solo CompiledGenerator produces —
zero truncated or duplicated tokens (mid-stream migration re-prefills
prompt + emitted history on a survivor); a poisoned request 422s alone
while its co-residents complete token-identically on the same replica.

Pure units (no threads, fake clocks): CircuitBreaker state machine,
ReplicaWatchdog staleness scan, FaultInjector determinism, the
Ticket retry-backoff and cancel-vs-retry lock fixes.
"""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
from paddle_tpu.serving import (FaultInjector, InjectedFault,
                                PoisonedRequest, SamplingParams,
                                ServingEngine, prometheus_render,
                                resolve_faults)
from paddle_tpu.serving.http import (CircuitBreaker, EngineDriver,
                                     ReplicaHung, ReplicaWatchdog,
                                     Router)

_MODELS = {}


def tiny_gpt():
    m = _MODELS.get("gpt")
    if m is None:
        paddle.seed(7)
        cfg = GPTConfig(vocab_size=97, hidden_size=32,
                        num_hidden_layers=2, num_attention_heads=4,
                        intermediate_size=64,
                        max_position_embeddings=128,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
        m = _MODELS["gpt"] = GPTForCausalLM(cfg)
        m.eval()
    return m


def oracle_greedy(model, prompt, n_new):
    out = model.generate(paddle.to_tensor(np.asarray(prompt)[None]),
                         max_new_tokens=n_new).numpy()
    return out[0, len(prompt):].tolist()


def wait_until(pred, timeout=30.0, interval=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def make_cluster(n_replicas=2, *, faults=None, warm=True,
                 router_kw=None, **engine_kw):
    """N warmed engines behind started drivers + router (no HTTP —
    Ticket.events() is the consumption point under test). Warming
    compiles every program BEFORE any fault can fire, so an injected
    hang is the only thing that ever stalls a heartbeat."""
    model = tiny_gpt()
    kw = dict(num_slots=2, max_len=64)
    kw.update(engine_kw)
    engines = [ServingEngine(model, **kw) for _ in range(n_replicas)]
    if warm:
        for e in engines:
            e.generate([np.array([1, 2, 3])],
                       SamplingParams(max_new_tokens=2))
    drivers = [EngineDriver(e, name=f"replica-{i}", faults=faults)
               for i, e in enumerate(engines)]
    router = Router(drivers, **(router_kw or {})).start()
    return model, engines, drivers, router


def consume(ticket, on_token=None, poll_s=0.01):
    """Drain a ticket; returns (tokens, done_reason_or_None, error)."""
    tokens = []
    for kind, val in ticket.events(poll_s=poll_s):
        if kind == "token":
            tokens.append(val)
            if on_token is not None:
                on_token(tokens)
        elif kind == "done":
            return tokens, val, None
        elif kind == "error":
            return tokens, None, val
    return tokens, None, None


# -- FaultInjector units ----------------------------------------------------
class TestFaultInjector:
    def test_kill_fires_once_at_threshold_step(self):
        inj = FaultInjector()
        inj.kill_at_step("r0", 3)
        for s in range(3):
            inj.on_step("r0", s)          # below threshold: no-op
            inj.on_step("r1", 99)         # other replica: never
        with pytest.raises(InjectedFault) as ei:
            inj.on_step("r0", 3)
        assert ei.value.kind == "kill"
        inj.on_step("r0", 4)              # one-shot: consumed
        assert inj.kills_fired == 1

    def test_fail_kth_add_request_scoped_and_global(self):
        inj = FaultInjector()
        inj.fail_add_request(2)                    # global ordinal 2
        inj.fail_add_request(1, replica="r1")      # r1's first
        inj.on_add_request("r0", "a")              # global #1: ok
        with pytest.raises(InjectedFault):
            inj.on_add_request("r1", "b")          # r1 #1 AND global #2
        inj.on_add_request("r0", "c")
        inj.on_add_request("r1", "d")
        assert inj.add_fails_fired == 1

    def test_poison_hits_only_that_request(self):
        inj = FaultInjector()
        inj.poison("req-7")
        inj.on_engine_step("r0", ["req-1", "req-2"])
        with pytest.raises(InjectedFault) as ei:
            inj.on_engine_step("r0", ["req-1", "req-7"])
        assert ei.value.kind == "poison"
        assert ei.value.request_id == "req-7"
        inj.clear_poison("req-7")
        inj.on_engine_step("r0", ["req-7"])
        assert inj.poison_hits == 1

    def test_env_spec_parsing(self, monkeypatch):
        monkeypatch.setenv(
            "PADDLE_TPU_FAULTS",
            "kill:replica-0@40; hang:replica-1@10x5.0;"
            "fail_add:3;fail_add:replica-0@7;poison:req-9")
        inj = resolve_faults()
        assert inj._kills == {"replica-0": [40]}
        assert inj._hangs == {"replica-1": [(10, 5.0)]}
        assert inj._fail_adds == {"*": {3}, "replica-0": {7}}
        assert inj._poisoned == {"req-9"}
        monkeypatch.setenv("PADDLE_TPU_FAULTS", "")
        assert resolve_faults() is None
        with pytest.raises(ValueError):
            FaultInjector.parse("explode:everything")

    def test_chaos_schedule_reproducible_and_leaves_survivor(self):
        replicas = [f"replica-{i}" for i in range(3)]
        a = FaultInjector(seed=11).chaos_schedule(replicas, kills=1,
                                                  hangs=1)
        b = FaultInjector(seed=11).chaos_schedule(replicas, kills=1,
                                                  hangs=1)
        assert a == b and len(a) == 2          # seeded: identical
        victims = {e.split(":")[1].split("@")[0] for e in a}
        assert len(victims) == 2               # >= 1 replica untouched
        c = FaultInjector(seed=12).chaos_schedule(replicas, kills=1,
                                                  hangs=1)
        assert a != c                          # seed actually matters


# -- circuit breaker + watchdog units (fake clock, no threads) --------------
class TestCircuitBreaker:
    def test_opens_after_threshold_consecutive_failures(self):
        b = CircuitBreaker(failure_threshold=3, open_s=10.0)
        assert b.allow(0.0)
        b.record_failure(1.0)
        b.record_failure(2.0)
        assert b.state(2.0) == "closed" and b.allow(2.0)
        b.record_failure(3.0)
        assert b.state(3.0) == "open" and not b.allow(3.0)
        assert b.opens_total == 1

    def test_success_resets_consecutive_count(self):
        b = CircuitBreaker(failure_threshold=2, open_s=10.0)
        b.record_failure(1.0)
        b.record_success(2.0)
        b.record_failure(3.0)
        assert b.state(3.0) == "closed"       # never 2 consecutive

    def test_half_open_probe_success_closes_failure_reopens(self):
        b = CircuitBreaker(failure_threshold=1, open_s=5.0)
        b.record_failure(0.0)
        assert not b.allow(4.9)               # still cooling off
        assert b.allow(5.0)                   # half-open: one probe
        assert b.state(5.0) == "half_open"
        b.record_failure(6.0)                 # probe failed: reopen
        assert b.state(6.0) == "open" and not b.allow(10.9)
        assert b.allow(11.0)                  # cooled off again
        b.record_success(11.5)                # probe succeeded
        assert b.state(12.0) == "closed"
        b.trip(13.0)                          # death: immediate open
        assert b.state(13.0) == "open"

    def test_watchdog_condemns_only_stale_started_replicas(self):
        class FakeDriver:
            def __init__(self, name, beat, started=True, dead=False,
                         draining=False):
                self.name, self.last_beat = name, beat
                self.started, self.dead = started, dead
                self.draining = draining
                self.condemned_with = None

            def condemn(self, exc=None):
                self.condemned_with = exc
                self.dead = True    # mirrors EngineDriver.condemn

        t = [100.0]
        fresh = FakeDriver("fresh", beat=99.8)
        stale = FakeDriver("stale", beat=90.0)
        unborn = FakeDriver("unborn", beat=None)
        unstarted = FakeDriver("unstarted", beat=1.0, started=False)
        dead = FakeDriver("dead", beat=1.0, dead=True)
        draining = FakeDriver("draining", beat=1.0, draining=True)
        kills = []
        wd = ReplicaWatchdog(
            [fresh, stale, unborn, unstarted, dead, draining],
            timeout_s=1.0, clock=lambda: t[0],
            on_kill=lambda d: kills.append(d.name))
        assert wd.poll() == [stale]
        assert isinstance(stale.condemned_with, ReplicaHung)
        assert kills == ["stale"] and wd.kills_total == 1
        for d in (fresh, unborn, unstarted, dead, draining):
            assert d.condemned_with is None
        t[0] = 102.0                           # now fresh went stale too
        assert wd.poll() == [fresh]
        assert wd.kills_total == 2


# -- Ticket retry semantics (satellite fixes) -------------------------------
class TestTicketRetry:
    def test_first_failover_attempt_has_no_backoff_sleep(self):
        """Attempt 0 re-places IMMEDIATELY; backoff paces attempts
        1..N-1 starting at backoff_base_s (satellite fix — previously
        every failover slept before even trying). The router's jitter
        hook fires exactly once per backoff sleep, so counting its
        invocations counts the sleeps without patching time.sleep."""
        jitter_calls = []

        def jitter():
            jitter_calls.append(1)
            return 1.0

        model, engines, drivers, router = make_cluster(
            2, router_kw=dict(backoff_base_s=0.05, jitter=jitter))
        t = router.submit(np.array([3, 14, 15], np.int64),
                          SamplingParams(max_new_tokens=30))
        victim = t.driver
        assert wait_until(lambda: len(t.request.output_tokens) > 0)
        victim.kill()
        toks, done, err = consume(t)
        assert done == "length" and err is None
        # the failover needed zero backoff sleeps: a survivor was free
        assert jitter_calls == []
        assert t.attempts == 2 and t.migrations == 1
        router.drain()

    def test_cancel_racing_retry_never_cancels_stale_pair(self):
        """cancel() during a mid-failover re-place must cancel the NEW
        attempt, not the dead one: _retry re-checks the flag under the
        router lock after swapping the pair in."""
        model, engines, drivers, router = make_cluster(2)
        t = router.submit(np.array([3, 14, 15, 9], np.int64),
                          SamplingParams(max_new_tokens=60))
        first = t.request
        assert wait_until(lambda: len(first.output_tokens) > 2)
        # freeze the race deterministically: cancel flag flips while
        # the retry is between _place and the lock re-check
        t._cancelled = True
        t._failover(first)
        new_req = t.request
        assert new_req is not first
        assert wait_until(lambda: new_req.finished, timeout=30)
        assert new_req.finish_reason == "cancelled"
        router.drain()
        for e in engines:
            e.pool.assert_quiesced()


# -- mid-stream migration vs the solo oracle --------------------------------
class TestMigration:
    def test_midstream_kill_migrates_token_identical(self):
        """THE chaos oracle: kill the serving replica after >= 3 tokens
        have streamed; the client's full sequence equals solo
        CompiledGenerator greedy decode — no truncation, no dupes —
        and usage reports the migration."""
        model, engines, drivers, router = make_cluster(2)
        prompt = [3, 14, 15, 9]
        want = oracle_greedy(model, prompt, 24)
        t = router.submit(np.array(prompt, np.int64),
                          SamplingParams(max_new_tokens=24))
        victim = t.driver

        def kill_at_3(tokens):
            if len(tokens) == 3 and not victim.dead:
                victim.kill()

        toks, done, err = consume(t, on_token=kill_at_3)
        assert err is None and done == "length"
        assert toks == want
        out = t.output()
        assert out.token_ids == want
        assert out.prompt_token_ids == prompt
        assert out.migrations == 1 and t.attempts == 2
        assert router.migrations_total == 1
        assert router.retries_total == 1
        router.drain()
        for e in engines:
            e.pool.assert_quiesced()

    def test_migration_under_page_pressure_and_eviction(self):
        """Migration onto a survivor whose pool is tight: the re-placed
        prompt (original + emitted history) must evict prefix-cache
        leaves to fit, and the continuation stays token-identical
        through the eviction."""
        model, engines, drivers, router = make_cluster(
            2, num_slots=2, max_len=64, page_size=8, num_pages=17)
        # dirty the survivor's pool with finished requests so its
        # radix cache holds parked pages the migration must evict
        for p in ([5, 6, 7, 8], [9, 10, 11], [12, 13]):
            drivers[1].submit(np.array(p, np.int64),
                              SamplingParams(max_new_tokens=8))
        assert wait_until(
            lambda: engines[1].pool.cached_pages > 0, timeout=30)
        prompt = [3, 14, 15, 9, 26, 5]
        want = oracle_greedy(model, prompt, 40)
        t = router.submit(np.array(prompt, np.int64),
                          SamplingParams(max_new_tokens=40))
        assert t.driver is drivers[0]          # survivor is loaded
        def kill_at_4(tokens):
            if len(tokens) == 4 and not drivers[0].dead:
                drivers[0].kill()
        toks, done, err = consume(t, on_token=kill_at_4)
        assert err is None and done == "length"
        assert toks == want and t.migrations == 1
        router.drain()
        engines[1].pool.assert_quiesced()

    def test_migration_with_prefix_cache_off(self):
        """The oracle holds with the radix cache disabled — migration
        re-prefills the full prompt + history the slow way."""
        model, engines, drivers, router = make_cluster(
            2, prefix_cache=False)
        prompt = [26, 5, 35]
        want = oracle_greedy(model, prompt, 20)
        t = router.submit(np.array(prompt, np.int64),
                          SamplingParams(max_new_tokens=20))
        victim = t.driver
        def kill_at_2(tokens):
            if len(tokens) == 2 and not victim.dead:
                victim.kill()
        toks, done, err = consume(t, on_token=kill_at_2)
        assert err is None and done == "length" and toks == want
        assert t.output().migrations == 1
        router.drain()
        for e in engines:
            e.pool.assert_quiesced()

    def test_double_kill_migrates_twice(self):
        """Two migrations of one stream (3 replicas, kill two in
        sequence): still token-identical, migrations == 2."""
        model, engines, drivers, router = make_cluster(3)
        prompt = [7, 8, 9, 10]
        want = oracle_greedy(model, prompt, 30)
        t = router.submit(np.array(prompt, np.int64),
                          SamplingParams(max_new_tokens=30))
        killed = []

        def killer(tokens):
            n = len(tokens)
            if n in (3, 12) and n not in killed:
                killed.append(n)
                t.driver.kill()

        toks, done, err = consume(t, on_token=killer)
        assert err is None and done == "length"
        assert toks == want
        assert t.migrations == 2 and t.attempts == 3
        assert t.output().migrations == 2
        router.drain()

    def test_failed_migration_ends_stream_as_replica_failure(self):
        """When no survivor exists, the stream closes with the partial
        tokens and reason replica_failure (the pre-migration
        semantics are the documented fallback)."""
        model, engines, drivers, router = make_cluster(
            1, router_kw=dict(max_retries=2, backoff_base_s=0.0))
        t = router.submit(np.array([3, 14, 15], np.int64),
                          SamplingParams(max_new_tokens=40))
        assert wait_until(lambda: len(t.request.output_tokens) > 1)
        drivers[0].kill()
        toks, done, err = consume(t)
        assert done == "replica_failure" and len(toks) >= 1
        assert t.error is not None and t.migrations == 0


# -- watchdog end to end ----------------------------------------------------
class TestWatchdogEndToEnd:
    def test_hung_replica_condemned_and_stream_migrates(self):
        """An injected hang (no raise, heartbeat goes stale) is caught
        by the watchdog, the replica is condemned, its breaker trips
        open, and the resident stream migrates token-identically."""
        inj = FaultInjector()
        model, engines, drivers, router = make_cluster(
            2, faults=inj,
            router_kw=dict(watchdog_timeout_s=0.4,
                           watchdog_interval_s=0.1))
        prompt = [3, 14, 15, 9]
        want = oracle_greedy(model, prompt, 25)
        t = router.submit(np.array(prompt, np.int64),
                          SamplingParams(max_new_tokens=25))
        victim = t.driver
        hung = []

        def hang_at_3(tokens):
            if len(tokens) == 3 and not hung:
                hung.append(1)
                inj.hang_at_step(victim.name, 0, 60.0)

        toks, done, err = consume(t, on_token=hang_at_3)
        assert err is None and done == "length"
        assert toks == want and t.migrations == 1
        assert router.watchdog_kills_total == 1
        assert victim.dead and not victim.healthy
        assert isinstance(victim.death_exc, ReplicaHung)
        assert router.breakers[victim.name].state(
            time.monotonic()) == "open"
        inj.release_hangs()                 # let the wedged pump exit
        router.drain()

    def test_breaker_takes_flapping_replica_out_of_rotation(self):
        """Injected add_request failures on one replica open its
        breaker after `breaker_failures` consecutive placement
        failures; traffic then lands on the healthy replica WITHOUT
        paying the failed submit, and a half-open probe readmits the
        flapper once the injected fault schedule is exhausted."""
        inj = FaultInjector()
        for k in range(1, 4):
            inj.fail_add_request(k, replica="replica-0")
        model, engines, drivers, router = make_cluster(
            2, faults=inj,
            router_kw=dict(breaker_failures=3, breaker_open_s=0.2))
        outs = []
        for i in range(5):
            t = router.submit(np.array([3 + i, 14, 15], np.int64),
                              SamplingParams(max_new_tokens=2))
            toks, done, err = consume(t)
            assert done == "length" and err is None
            outs.append(t.driver.name)
        # every request SERVED despite the flapper (placement absorbed
        # the injected failures), breaker opened after 3 in a row
        assert inj.add_fails_fired == 3
        assert router.breakers["replica-0"].opens_total >= 1
        assert all(n == "replica-1" for n in outs)
        time.sleep(0.25)                    # past breaker_open_s
        t = router.submit(np.array([40, 41, 42], np.int64),
                          SamplingParams(max_new_tokens=2))
        toks, done, err = consume(t)
        assert done == "length"
        # the half-open probe's success closed the breaker again
        assert wait_until(lambda: router.breakers["replica-0"].state(
            time.monotonic()) == "closed", timeout=5)
        router.drain()


# -- poison quarantine ------------------------------------------------------
class TestPoisonQuarantine:
    @pytest.mark.parametrize("unified", [True, False])
    def test_bisect_isolates_poison_neighbors_token_identical(
            self, unified):
        """A poisoned resident deterministically kills the step; the
        engine bisects the batch, 422s it ALONE (typed
        PoisonedRequest) and every innocent co-resident completes
        bit-identical to solo decode on the SAME replica."""
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=4, max_len=64,
                            unified=unified)
        inj = FaultInjector()
        eng.step_fault_hook = \
            lambda ids: inj.on_engine_step("r0", ids)
        prompts = [[3, 14, 15, 9], [26, 5, 35], [1, 2, 3, 4, 5, 6],
                   [7, 8, 9]]
        reqs = [eng.add_request(np.array(p),
                                SamplingParams(max_new_tokens=10))
                for p in prompts]
        inj.poison(reqs[1].request_id)
        eng.run()
        assert reqs[1].finish_reason == "poisoned"
        assert isinstance(reqs[1].error, PoisonedRequest)
        for i in (0, 2, 3):
            assert reqs[i].finish_reason == "length"
            assert reqs[i].output_tokens == oracle_greedy(
                model, prompts[i], 10), (unified, i)
        assert eng.metrics.requests_poisoned == 1
        assert eng.metrics.snapshot()["requests"]["poisoned"] == 1
        eng.drain()
        eng.pool.assert_quiesced()

    def test_poison_arriving_mid_decode_is_still_isolated(self):
        """Poison injected after tokens already streamed (a decode-time
        poison, not an admission-time one): the victim keeps its
        emitted prefix, the neighbor is unharmed."""
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=2, max_len=64)
        inj = FaultInjector()
        eng.step_fault_hook = \
            lambda ids: inj.on_engine_step("r0", ids)
        a = eng.add_request(np.array([3, 14, 15, 9]),
                            SamplingParams(max_new_tokens=12))
        b = eng.add_request(np.array([26, 5, 35]),
                            SamplingParams(max_new_tokens=12))
        for _ in range(5):
            eng.step()
        assert len(a.output_tokens) > 0
        inj.poison(a.request_id)
        eng.run()
        assert a.finish_reason == "poisoned"
        assert b.finish_reason == "length"
        assert b.output_tokens == oracle_greedy(model, [26, 5, 35], 12)
        eng.drain()
        eng.pool.assert_quiesced()

    def test_global_fault_is_not_blamed_on_a_request(self):
        """A fault that does NOT track one request (every probe
        raises) fails the verdict check and propagates as replica
        death instead of poisoning an innocent."""
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=2, max_len=64)
        boom = RuntimeError("global device fault")

        def hook(ids):
            raise boom

        eng.step_fault_hook = hook
        eng.add_request(np.array([3, 14, 15]),
                        SamplingParams(max_new_tokens=4))
        eng.add_request(np.array([5, 6, 7]),
                        SamplingParams(max_new_tokens=4))
        with pytest.raises(RuntimeError) as ei:
            eng.run()
        assert ei.value is boom
        # nothing was spuriously quarantined
        assert eng.metrics.requests_poisoned == 0

    def test_poisoned_request_is_422_over_http_and_rendered(self):
        """Full vertical: HTTP client sends the poisoned request, gets
        a typed 422 with finish_reason "poisoned"; the co-resident
        stream completes; /metrics renders poisoned_total,
        migrations_total and per-replica breaker_state."""
        import http.client
        import json as json_mod

        from paddle_tpu.serving.http import serve

        model = tiny_gpt()
        inj = FaultInjector()
        engines = [ServingEngine(model, num_slots=2, max_len=64)]
        for e in engines:
            e.generate([np.array([1, 2, 3])],
                       SamplingParams(max_new_tokens=2))
        server = serve(engines, poll_interval_s=0.01, faults=inj)
        addr = server.server_address[:2]
        try:
            inj.poison("req-poison")
            # pin the engine-level id of the poisoned request via the
            # driver (the HTTP layer auto-generates ids otherwise)
            results = {}

            def victim():
                conn = http.client.HTTPConnection(*addr, timeout=60)
                conn.request("POST", "/v1/completions",
                             json_mod.dumps({"prompt": [26, 5, 35],
                                             "max_tokens": 8}),
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                results["victim"] = (resp.status,
                                     json_mod.loads(resp.read()))
                conn.close()

            # identify the auto-generated id: submit through the
            # driver directly with a pinned id instead
            drv = server.router.drivers[0]
            neighbor = drv.submit(np.array([3, 14, 15, 9], np.int64),
                                  SamplingParams(max_new_tokens=20))
            poisoned = drv.submit(np.array([26, 5, 35], np.int64),
                                  SamplingParams(max_new_tokens=8),
                                  request_id="req-poison")
            assert wait_until(lambda: poisoned.finished, timeout=30)
            assert poisoned.finish_reason == "poisoned"
            assert wait_until(lambda: neighbor.finished, timeout=30)
            assert neighbor.finish_reason == "length"
            assert neighbor.output_tokens == oracle_greedy(
                model, [3, 14, 15, 9], 20)
            # protocol mapping: poisoned output -> 422
            from paddle_tpu.serving.http.protocol import \
                status_for_output
            assert status_for_output(poisoned.output()) == 422
            # /metrics renders the resilience series
            conn = http.client.HTTPConnection(*addr, timeout=30)
            conn.request("GET", "/metrics")
            text = conn.getresponse().read().decode()
            conn.close()
            assert 'paddle_serving_poisoned_total' \
                '{replica="replica-0"} 1' in text
            assert 'paddle_serving_requests_total{outcome="poisoned",' \
                'replica="replica-0"} 1' in text
            assert "paddle_serving_migrations_total 0" in text
            assert "paddle_serving_watchdog_kills_total 0" in text
            assert 'paddle_serving_breaker_state{replica="replica-0",' \
                'state="closed"} 0' in text
            assert "paddle_serving_retries_total 0" in text
        finally:
            server.drain()
        engines[0].pool.assert_quiesced()


# -- HTTP chaos oracle ------------------------------------------------------
class TestHTTPMigration:
    def test_sse_stream_survives_replica_kill_usage_counts_it(self):
        """SSE client vs a 2-replica server: its replica dies after
        tokens streamed; the client reads the EXACT oracle sequence to
        [DONE] with finish_reason length and usage.migrations == 1."""
        import http.client
        import json as json_mod

        from paddle_tpu.serving.http import serve

        model = tiny_gpt()
        engines = [ServingEngine(model, num_slots=2, max_len=64)
                   for _ in range(2)]
        for e in engines:
            e.generate([np.array([1, 2, 3])],
                       SamplingParams(max_new_tokens=2))
        server = serve(engines, poll_interval_s=0.01)
        addr = server.server_address[:2]
        try:
            prompt = [3, 14, 15, 9]
            want = oracle_greedy(model, prompt, 30)
            conn = http.client.HTTPConnection(*addr, timeout=120)
            conn.request("POST", "/v1/completions",
                         json_mod.dumps({"prompt": prompt,
                                         "stream": True,
                                         "max_tokens": 30}),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            tokens, fin, usage = [], None, None
            while True:
                line = resp.readline()
                if not line or line.strip() == b"data: [DONE]":
                    break
                if not line.startswith(b"data: "):
                    continue
                frame = json_mod.loads(line[6:])
                choice = frame["choices"][0]
                if choice["token"] is not None:
                    tokens.append(choice["token"])
                    if len(tokens) == 3:
                        victim = next(
                            d for d in server.router.drivers
                            if d.engine.scheduler.running)
                        victim.kill()
                if choice["finish_reason"]:
                    fin = choice["finish_reason"]
                    usage = frame.get("usage")
            conn.close()
            assert fin == "length"
            assert tokens == want          # zero truncated/duplicated
            assert usage["migrations"] == 1
            assert usage["completion_tokens"] == 30
            assert server.router.migrations_total == 1
        finally:
            server.drain()


# -- chaos soak (slow) ------------------------------------------------------
@pytest.mark.slow
def test_chaos_soak_random_schedule_token_identity():
    """~30s soak: 3 replicas under continuous traffic while a SEEDED
    random schedule kills one replica, hangs another past the watchdog
    timeout, and poisons every 7th request. Every non-poisoned request
    must finish token-identical to the solo oracle (migrated or not);
    every poisoned request must 422 alone; the survivor's pool must
    quiesce."""
    inj = FaultInjector(seed=1234)
    model, engines, drivers, router = make_cluster(
        3, faults=inj, num_slots=2, max_len=64,
        router_kw=dict(watchdog_timeout_s=1.0,
                       watchdog_interval_s=0.25))
    events = inj.chaos_schedule(
        [d.name for d in drivers], kills=1, hangs=1, hang_s=120.0,
        max_step=60, keep_alive=1)
    assert len(events) == 2
    deadline = time.monotonic() + 25.0
    results = []
    lock = threading.Lock()
    oracle_cache = {}

    def want(prompt, n):
        key = (tuple(prompt), n)
        if key not in oracle_cache:
            oracle_cache[key] = oracle_greedy(model, list(prompt), n)
        return oracle_cache[key]

    def client(i):
        rng = np.random.RandomState(i)
        prompt = (1 + rng.randint(0, 90, size=3 + (i % 5))).tolist()
        n = 6 + (i % 9)
        try:
            t = router.submit(np.array(prompt, np.int64),
                              SamplingParams(max_new_tokens=n))
        except Exception as exc:
            with lock:
                results.append((i, "submit_error", repr(exc)))
            return
        if i % 7 == 0:
            inj.poison(t.request.request_id)
        toks, done, err = consume(t)
        with lock:
            if i % 7 == 0:
                results.append((i, "poisoned_ok"
                                if done == "poisoned" else "BAD",
                                done or repr(err)))
                inj.clear_poison(t.request.request_id)
            elif done == "length" and toks == want(prompt, n):
                results.append((i, "ok", t.migrations))
            else:
                results.append((i, "BAD", (done, repr(err), toks,
                                           want(prompt, n))))

    i = 0
    threads = []
    while time.monotonic() < deadline:
        threads = [th for th in threads if th.is_alive()]
        while len(threads) < 6:
            th = threading.Thread(target=client, args=(i,))
            th.start()
            threads.append(th)
            i += 1
        time.sleep(0.02)
    for th in threads:
        th.join(60)
    inj.release_hangs()
    bad = [r for r in results if r[1] == "BAD"]
    assert not bad, bad[:5]
    oks = [r for r in results if r[1] == "ok"]
    assert len(oks) > 20
    # at least one fault actually fired against live traffic
    assert inj.kills_fired + inj.hangs_fired + inj.poison_hits >= 1
    router.drain()
    for d, e in zip(drivers, engines):
        if not d.dead:
            e.pool.assert_quiesced()


@pytest.mark.slow
def test_serving_bench_chaos_smoke(tmp_path, monkeypatch):
    """`serving_bench.py --smoke --chaos` in-process: the schema-v6
    report gains the chaos section and its own assertions hold
    (truncated_streams == 0 with a replica killed mid-load)."""
    import importlib.util
    import json as json_mod
    import os
    import sys
    script = os.path.join(os.path.dirname(__file__), os.pardir,
                          "scripts", "serving_bench.py")
    spec = importlib.util.spec_from_file_location(
        "serving_bench_chaos", script)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = str(tmp_path / "BENCH_serving.json")
    monkeypatch.setattr(sys, "argv",
                        ["serving_bench.py", "--smoke", "--chaos",
                         "--requests", "4", "--out", out])
    mod.main()
    with open(out) as f:
        report = json_mod.load(f)
    assert report["schema_version"] == 19
    chaos = report["chaos"]
    assert chaos["replicas"] == 2
    assert chaos["truncated_streams"] == 0
    assert chaos["completed"] == 4
    assert chaos["kills_fired"] >= 1
    assert chaos["fault_free"]["truncated_streams"] == 0
    assert chaos["goodput_tokens_per_sec"] > 0
