"""Flash attention (Pallas, TPU).

TPU-native replacement for the reference's fused FMHA CUDA
(paddle/fluid/operators/fused/fused_attention_op.cu, fmha_ref.h). Online
softmax over K/V blocks: running (m, l, acc) scratch in VMEM, one MXU
dot per (q-block, k-block) pair, no [L, L] logits materialized in HBM.

Forward runs the kernel; backward recomputes attention with the plain-XLA
reference math via jax.custom_vjp (the standard TPU remat trade — see
SURVEY.md §7 "fused_attention → Pallas flash-attention custom-calls").
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
               scale, causal, block_q, block_k, q_len, kv_len):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_kv = pl.num_programs(2)

    neg_inf = jnp.float32(_NEG_INF)
    scale32 = jnp.float32(scale)

    @pl.when(kj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, neg_inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # bottom-right causal alignment (matches the XLA reference: query i may
    # see keys j <= i + (kv_len - q_len)); whole k-blocks past the last
    # query of this q-block are predicated away.
    offset = kv_len - q_len
    run = True
    if causal:
        run = kj * block_k <= qi * block_q + block_q - 1 + offset

    @pl.when(run)
    def _compute():
        q = q_ref[0]                       # [bq, d]
        k = k_ref[0]                       # [bk, d]
        v = v_ref[0]                       # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale32  # [bq, bk]

        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_pos < kv_len
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = jnp.logical_and(valid, q_pos + offset >= k_pos)
        s = jnp.where(valid, s, neg_inf)

        m_prev = m_ref[:, :1]              # [bq, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kj == n_kv - 1)
    def _finalize():
        l = l_ref[:, :1]
        o_ref[0] = (acc_ref[:] /
                    jnp.maximum(l, jnp.float32(1e-30))).astype(o_ref.dtype)


def _pad_to(x, axis, mult):
    n = x.shape[axis]
    rem = (-n) % mult
    if rem == 0:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, rem)
    return jnp.pad(x, pads)


def _flash_fwd_bhld(q, k, v, causal, scale, block_q, block_k):
    """q: [BH, Lq, D], k/v: [BH, Lk, D] -> [BH, Lq, D]."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    block_q = min(block_q, max(128, 1))
    qp = _pad_to(q, 1, block_q)
    kp = _pad_to(k, 1, block_k)
    vp = _pad_to(v, 1, block_k)
    n_q = qp.shape[1] // block_q
    n_k = kp.shape[1] // block_k

    kernel = functools.partial(
        _fa_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, q_len=lq, kv_len=lk)
    # Mosaic rejects i64 index arithmetic; trace the kernel in 32-bit
    # mode regardless of the global jax_enable_x64 (paddle int64 parity)
    with jax.enable_x64(False):
        return _call_kernel(kernel, qp, kp, vp, bh, n_q, n_k, block_q,
                            block_k, d, q.dtype)[:, :lq]


def _call_kernel(kernel, qp, kp, vp, bh, n_q, n_k, block_q, block_k, d,
                 dtype):
    out = pl.pallas_call(
        kernel,
        grid=(bh, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qp.shape, dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(qp, kp, vp)
    return out


def _ref_blhd(q, k, v, causal, scale):
    logits = jnp.einsum("blhd,bmhd->bhlm", q, k).astype(jnp.float32) * scale
    if causal:
        lq, lk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((lq, lk), dtype=bool), lk - lq)
        logits = jnp.where(cm, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhlm,bmhd->blhd", probs, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_blhd(q, k, v, causal=False, scale=None,
                         block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """Flash attention over [batch, seq, heads, head_dim] inputs."""
    return _fa_fwd(q, k, v, causal, scale, block_q, block_k)[0]


def _fa_fwd(q, k, v, causal, scale, block_q, block_k):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    b, lq, h, d = q.shape
    lk = k.shape[1]
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, lk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, lk, d)
    out = _flash_fwd_bhld(qt, kt, vt, causal, scale, block_q, block_k)
    out = out.reshape(b, h, lq, d).transpose(0, 2, 1, 3)
    return out, (q, k, v)


def _fa_bwd(causal, scale, block_q, block_k, res, g):
    q, k, v = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    _, vjp = jax.vjp(lambda q, k, v: _ref_blhd(q, k, v, causal, scale),
                     q, k, v)
    return vjp(g)


flash_attention_blhd.defvjp(_fa_fwd, _fa_bwd)
