"""AMP: automatic mixed precision.

TPU-native replacement for paddle.amp (reference:
python/paddle/amp/auto_cast.py:20, grad_scaler.py:26; C++ hook
paddle/fluid/eager/amp_utils.h; op lists
python/paddle/fluid/dygraph/amp/auto_cast.py). Dispatch-level O1
white/black-list casting like the reference — but the native fast dtype
is bfloat16 (MXU), where loss scaling is unnecessary: GradScaler keeps
the fp16 contract (dynamic scaling + inf check) and becomes a cheap
pass-through for bf16.
"""
from __future__ import annotations

import threading

import numpy as np
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.tensor import Tensor

__all__ = ["auto_cast", "amp_guard", "GradScaler", "decorate",
           "white_list", "black_list"]

# O1 lists (reference: fluid/dygraph/amp/auto_cast.py WHITE_LIST/BLACK_LIST)
WHITE_LIST = {
    "matmul", "linear", "linear_bias", "conv1d", "conv2d", "conv3d",
    "conv1d_bias", "conv2d_bias", "conv3d_bias", "conv1d_transpose",
    "conv2d_transpose", "conv3d_transpose", "conv1d_transpose_bias",
    "conv2d_transpose_bias", "conv3d_transpose_bias", "einsum", "inner",
    "outer", "sdpa", "sdpa_mask", "sdpa_dropout", "sdpa_mask_dropout",
}
BLACK_LIST = {
    "exp", "log", "log2", "log10", "square", "pow", "sqrt", "rsqrt",
    "softmax", "log_softmax", "cross_entropy_hard", "cross_entropy_hard_w",
    "cross_entropy_soft", "cross_entropy_soft_w", "layer_norm",
    "layer_norm_noaffine", "rms_norm", "batch_norm_train",
    "batch_norm_infer", "batch_norm_train_noaffine",
    "batch_norm_infer_noaffine", "mse_loss", "l1_loss", "nll_loss",
    "bce_loss", "bce_logits", "kl_div_loss", "cumsum",
    "reduce_sum", "reduce_mean", "std", "var",
    "cosine_similarity_op", "p_normalize", "logsumexp", "logcumsumexp",
}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.level = "O1"
        self.dtype = None  # np dtype
        self.white = frozenset()
        self.black = frozenset()


_state = _AmpState()


def amp_active():
    return _state.enabled


def maybe_cast_inputs(op_name, vals):
    """Called from the eager dispatch hot path."""
    if not _state.enabled:
        return vals
    amp_dt = _state.dtype
    if _state.level == "O2":
        if op_name in _state.black:
            return tuple(v.astype(np.float32) if _is_half(v) else v
                         for v in vals)
        return tuple(v.astype(amp_dt) if _is_f32(v) else v for v in vals)
    if op_name in _state.white:
        return tuple(v.astype(amp_dt) if _is_f32(v) else v for v in vals)
    if op_name in _state.black:
        return tuple(v.astype(np.float32) if _is_half(v) else v
                     for v in vals)
    return vals


def _is_f32(v):
    return v.dtype == np.float32


def _is_half(v):
    return v.dtype in (np.dtype("float16"), jnp.bfloat16)


class _AmpGuard:
    def __init__(self, enable, custom_white_list, custom_black_list, level,
                 dtype):
        self.enable = enable
        self.level = level
        np_dt = dtypes.to_np_dtype(dtype)
        self.dtype = np_dt
        white = set(WHITE_LIST)
        black = set(BLACK_LIST)
        if custom_white_list:
            white |= set(custom_white_list)
            black -= set(custom_white_list)
        if custom_black_list:
            black |= set(custom_black_list)
            white -= set(custom_black_list)
        self.white = frozenset(white)
        self.black = frozenset(black)

    def __enter__(self):
        self._prev = (_state.enabled, _state.level, _state.dtype,
                      _state.white, _state.black)
        _state.enabled = self.enable
        _state.level = self.level
        _state.dtype = self.dtype
        _state.white = self.white
        _state.black = self.black
        return self

    def __exit__(self, *exc):
        (_state.enabled, _state.level, _state.dtype, _state.white,
         _state.black) = self._prev
        return False


def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    """paddle.amp.auto_cast parity; default dtype is bfloat16 (TPU-native).
    """
    if level not in ("O0", "O1", "O2"):
        raise ValueError(f"bad AMP level {level}")
    if level == "O0":
        enable = False
    return _AmpGuard(enable, custom_white_list, custom_black_list, level,
                     dtype)


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate parity: O2 casts model params to the AMP dtype
    (master weights stay in the optimizer's fp32 state)."""
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=dtype)
    if optimizers is None:
        return models if single else model_list
    single_opt = not isinstance(optimizers, (list, tuple))
    opt_list = [optimizers] if single_opt else list(optimizers)
    if level == "O2" and master_weight is not False:
        for o in opt_list:
            o._multi_precision = True
    return ((models if single else model_list),
            (optimizers if single_opt else opt_list))


class GradScaler:
    """reference: python/paddle/amp/grad_scaler.py:26. Dynamic loss
    scaling for fp16; transparent for bf16/fp32 (TPU default)."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def scale(self, var):
        if not self._enable:
            return var
        from ..ops import math as math_ops
        return math_ops.scale(var, self._scale)

    def unscale_(self, optimizer):
        if not self._enable or self._unscaled:
            return
        inv = 1.0 / self._scale
        flags = []
        for p in optimizer._parameter_list:
            if p.grad is not None:
                gv = p.grad._value * inv
                p.grad._rebind(gv)
                flags.append(jnp.any(~jnp.isfinite(gv)))
        # one fused reduction -> one host sync, not one per parameter
        self._found_inf = bool(jnp.any(jnp.stack(flags))) if flags \
            else False
        self._unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._update_scale()
        self._unscaled = False

    def minimize(self, optimizer, loss):
        loss.backward()
        self.step(optimizer)
        optimizer.clear_grad()

    def update(self):
        # paddle's step() doesn't auto-update; update() does. Our step()
        # already updates; keep update() idempotent for API parity.
        return

    def _update_scale(self):
        if not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every,
                "decr_every_n_nan_or_inf": self._decr_every,
                "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def set_state_dict(self, sd):
        self._scale = sd.get("scale", self._scale)
        self._good_steps = sd.get("good_steps", 0)
        self._bad_steps = sd.get("bad_steps", 0)


white_list = WHITE_LIST
black_list = BLACK_LIST


# install the dispatch-boundary cast hook
from ..core import tensor as _tensor_mod  # noqa: E402

_tensor_mod._amp_hook = maybe_cast_inputs
