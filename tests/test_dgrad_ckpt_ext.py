"""Double grad, sharded checkpoint, custom-kernel API, elastic tests."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.core.tensor import grad


class TestDoubleGrad:
    def test_second_order_scalar(self):
        x = paddle.to_tensor(np.float32(2.0), stop_gradient=False)
        y = x * x * x
        (g,) = grad(y, [x], create_graph=True)
        assert abs(float(g) - 12.0) < 1e-5
        (g2,) = grad(g, [x])
        assert abs(float(g2) - 12.0) < 1e-5          # 6x

    def test_third_order(self):
        x = paddle.to_tensor(np.float32(1.5), stop_gradient=False)
        (g1,) = grad(x ** 4, [x], create_graph=True)
        (g2,) = grad(g1, [x], create_graph=True)
        (g3,) = grad(g2, [x])
        assert abs(float(g3) - 36.0) < 1e-4          # 24x

    def test_gradient_penalty_reaches_weights(self):
        paddle.seed(0)
        lin = nn.Linear(4, 1)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(8, 4).astype("float32"),
            stop_gradient=False)
        out = paddle.tanh(lin(x)).sum()
        (gx,) = grad(out, [x], create_graph=True)
        ((gx ** 2).sum()).backward()
        g = lin.weight.grad
        assert g is not None and np.isfinite(g.numpy()).all()
        assert float(np.abs(g.numpy()).sum()) > 0

    def test_mixed_partial(self):
        # f = x^2 * y; d2f/dxdy = 2x
        x = paddle.to_tensor(np.float32(3.0), stop_gradient=False)
        y = paddle.to_tensor(np.float32(5.0), stop_gradient=False)
        f = x * x * y
        (gx,) = grad(f, [x], create_graph=True)      # 2xy
        (gxy,) = grad(gx, [y])
        assert abs(float(gxy) - 6.0) < 1e-5

    def test_without_create_graph_unchanged(self):
        x = paddle.to_tensor(np.float32(2.0), stop_gradient=False)
        (g,) = grad(x * x, [x])
        assert abs(float(g) - 4.0) < 1e-6
        with pytest.raises(RuntimeError):
            grad(g, [x])  # g is detached without create_graph


class TestShardedCheckpoint:
    def test_sharded_save_restore_roundtrip(self, tmp_path):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed import checkpoint as ckpt
        from paddle_tpu.distributed import fleet
        from jax.sharding import PartitionSpec as P

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"sharding_degree": 8}
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                              nn.Linear(32, 16))
        import paddle_tpu.optimizer as opt
        o = opt.Adam(learning_rate=1e-3, parameters=model.parameters())
        out = dist.group_sharded_parallel(model, o, "p_g_os")
        model, o = out[0], out[1]
        want = {k: v.numpy().copy()
                for k, v in model.state_dict().items()}
        path = str(tmp_path / "sharded_ckpt")
        ckpt.save_state_dict(model.state_dict(), path)

        # scribble over the weights (sharding-preserving), then restore
        for p in model.parameters():
            p._rebind(p._value * 0)
        sd = model.state_dict()
        ckpt.load_state_dict(sd, path)
        for k, v in model.state_dict().items():
            np.testing.assert_allclose(v.numpy(), want[k], rtol=1e-6)
        # restored arrays keep their SHARDED placement
        for p in model.parameters():
            if p._value.size >= 8:
                assert p._value.addressable_shards[0].data.nbytes \
                    == p._value.nbytes // 8

    def test_async_save(self, tmp_path):
        from paddle_tpu.distributed import checkpoint as ckpt
        sd = {"w": paddle.to_tensor(np.arange(12, dtype="float32"))}
        path = str(tmp_path / "async_ckpt")
        ckpt.save_state_dict(sd, path, async_save=True)
        ckpt.async_save_wait()
        sd2 = {"w": paddle.to_tensor(np.zeros(12, "float32"))}
        ckpt.load_state_dict(sd2, path)
        np.testing.assert_allclose(sd2["w"].numpy(),
                                   np.arange(12, dtype="float32"))


class TestCustomKernel:
    def test_register_and_autograd(self):
        from paddle_tpu.utils.cpp_extension import CustomOp
        import jax.numpy as jnp
        op = CustomOp("test_mul_add",
                      fwd=lambda x, y, c=1.0: x * y + c)
        a = paddle.to_tensor(np.array([1.0, 2.0], "float32"),
                             stop_gradient=False)
        b = paddle.to_tensor(np.array([3.0, 4.0], "float32"),
                             stop_gradient=False)
        out = op(a, b, attrs=dict(c=10.0))
        np.testing.assert_allclose(out.numpy(), [13.0, 18.0])
        out.sum().backward()
        np.testing.assert_allclose(a.grad.numpy(), [3.0, 4.0])
        np.testing.assert_allclose(b.grad.numpy(), [1.0, 2.0])

    def test_custom_backward(self):
        from paddle_tpu.utils.cpp_extension import CustomOp
        import jax.numpy as jnp

        def bwd(attrs, inputs, outputs, cts):
            (x,) = inputs
            (ct,) = cts
            return (ct * 2.0 * x * attrs["k"],)   # d(k x^2)/dx

        op = CustomOp("test_ksquare",
                      fwd=lambda x, k=1.0: k * x * x, bwd=bwd)
        x = paddle.to_tensor(np.array([3.0], "float32"),
                             stop_gradient=False)
        y = op(x, attrs=dict(k=2.0))
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [12.0])

    def test_pallas_kernel_interpret(self):
        """A real pallas_call kernel through the custom-op path
        (interpret mode on CPU; same code compiles on TPU). Pallas
        kernels define their backward explicitly, exactly like the
        in-tree flash-attention kernel does."""
        import jax
        from jax.experimental import pallas as pl
        from paddle_tpu.utils.cpp_extension import CustomOp

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...] * 2.0 + 1.0

        def fwd(x):
            return pl.pallas_call(
                kernel, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                interpret=True)(x)

        def bwd(attrs, inputs, outputs, cts):
            return (cts[0] * 2.0,)

        op = CustomOp("test_pallas_affine", fwd=fwd, bwd=bwd)
        x = paddle.to_tensor(np.ones((8, 128), "float32"),
                             stop_gradient=False)
        y = op(x)
        np.testing.assert_allclose(y.numpy(), 3.0)
        y.mean().backward()
        np.testing.assert_allclose(x.grad.numpy(),
                                   np.full((8, 128), 2.0 / (8 * 128)),
                                   rtol=1e-5)

    def test_cpp_shims_raise(self):
        from paddle_tpu.utils import cpp_extension
        with pytest.raises(RuntimeError, match="Pallas"):
            cpp_extension.load(name="x", sources=["x.cc"])
        with pytest.raises(RuntimeError, match="Pallas"):
            cpp_extension.CppExtension()


class TestElastic:
    def test_manager_restarts_until_success(self):
        from paddle_tpu.distributed.fleet.elastic import (
            ElasticManager, ElasticStatus)
        calls = {"n": 0}

        def run_once():
            calls["n"] += 1
            return 0 if calls["n"] >= 3 else 1

        mgr = ElasticManager(max_restarts=5)
        assert mgr.watch(run_once) == 0
        assert calls["n"] == 3
        assert mgr.restarts == 2
        assert mgr.status == ElasticStatus.COMPLETED

    def test_manager_budget_exhausted(self):
        from paddle_tpu.distributed.fleet.elastic import (
            ElasticManager, ElasticStatus)
        mgr = ElasticManager(max_restarts=2)
        rc = mgr.watch(lambda: 7)
        assert rc == 7
        assert mgr.status == ElasticStatus.FAILED

    def test_launch_elastic_restarts_real_processes(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import launch_elastic
        marker = tmp_path / "attempts"
        script = tmp_path / "flaky.py"
        script.write_text(
            "import os, sys\n"
            f"p = {str(marker)!r}\n"
            "n = int(open(p).read()) if os.path.exists(p) else 0\n"
            "open(p, 'w').write(str(n + 1))\n"
            "sys.exit(0 if n >= 1 else 1)\n")
        rc, mgr = launch_elastic(str(script), nproc_per_node=1,
                                 max_restarts=3)
        assert rc == 0
        assert int(marker.read_text()) == 2  # failed once, then passed
