"""Fleet control plane (serving/controlplane.py): decision core,
admission math, runtime replica registration, actuation, exposition.

The load-bearing properties (ISSUE 16 acceptance):
- the decision core is pure + fake-clock driven: double-window burn
  scales up, cool-downs suppress, the hysteresis band never flaps;
- deadline-aware admission sheds AT THE DOOR with the predicted-wait
  math (measured rate when warm, census fallback when cold) and a
  typed DeadlineInfeasible (429 + Retry-After);
- `add_replica` / `remove_replica` resize a LIVE router under the
  router lock: names never reused, the last live replica is refused,
  a replica removed mid-stream still completes token-identically;
- dead replicas are tombstones capped at `dead_replica_cap` (older
  evicted + counted by `fleet_dead_evicted_total`);
- SLO-aware placement ranks warn below ok and page below warn — after
  the breaker, before load — and counts avoided placements;
- every scaling decision lands as a flight-recorder note; the
  Prometheus render carries the controller gauge + counters through
  the strict exposition parser; fleet_top shows desired-vs-actual.
"""
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
from paddle_tpu.serving import (ControlPlaneConfig, DeadlineInfeasible,
                                FleetController, FleetSignals,
                                QueueFull, SamplingParams,
                                ServingEngine, SLOConfig,
                                parse_controlplane_spec,
                                prometheus_render,
                                resolve_controlplane,
                                slo_placement_rank)
from paddle_tpu.serving.http import EngineDriver, Router, serve

from test_serving_obs import check_histograms, parse_exposition

_MODELS = {}


def tiny_gpt():
    m = _MODELS.get("gpt")
    if m is None:
        paddle.seed(7)
        cfg = GPTConfig(vocab_size=97, hidden_size=32,
                        num_hidden_layers=2, num_attention_heads=4,
                        intermediate_size=64,
                        max_position_embeddings=128,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
        m = _MODELS["gpt"] = GPTForCausalLM(cfg)
        m.eval()
    return m


def make_engine(**kw):
    opts = dict(num_slots=2, max_len=64)
    opts.update(kw)
    return ServingEngine(tiny_gpt(), **opts)


def flight_notes(eng, kind):
    snap = eng.obs.flight.snapshot()
    return [e for e in snap["steps"] if e.get("note") == kind]


# -- gate: spec parsing + resolution (no engine) ----------------------------
class TestSpecAndResolve:
    def test_off_on_defaults(self):
        assert parse_controlplane_spec("off") is None
        assert parse_controlplane_spec("0") is None
        assert parse_controlplane_spec("on") == ControlPlaneConfig()
        assert parse_controlplane_spec("") == ControlPlaneConfig()

    def test_kv_spec(self):
        cfg = parse_controlplane_spec(
            "min=2,max=5,target_util=0.6,up_burn=3.5,down_util=0.2,"
            "up_cooldown=1,down_cooldown=2,interval=0.5,"
            "est_tokens=32,hw_flops=1e9,slack=1.5")
        assert cfg.min_replicas == 2 and cfg.max_replicas == 5
        assert cfg.target_util == 0.6 and cfg.scale_up_burn == 3.5
        assert cfg.scale_down_util == 0.2
        assert cfg.scale_up_cooldown_s == 1.0
        assert cfg.scale_down_cooldown_s == 2.0
        assert cfg.interval_s == 0.5 and cfg.est_request_tokens == 32
        assert cfg.hw_flops_per_s == 1e9 and cfg.admission_slack == 1.5

    def test_spec_errors(self):
        with pytest.raises(ValueError, match="expected k=v"):
            parse_controlplane_spec("bogus_key=1")
        with pytest.raises(ValueError, match="expected k=v"):
            parse_controlplane_spec("min")
        with pytest.raises(ValueError, match="value"):
            parse_controlplane_spec("min=lots")

    def test_config_validation(self):
        with pytest.raises(ValueError, match="min_replicas"):
            ControlPlaneConfig(min_replicas=0)
        with pytest.raises(ValueError, match="max_replicas"):
            ControlPlaneConfig(min_replicas=3, max_replicas=2)
        with pytest.raises(ValueError, match="target_util"):
            ControlPlaneConfig(target_util=0.0)
        # the hysteresis band must exist: low-water >= target is flap
        with pytest.raises(ValueError, match="hysteresis"):
            ControlPlaneConfig(target_util=0.5, scale_down_util=0.5)
        with pytest.raises(ValueError, match="cool-downs"):
            ControlPlaneConfig(scale_up_cooldown_s=-1)
        with pytest.raises(ValueError, match="admission_slack"):
            ControlPlaneConfig(admission_slack=0)

    def test_resolve_override_wins_over_env(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_CONTROLPLANE", "on")
        assert resolve_controlplane(False) is None
        monkeypatch.setenv("PADDLE_TPU_CONTROLPLANE", "off")
        assert resolve_controlplane(True) == ControlPlaneConfig()
        cfg = ControlPlaneConfig(min_replicas=2)
        assert resolve_controlplane(cfg) is cfg
        assert resolve_controlplane("min=3").min_replicas == 3

    def test_resolve_env_default_off(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_CONTROLPLANE", raising=False)
        assert resolve_controlplane() is None
        monkeypatch.setenv("PADDLE_TPU_CONTROLPLANE", "min=2,max=4")
        cfg = resolve_controlplane()
        assert cfg.min_replicas == 2 and cfg.max_replicas == 4

    def test_slo_placement_rank(self):
        assert slo_placement_rank("ok") == 0
        assert slo_placement_rank("warn") == 1
        assert slo_placement_rank("page") == 2
        assert slo_placement_rank(None) == 0   # SLO tracking off


# -- decision core (pure, fake clock, no threads) ---------------------------
class TestDecide:
    def mk(self, **kw):
        return FleetController(ControlPlaneConfig(**kw),
                               clock=lambda: 0.0)

    def test_double_window_burn_scales_up(self):
        ctrl = self.mk()
        d = ctrl.decide(FleetSignals(replicas=2, fast_burn=5.0,
                                     slow_burn=5.0, mean_util=0.5),
                        now=0.0)
        assert d.action == "scale_up" and d.desired == 3
        assert d.reason == "double-window burn"
        assert ctrl.desired_replicas == 3

    def test_single_window_burn_holds(self):
        # fast window alone is noise; the slow window must agree —
        # the same multi-window discipline the SLO tracker alerts on
        ctrl = self.mk()
        d = ctrl.decide(FleetSignals(replicas=2, fast_burn=50.0,
                                     slow_burn=0.0, mean_util=0.5),
                        now=0.0)
        assert d.action == "hold" and d.reason == "steady"

    def test_util_scale_up_then_cooldown_suppresses(self):
        ctrl = self.mk(scale_up_cooldown_s=15.0)
        hot = FleetSignals(replicas=2, mean_util=0.9)
        d = ctrl.decide(hot, now=100.0)
        assert d.action == "scale_up" and d.desired == 3
        assert d.reason == "util 0.90 over target"
        # still hot 1s later: the up-cooldown holds the fleet
        d = ctrl.decide(hot, now=101.0)
        assert d.action == "hold" and d.reason.startswith("cooldown")
        # cooldown elapsed: free to act again
        d = ctrl.decide(hot, now=116.0)
        assert d.action == "scale_up"

    def test_hysteresis_band_never_flaps(self):
        # utilization oscillating between the low-water mark (0.45)
        # and the planning target (0.75) must produce ZERO actions
        ctrl = self.mk()
        for i, util in enumerate([0.5, 0.7, 0.5, 0.7, 0.5]):
            d = ctrl.decide(FleetSignals(replicas=3, mean_util=util),
                            now=float(i))
            assert d.action == "hold", (util, d)
        reasons = {rec["reason"] for rec in ctrl.decisions}
        assert "hysteresis" in reasons

    def test_idle_scale_down_one_at_a_time_with_cooldown(self):
        ctrl = self.mk(scale_down_cooldown_s=60.0)
        idle3 = FleetSignals(replicas=3, mean_util=0.1)
        d = ctrl.decide(idle3, now=0.0)
        assert d.action == "scale_down" and d.desired == 2  # ONE step
        d = ctrl.decide(FleetSignals(replicas=2, mean_util=0.1),
                        now=1.0)
        assert d.action == "hold" and d.reason.startswith("cooldown")
        d = ctrl.decide(FleetSignals(replicas=2, mean_util=0.1),
                        now=61.0)
        assert d.action == "scale_down" and d.desired == 1
        # at min_replicas the fleet holds steady
        d = ctrl.decide(FleetSignals(replicas=1, mean_util=0.1),
                        now=122.0)
        assert d.action == "hold" and d.reason == "steady"

    def test_scale_down_blocked_by_queue_or_burn(self):
        ctrl = self.mk()
        # idle util but a queued backlog: hold (hysteresis), not drain
        d = ctrl.decide(FleetSignals(replicas=3, mean_util=0.1,
                                     queue_depth=4, capacity_tokens=0),
                        now=0.0)
        assert d.action == "hold" and d.reason == "hysteresis"

    def test_clamps_at_max_and_min(self):
        ctrl = self.mk(min_replicas=2, max_replicas=3)
        # burn-hot at max: desired clamps to live -> hold, not grow
        d = ctrl.decide(FleetSignals(replicas=3, fast_burn=99.0,
                                     slow_burn=99.0, mean_util=1.0),
                        now=0.0)
        assert d.action == "hold" and d.desired == 3
        # fully idle at min: hold
        d = ctrl.decide(FleetSignals(replicas=2, mean_util=0.0),
                        now=1.0)
        assert d.action == "hold" and d.desired == 2

    def test_queue_backlog_feeds_capacity_model(self):
        # 8 queued * 64 est tokens / 64-token steps = 8 replica-steps
        # of backlog on a single idle replica -> wants max_replicas
        ctrl = self.mk(max_replicas=4)
        d = ctrl.decide(FleetSignals(replicas=1, mean_util=0.0,
                                     queue_depth=8, capacity_tokens=64),
                        now=0.0)
        assert d.action == "scale_up" and d.desired == 4

    def test_decisions_recorded_with_clock(self):
        ctrl = self.mk()
        ctrl.decide(FleetSignals(replicas=1), now=42.0)
        rec = ctrl.decisions[-1]
        assert rec["t"] == 42.0 and rec["action"] == "hold"
        assert ctrl.stats()["last_decision"] == rec


# -- deadline-aware admission (pure math) -----------------------------------
class TestAdmission:
    def test_measured_rate_shed_math(self):
        ctrl = FleetController()       # est_request_tokens=64
        s = FleetSignals(replicas=2, queue_depth=10,
                         tokens_per_sec=100.0)
        assert ctrl.predicted_wait_s(s) == pytest.approx(6.4)
        retry = ctrl.check_admission(s, 5.0)
        assert retry == pytest.approx(1.4)     # wait - deadline
        assert ctrl.admission_shed_total == 1
        # a deadline past the predicted wait admits
        assert ctrl.check_admission(s, 10.0) is None
        assert ctrl.admission_shed_total == 1

    def test_retry_after_floor_is_one_second(self):
        ctrl = FleetController()
        s = FleetSignals(replicas=1, queue_depth=1,
                         tokens_per_sec=100.0)    # wait 0.64s
        assert ctrl.check_admission(s, 0.5) == 1.0

    def test_census_fallback_predicts_before_throughput(self):
        # cold fleet: no measured tokens/s yet — the census predicts
        # the rate: step_s = flops/step / hw, tokens/step = cap * util
        ctrl = FleetController(ControlPlaneConfig(hw_flops_per_s=1e6))
        s = FleetSignals(replicas=1, queue_depth=5, mean_util=0.5,
                         capacity_tokens=64, flops_per_token=1000.0)
        # step 64e3 flops / 1e6 = 0.064s; 32 tok/step -> 500 tok/s
        assert ctrl.predicted_wait_s(s) == pytest.approx(0.64)
        # idle util floors at 10% (an idle fleet is about to speed
        # up, not shed everything): 6.4 tok/step -> 100 tok/s
        s0 = FleetSignals(replicas=1, queue_depth=5, mean_util=0.0,
                          capacity_tokens=64, flops_per_token=1000.0)
        assert ctrl.predicted_wait_s(s0) == pytest.approx(3.2)

    def test_admit_paths(self):
        ctrl = FleetController()
        busy = FleetSignals(replicas=1, queue_depth=50,
                            tokens_per_sec=10.0)
        assert ctrl.check_admission(busy, None) is None  # no deadline
        empty = FleetSignals(replicas=1, tokens_per_sec=10.0)
        assert ctrl.check_admission(empty, 0.001) is None  # no backlog
        blind = FleetSignals(replicas=1, queue_depth=50)
        assert ctrl.check_admission(blind, 0.001) is None  # no model
        assert ctrl.admission_shed_total == 0

    def test_admission_slack_relaxes_the_bar(self):
        ctrl = FleetController(ControlPlaneConfig(admission_slack=2.0))
        s = FleetSignals(replicas=2, queue_depth=10,
                         tokens_per_sec=100.0)    # wait 6.4s
        assert ctrl.check_admission(s, 4.0) is None    # 6.4 <= 2*4
        assert ctrl.check_admission(s, 3.0) is not None


# -- live router runtime (engines) ------------------------------------------
class TestRouterRuntime:
    def test_add_remove_replica_lifecycle(self):
        d0 = EngineDriver(make_engine(), name="replica-0")
        r = Router([d0], watchdog_timeout_s=120.0).start()
        try:
            d1 = r.add_replica(make_engine())
            assert d1.name == "replica-1" and d1 in r.drivers
            assert d1 in r.watchdog.drivers
            assert "replica-1" in r.breakers
            with pytest.raises(ValueError, match="already used"):
                r.add_replica(driver=EngineDriver(make_engine(),
                                                  name="replica-0"))
            with pytest.raises(ValueError, match="exactly one"):
                r.add_replica()
            removed = r.remove_replica("replica-1", wait=True)
            assert removed is d1 and d1 not in r.drivers
            assert d1 not in r.watchdog.drivers
            # a tombstoned name is never reused
            d2 = r.add_replica(make_engine())
            assert d2.name == "replica-2"
            with pytest.raises(ValueError, match="no replica named"):
                r.remove_replica("nope")
            r.remove_replica("replica-2", wait=True)
            with pytest.raises(ValueError, match="last live"):
                r.remove_replica("replica-0")
        finally:
            r.drain(10.0)

    def test_remove_mid_stream_completes_token_identically(self):
        prompt = np.arange(1, 7)
        oracle = make_engine().generate(
            [prompt], SamplingParams(max_new_tokens=8))[0]
        drivers = [EngineDriver(make_engine(), name=f"replica-{i}")
                   for i in range(2)]
        r = Router(drivers).start()
        try:
            t = r.submit(prompt, SamplingParams(max_new_tokens=8))
            # deregister the serving replica mid-stream: graceful
            # drain finishes residents, the stream completes
            r.remove_replica(t.driver.name, wait=False)
            out = t.result()
            assert out.finish_reason == "length"
            assert out.token_ids == oracle.token_ids
            assert len(r.drivers) == 1
        finally:
            r.drain(10.0)

    def test_dead_tombstone_cap_evicts_oldest(self):
        eng = make_engine()
        drivers = [EngineDriver(eng, name=f"r{i}") for i in range(5)]
        r = Router(drivers, dead_replica_cap=2)
        for d in drivers[:4]:
            d.condemn()
        snap = r.fleet_snapshot()
        # only the LAST 2 tombstones survive; older evicted + counted
        assert set(snap["replicas"]) == {"r2", "r3", "r4"}
        assert snap["replicas"]["r2"]["dead"]
        assert snap["replicas"]["r3"]["dead"]
        assert not snap["replicas"]["r4"]["dead"]
        assert r.fleet_dead_evicted_total == 2
        assert snap["router"]["fleet_dead_evicted_total"] == 2
        assert "r0" not in r.breakers and "r1" not in r.breakers

    def test_slo_aware_placement_and_breaker_dominance(self):
        slo_cfg = SLOConfig(min_events=5)
        drivers = [EngineDriver(make_engine(slo=slo_cfg),
                                name=f"replica-{i}") for i in range(2)]
        ctrl = FleetController()
        r = Router(drivers, controller=ctrl).start()
        try:
            # replica-0's tracker burns to `page` in both windows
            for _ in range(10):
                drivers[0].engine.slo.on_ttft(5.0)
            assert drivers[0].engine.slo.worst_state() == "page"
            assert r._load_key(drivers[0])[1] == 2
            assert r._load_key(drivers[1])[1] == 0
            # traffic steers to the ok replica, and the steer counts
            t = r.submit(np.arange(1, 5),
                         SamplingParams(max_new_tokens=4))
            assert t.driver is drivers[1]
            assert t.result().finish_reason == "length"
            assert ctrl.placement_avoided_total >= 1
            snap = r.fleet_snapshot()
            assert snap["replicas"]["replica-0"][
                "placement_avoided"] >= 1
            assert snap["controlplane"][
                "placement_avoided_total"] >= 1
            # breaker health DOMINATES the SLO rank: a tripped ok
            # replica is worse than a burning closed one
            r.breakers["replica-1"].trip(time.monotonic())
            assert r._load_key(drivers[0]) < r._load_key(drivers[1])
        finally:
            r.drain(10.0)

    def test_slo_rank_inert_with_controller_off(self):
        slo_cfg = SLOConfig(min_events=5)
        d0 = EngineDriver(make_engine(slo=slo_cfg), name="replica-0")
        r = Router([d0])           # no controller: rank stays 0
        for _ in range(10):
            d0.engine.slo.on_ttft(5.0)
        assert r._load_key(d0)[1] == 0

    def test_poll_actuates_scale_up_then_down_with_notes(self):
        clk = [0.0]
        e0 = make_engine()
        cfg = ControlPlaneConfig(min_replicas=1, max_replicas=3,
                                 scale_up_cooldown_s=0.0,
                                 scale_down_cooldown_s=0.0)
        ctrl = FleetController(cfg, replica_factory=make_engine,
                               clock=lambda: clk[0])
        r = Router([EngineDriver(e0, name="replica-0")],
                   controller=ctrl).start()
        try:
            ctrl.observe = lambda router: FleetSignals(
                replicas=1, fast_burn=9.0, slow_burn=9.0)
            d = ctrl.poll(r)
            assert d.action == "scale_up" and len(r.drivers) == 2
            assert ctrl.scale_up_total == 1
            assert flight_notes(e0, "controlplane:scale_up")
            clk[0] = 100.0
            ctrl.observe = lambda router: FleetSignals(
                replicas=2, mean_util=0.0)
            d = ctrl.poll(r)
            assert d.action == "scale_down" and len(r.drivers) == 1
            assert ctrl.scale_down_total == 1
            st = r.stats()["controlplane"]
            assert st["scale_up_total"] == 1
            assert st["scale_down_total"] == 1
            assert st["desired_replicas"] == 1
        finally:
            r.drain(10.0)

    def test_poll_without_factory_cannot_grow(self):
        ctrl = FleetController(clock=lambda: 0.0)
        r = Router([EngineDriver(make_engine(), name="replica-0")],
                   controller=ctrl)
        ctrl.observe = lambda router: FleetSignals(
            replicas=1, fast_burn=9.0, slow_burn=9.0)
        d = ctrl.poll(r)
        assert d.action == "scale_up"      # decided, but no factory:
        assert len(r.drivers) == 1         # the fleet cannot grow
        assert ctrl.scale_up_total == 0    # counters count ACTUATION

    def test_deadline_infeasible_shed_at_submit(self):
        e = make_engine()
        ctrl = FleetController()
        r = Router([EngineDriver(e, name="replica-0")],
                   controller=ctrl).start()
        try:
            ctrl.observe = lambda router: FleetSignals(
                replicas=1, queue_depth=50, tokens_per_sec=10.0)
            with pytest.raises(DeadlineInfeasible) as ei:
                r.submit(np.arange(1, 5),
                         SamplingParams(max_new_tokens=4,
                                        deadline_s=1.0))
            assert isinstance(ei.value, QueueFull)   # HTTP 429 path
            assert ei.value.retry_after_s == pytest.approx(319.0)
            assert ctrl.admission_shed_total == 1
            assert flight_notes(e, "controlplane:shed")
            # no deadline -> admission never consulted, served fine
            t = r.submit(np.arange(1, 5),
                         SamplingParams(max_new_tokens=4))
            assert t.result().finish_reason == "length"
        finally:
            r.drain(10.0)


# -- exposition + fleet_top + serve gate ------------------------------------
class TestExposition:
    def test_controlplane_series_through_strict_parser(self):
        eng = make_engine()
        ctrl = FleetController()
        ctrl.decide(FleetSignals(replicas=2, mean_util=0.9), now=0.0)
        ctrl.on_placement_avoided(3)
        r = Router([EngineDriver(eng, name="replica-0")],
                   controller=ctrl)
        text = prometheus_render({"replica-0": eng.metrics.snapshot()},
                                 router=r.stats())
        series = parse_exposition(text)
        check_histograms(series)
        vals = {name: v for name, labels, v in series if not labels}
        assert vals["paddle_serving_fleet_desired_replicas"] == 3
        assert vals["paddle_serving_scale_up_total"] == 0
        assert vals["paddle_serving_scale_down_total"] == 0
        assert vals["paddle_serving_admission_shed_total"] == 0
        assert vals["paddle_serving_placement_avoided_total"] == 3
        assert "paddle_serving_fleet_dead_evicted_total" in vals

    def test_controller_off_renders_no_series(self):
        eng = make_engine()
        r = Router([EngineDriver(eng, name="replica-0")])
        text = prometheus_render({"replica-0": eng.metrics.snapshot()},
                                 router=r.stats())
        assert "fleet_desired_replicas" not in text
        assert "admission_shed_total" not in text


class TestFleetTop:
    def render(self, snapshot):
        sys.path.insert(0, "scripts")
        try:
            import fleet_top
        finally:
            sys.path.pop(0)
        return fleet_top.render_fleet(snapshot)

    def snap(self, controlplane=None):
        return {
            "router": {"ready": True, "retries_total": 0,
                       "migrations_total": 0,
                       "watchdog_kills_total": 0},
            "slo_worst": "ok",
            "controlplane": controlplane,
            "replicas": {"replica-0": {
                "healthy": True, "dead": False, "draining": False,
                "breaker": "closed", "steps": 10, "queue_depth": 0,
                "residents": 1, "num_slots": 2,
                "pool": {"pages_used": 1, "pages_total": 7},
                "host_pages_used": 0, "tokens_per_sec": 5.0,
                "achieved_util": {"mean": 0.5},
                "slo": {"worst": "ok"}, "placement_avoided": 7,
                "incidents_total": 0}}}

    def test_header_shows_desired_vs_actual_and_counters(self):
        text = self.render(self.snap(controlplane={
            "desired_replicas": 3, "scale_up_total": 2,
            "scale_down_total": 1, "admission_shed_total": 4,
            "placement_avoided_total": 7}))
        assert "1 replicas (desired=3)" in text
        assert "scale_up=2" in text and "scale_down=1" in text
        assert "shed=4" in text and "avoided=7" in text
        assert "avoid" in text.splitlines()[1]

    def test_avoid_column_and_plain_header_without_controller(self):
        text = self.render(self.snap())
        assert "desired=" not in text and "shed=" not in text
        row = next(ln for ln in text.splitlines()
                   if ln.startswith("replica-0"))
        assert " 7 " in row + " "     # the avoid column value

    def test_error_row_still_renders(self):
        s = self.snap()
        s["replicas"]["replica-0"] = {"error": "boom"}
        assert "(boom)" in self.render(s)


class TestServeGate:
    def test_serve_default_off_env_spec_on(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_CONTROLPLANE", raising=False)
        server = serve([make_engine()])
        try:
            assert server.router.controller is None
        finally:
            server.drain(10.0)
        monkeypatch.setenv("PADDLE_TPU_CONTROLPLANE", "min=2,max=5")
        server = serve([make_engine()])
        try:
            ctrl = server.router.controller
            assert isinstance(ctrl, FleetController)
            assert ctrl.config.min_replicas == 2
            assert ctrl.config.max_replicas == 5
        finally:
            server.drain(10.0)
