"""jit.to_static: whole-program compilation.

TPU-native replacement for Paddle's dy2static + static executor
(reference: python/paddle/jit/dy2static/program_translator.py:272
StaticFunction, python/paddle/jit/api.py:744 save). The reference
rewrites Python ASTs into a ProgramDesc and interprets it op-by-op
(InterpreterCore); here the decorated function is TRACED ONCE by jax.jit
into a single StableHLO module — the "north star" executor from SURVEY.md
§7: one XLA computation per program, buffer donation, no interpreter.

Key mechanics:
- Layer parameters/buffers become implicit traced inputs; buffer
  mutations (BN running stats) are functionalized into extra outputs and
  rebound after each call.
- A fresh threefry key is an implicit input; `paddle.seed`-driven ops
  (dropout) fold_in from it, so compiled programs see fresh randomness.
- The compiled call is recorded on the eager tape as ONE op: backward
  runs the jax.vjp of the whole program (compiled+cached), so
  `loss.backward()` and optimizers work unchanged.
- Python control flow is traced (unrolled/functionalized). Tensor-
  predicated `if`/`while` are rewritten by a thin AST pass
  (jit/dy2static.py) into `ops.cond`/`ops.while_loop` calls that lower
  to lax.cond / lax.while_loop — reference user code with data-dependent
  branches compiles unmodified; `ops.cond`/`while_loop` remain available
  for explicit use.
"""
from __future__ import annotations

import functools
import threading
from typing import Any, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core import random as random_mod
from ..core.dispatch import OpDef
from ..core.tensor import Tensor, Parameter, apply_op

__all__ = ["to_static", "not_to_static", "InputSpec", "StaticFunction",
           "in_to_static_trace", "ignore_module", "enable_to_static"]

_TO_STATIC_ENABLED = {"on": True}


def enable_to_static(enable=True):
    """paddle.jit.enable_to_static parity: globally disable to_static
    (decorated functions run their original eager Python — the standard
    debugging switch)."""
    _TO_STATIC_ENABLED["on"] = bool(enable)


class _TraceState(threading.local):
    def __init__(self):
        self.depth = 0


_trace_state = _TraceState()


def in_to_static_trace():
    return _trace_state.depth > 0


class InputSpec:
    """reference: python/paddle/static/input.py InputSpec."""

    def __init__(self, shape=None, dtype="float32", name=None,
                 stop_gradient=False):
        self.shape = list(shape) if shape is not None else None
        self.dtype = dtypes.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype.name}, "
                f"name={self.name})")

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name or tensor.name)


# shared Tensor-pytree helpers (also used by ops/control_flow.py)
from ..core.pytree import (  # noqa: E402
    flatten_tensors as _flatten, unflatten_tensors as _unflatten,
    static_key as _static_key)


class StaticFunction:
    """A function compiled to one XLA program per input signature."""

    def __init__(self, fn, input_spec=None, build_strategy=None,
                 full_graph=True, backend=None):
        from .dy2static import convert_control_flow
        self._fn = convert_control_flow(fn)
        self._input_spec = input_spec
        self._layer = None  # bound Layer instance, if method
        functools.update_wrapper(self, fn)
        self._cache: dict = {}
        self._last_concrete = None

    def __get__(self, instance, owner):
        if instance is None:
            return self
        bound = StaticFunction(self._fn.__get__(instance, owner),
                               self._input_spec)
        bound._layer = instance
        # cache the bound wrapper on the instance
        object.__setattr__(instance, self._fn.__name__, bound)
        return bound

    @property
    def layer(self):
        return self._layer

    def _collect_state(self):
        """Captured Layer state: params + buffers as implicit inputs.

        Finds the bound Layer, or scans the function's closure cells and
        referenced globals for Layer/Tensor objects (the reference's
        ProgramTranslator similarly lifts closure-captured parameters into
        program inputs)."""
        from ..nn.layer.layers import Layer
        layers = []
        loose: list[Tensor] = []
        layer = self._layer
        if layer is None:
            fn_self = getattr(self._fn, "__self__", None)
            if isinstance(fn_self, Layer):
                self._layer = layer = fn_self
        if layer is not None:
            layers.append(layer)
        else:
            fn = self._fn
            seen = set()
            candidates = []
            closure = getattr(fn, "__closure__", None) or ()
            for cell in closure:
                try:
                    candidates.append(cell.cell_contents)
                except ValueError:
                    pass
            code = getattr(fn, "__code__", None)
            g = getattr(fn, "__globals__", {})
            if code is not None:
                for name in code.co_names:
                    if name in g:
                        candidates.append(g[name])
            for obj in candidates:
                if id(obj) in seen:
                    continue
                seen.add(id(obj))
                if isinstance(obj, Layer):
                    layers.append(obj)
                elif isinstance(obj, Tensor) and not obj.stop_gradient:
                    loose.append(obj)
        params, buffers = [], []
        pids = set()
        for lyr in layers:
            for _, p in lyr.named_parameters():
                if id(p) not in pids:
                    pids.add(id(p))
                    params.append(p)
            for _, b in lyr.named_buffers():
                if id(b) not in pids:
                    pids.add(id(b))
                    buffers.append(b)
        for t in loose:
            if id(t) not in pids:
                pids.add(id(t))
                params.append(t)
        return params, buffers

    def _build_pure(self, arg_spec, kw_spec, n_params, n_buffers,
                    state_tensors):
        fn = self._fn

        def pure(key, state_vals, arg_vals):
            # Rebind live Tensor objects to tracers for the trace, run the
            # python function, then restore. Mutation is trace-time only.
            originals = [t._value for t in state_tensors]
            sg = [t.stop_gradient for t in state_tensors]
            _trace_state.depth += 1
            random_mod.push_trace_key(key)
            try:
                for t, tracer in zip(state_tensors, state_vals):
                    t._value = tracer
                wrapped = [Tensor(v, stop_gradient=True)
                           for v in arg_vals]
                args = _unflatten(arg_spec, wrapped)
                kwargs = _unflatten(kw_spec, wrapped)
                out = fn(*args, **kwargs)
                out_tensors: list[Tensor] = []
                out_spec = _flatten(out, out_tensors)
                out_vals = tuple(t._value for t in out_tensors)
                new_buffer_vals = tuple(
                    t._value for t in state_tensors[n_params:])
                self._last_out_spec = out_spec
                return out_vals + new_buffer_vals
            finally:
                random_mod.pop_trace_key()
                _trace_state.depth -= 1
                for t, v, s in zip(state_tensors, originals, sg):
                    t._value = v
                    t.stop_gradient = s

        return pure

    def __call__(self, *args, **kwargs):
        if not _TO_STATIC_ENABLED["on"]:
            return self._fn(*args, **kwargs)  # debugging switch
        params, buffers = self._collect_state()
        arg_tensors: list[Tensor] = []
        arg_spec = _flatten(list(args), arg_tensors)
        kw_spec = _flatten(kwargs, arg_tensors)
        state_tensors = params + buffers
        cache_key = (_static_key(arg_spec), _static_key(kw_spec),
                     len(params), len(buffers))
        entry = self._cache.get(cache_key)
        # `pure` closes over the state tensor OBJECTS; if a parameter was
        # replaced since the entry was built (same count/shape, new
        # object), a retrace would bind tracers onto the stale objects
        # and bake the live weights in as constants — rebuild instead.
        state_ids = tuple(id(t) for t in state_tensors)
        if entry is not None and entry.get("state_ids") != state_ids:
            entry = None
        if entry is None:
            pure = self._build_pure(arg_spec, kw_spec, len(params),
                                    len(buffers), state_tensors)
            # the OpDef fwd signature: (key, *state_vals, *arg_vals)
            n_state = len(state_tensors)

            def fwd(key, *vals):
                state_vals = vals[:n_state]
                arg_vals = vals[n_state:]
                return pure(key, state_vals, arg_vals)

            entry = {"opdef": OpDef(f"to_static::{self._fn.__qualname__}",
                                    fwd),
                     "pure": pure, "n_state": n_state,
                     "state_ids": state_ids}
            self._cache[cache_key] = entry
        key_t = Tensor(random_mod.default_generator.next_key())
        all_inputs = [key_t] + state_tensors + arg_tensors
        outs = apply_op(entry["opdef"], *all_inputs)
        if not isinstance(outs, tuple):
            outs = (outs,)
        if "out_spec" not in entry:
            entry["out_spec"] = self._last_out_spec
        out_spec = entry["out_spec"]
        n_buf = len(buffers)
        if n_buf:
            out_leaves = list(outs[:len(outs) - n_buf])
            new_buf_vals = outs[len(outs) - n_buf:]
            for b, nv in zip(buffers, new_buf_vals):
                b._rebind(nv._value)
        else:
            out_leaves = list(outs)
        return _unflatten(out_spec, out_leaves)

    # paddle API parity ------------------------------------------------------
    def concrete_program_specify_input_spec(self, *a, **kw):
        raise NotImplementedError

    @property
    def code(self):
        import inspect
        try:
            return inspect.getsource(self._fn)
        except OSError:
            return "<source unavailable>"

    def get_traced(self, *args, **kwargs):
        """Return (jitted_fn, example_inputs) for export paths."""
        raise NotImplementedError


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """@paddle.jit.to_static parity (reference: python/paddle/jit/api.py)."""
    def deco(fn):
        from ..nn.layer.layers import Layer
        if isinstance(fn, Layer):
            layer = fn
            sf = StaticFunction(layer.forward, input_spec, build_strategy)
            sf._layer = layer
            layer.forward = sf
            return layer
        return StaticFunction(fn, input_spec, build_strategy)
    if function is not None:
        return deco(function)
    return deco


def not_to_static(fn=None):
    """Marks fn to run eagerly — under tracing this is identity (the traced
    values flow through python)."""
    if fn is None:
        return lambda f: f
    return fn


def ignore_module(modules):
    return None
