"""Test config: force an 8-device virtual CPU mesh before JAX initializes.

Mirrors the reference's strategy of testing device-independent plumbing on
fake backends (SURVEY.md §4: fake_cpu_device.h, ProcessGroupGloo): all
sharding/parallelism tests run on 8 virtual CPU devices so no TPU pod is
needed.

Note: the env var JAX_PLATFORMS is not enough on machines where an
accelerator PJRT plugin overrides it — jax.config.update is authoritative.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the suite builds hundreds of
# engines whose unified programs lower to identical HLO (same tiny-GPT
# geometry, same slot/page shapes), and on a 1-core box those duplicate
# compiles dominate tier-1 wall-clock. The disk cache dedups them both
# within one run and across runs (same executable bytes — numerics and
# the in-memory jit trace counts the retrace probes assert on are
# untouched). Opt out with PADDLE_TPU_TEST_NO_COMPILE_CACHE=1.
if not os.environ.get("PADDLE_TPU_TEST_NO_COMPILE_CACHE"):
    import tempfile

    _cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(tempfile.gettempdir(), "paddle_tpu_t1_xla_cache"))
    try:
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        # Only executables that took >= 1s to compile are persisted:
        # that captures every serving unified-step program (the whales)
        # while skipping the long tail of tiny layer/RNN executables.
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1.0)
    except Exception:  # older jax without the knobs: cache is a bonus
        pass

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_parallel_state():
    """Tests that init fleet/meshes must not leak the thread-local mesh
    into later tests (models built under a stale mesh mix device sets)."""
    yield
    from paddle_tpu.distributed import fleet
    fleet.shutdown()
