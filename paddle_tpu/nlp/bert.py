"""BERT encoder (BASELINE config #3: tokens/sec finetune).

Built on the stock nn.TransformerEncoder — the reference's BERT
(PaddleNLP) composes the same blocks; attention rides the Pallas flash
kernel via F.scaled_dot_product_attention.
"""
from __future__ import annotations

import numpy as np

from .. import nn
from ..nn import functional as F
from ..nn.initializer import Normal

__all__ = ["BertConfig", "BertModel", "BertForSequenceClassification"]


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, max_position_embeddings=512,
                 type_vocab_size=2, hidden_dropout_prob=0.1,
                 attention_probs_dropout_prob=0.1,
                 initializer_range=0.02, layer_norm_eps=1e-12):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.initializer_range = initializer_range
        self.layer_norm_eps = layer_norm_eps


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        init = nn.ParamAttr(initializer=Normal(0.0, cfg.initializer_range))
        self.word_embeddings = nn.Embedding(cfg.vocab_size,
                                            cfg.hidden_size,
                                            weight_attr=init)
        self.position_embeddings = nn.Embedding(
            cfg.max_position_embeddings, cfg.hidden_size, weight_attr=init)
        self.token_type_embeddings = nn.Embedding(
            cfg.type_vocab_size, cfg.hidden_size, weight_attr=init)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_eps)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        from ..ops import creation
        l = input_ids.shape[1]
        if position_ids is None:
            position_ids = creation.arange(0, l, dtype="int64")
        x = self.word_embeddings(input_ids) + \
            self.position_embeddings(position_ids)
        if token_type_ids is not None:
            x = x + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(x))


class BertModel(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        layer = nn.TransformerEncoderLayer(
            config.hidden_size, config.num_attention_heads,
            config.intermediate_size,
            dropout=config.hidden_dropout_prob, activation="gelu",
            attn_dropout=config.attention_probs_dropout_prob,
            layer_norm_eps=config.layer_norm_eps)
        self.encoder = nn.TransformerEncoder(layer,
                                             config.num_hidden_layers)
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        seq = self.encoder(x, attention_mask)
        pooled = F.tanh(self.pooler(seq[:, 0]))
        return seq, pooled


class BertForSequenceClassification(nn.Layer):
    def __init__(self, config: BertConfig, num_classes=2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, labels=None):
        _, pooled = self.bert(input_ids, token_type_ids, position_ids,
                              attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            return F.cross_entropy(logits, labels)
        return logits
