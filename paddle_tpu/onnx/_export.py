"""StableHLO-free ONNX export: trace the layer to a jaxpr and map the
inference-subset primitives onto ONNX opset-11 nodes.

Reference: python/paddle/onnx/export.py:22 delegates to the external
paddle2onnx (a full Program->ONNX converter). The TPU-native form
traces the SAME functionalized forward jit.save uses and converts the
jaxpr — matmul/conv/activation/normalization/pool/shape ops, the
subset the reference's deploy docs demonstrate — serialized with the
dependency-free wire-format writer in _proto.py.

Unsupported primitives raise with the primitive name and the documented
StableHLO alternative, so partial coverage is loud, never silent.
"""
from __future__ import annotations

import numpy as np

from . import _proto as P

_ONNX_DTYPE = {
    "float32": 1, "uint8": 2, "int8": 3, "int32": 6, "int64": 7,
    "bool": 9, "float16": 10, "float64": 11, "bfloat16": 16,
}

_OPSET = 11


class Unsupported(NotImplementedError):
    pass


# -- proto builders ---------------------------------------------------------


def attr_i(name, v):
    return P.f_msg(5, P.f_bytes(1, name) + P.f_varint(3, v)
                   + P.f_varint(20, 2))


def attr_f(name, v):
    return P.f_msg(5, P.f_bytes(1, name) + P.f_float(2, v)
                   + P.f_varint(20, 1))


def attr_ints(name, vs):
    body = P.f_bytes(1, name) + b"".join(P.f_varint(8, v) for v in vs) \
        + P.f_varint(20, 7)
    return P.f_msg(5, body)


def attr_s(name, v):
    return P.f_msg(5, P.f_bytes(1, name) + P.f_bytes(4, v)
                   + P.f_varint(20, 3))


def tensor_proto(name, arr):
    arr = np.asarray(arr)
    dt = _ONNX_DTYPE[str(arr.dtype)]
    body = b"".join(P.f_varint(1, d) for d in arr.shape)
    body += P.f_varint(2, dt)
    body += P.f_bytes(8, name)
    body += P.f_bytes(9, np.ascontiguousarray(arr).tobytes())
    return body


def value_info(name, shape, dtype):
    dims = b"".join(P.f_msg(1, P.f_varint(1, d)) for d in shape)
    ttype = P.f_varint(1, _ONNX_DTYPE[str(dtype)]) + \
        P.f_msg(2, dims)
    return P.f_bytes(1, name) + P.f_msg(2, P.f_msg(1, ttype))


def node(op_type, inputs, outputs, attrs=b"", name=None):
    body = b"".join(P.f_bytes(1, i) for i in inputs)
    body += b"".join(P.f_bytes(2, o) for o in outputs)
    if name:
        body += P.f_bytes(3, name)
    body += P.f_bytes(4, op_type)
    body += attrs
    return body


# -- conversion context -----------------------------------------------------


class _Ctx:
    def __init__(self):
        self.nodes = []          # serialized NodeProto payloads
        self.inits = []          # serialized TensorProto payloads
        self.names = {}          # jaxpr var -> onnx value name
        self.counter = [0]

    def fresh(self, hint="t"):
        self.counter[0] += 1
        return f"{hint}_{self.counter[0]}"

    def name_of(self, var):
        from jax._src.core import Literal
        if isinstance(var, Literal):
            return self.add_const(np.asarray(var.val))
        return self.names[var]

    def add_const(self, arr, hint="const"):
        n = self.fresh(hint)
        self.inits.append(tensor_proto(n, arr))
        return n

    def emit(self, op, ins, outs, attrs=b""):
        self.nodes.append(node(op, ins, outs, attrs,
                               name=self.fresh(op.lower())))


# -- primitive handlers -----------------------------------------------------

_ELEMENTWISE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "max": "Max", "min": "Min", "pow": "Pow",
    "tanh": "Tanh", "exp": "Exp", "log": "Log", "logistic": "Sigmoid",
    "erf": "Erf", "sqrt": "Sqrt", "neg": "Neg", "abs": "Abs",
    "sign": "Sign", "floor": "Floor", "ceil": "Ceil",
    "reciprocal": "Reciprocal", "relu": "Relu",
}


def _conv_square(ctx, eqn):
    x = ctx.name_of(eqn.invars[0])
    ctx.emit("Mul", [x, x], [_out(ctx, eqn)])


def _conv_erfc(ctx, eqn):
    x = ctx.name_of(eqn.invars[0])
    e = ctx.fresh()
    ctx.emit("Erf", [x], [e])
    one = ctx.add_const(np.asarray(1.0, np.float32), "one")
    ctx.emit("Sub", [one, e], [_out(ctx, eqn)])


def _out(ctx, eqn, i=0):
    v = eqn.outvars[i]
    n = ctx.fresh()
    ctx.names[v] = n
    return n


def _conv_elementwise(ctx, eqn, onnx_op):
    ins = [ctx.name_of(v) for v in eqn.invars]
    ctx.emit(onnx_op, ins, [_out(ctx, eqn)])


def _conv_rsqrt(ctx, eqn):
    x = ctx.name_of(eqn.invars[0])
    s = ctx.fresh()
    ctx.emit("Sqrt", [x], [s])
    ctx.emit("Reciprocal", [s], [_out(ctx, eqn)])


def _conv_integer_pow(ctx, eqn):
    x = ctx.name_of(eqn.invars[0])
    y = int(eqn.params["y"])
    e = ctx.add_const(np.asarray(float(y), np.float32), "exp")
    ctx.emit("Pow", [x, e], [_out(ctx, eqn)])


def _conv_dot_general(ctx, eqn):
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs, rhs = eqn.invars
    lnd = len(lhs.aval.shape)
    rnd = len(rhs.aval.shape)
    ln_ = ctx.name_of(lhs)
    rn = ctx.name_of(rhs)
    nb = len(lb)
    if tuple(lb) != tuple(range(nb)) or tuple(rb) != tuple(range(nb)):
        raise Unsupported(f"dot_general batch dims {lb}/{rb}")
    if lc != (lnd - 1,):
        raise Unsupported(f"dot_general lhs contraction {lc}")
    if rnd < 2:
        raise Unsupported(
            "dot_general with a rank-1 rhs (matvec); reshape the "
            "vector operand to a matrix for ONNX export")
    if rc == (rnd - 2,):
        pass
    elif rc == (rnd - 1,):
        # contraction on the last rhs axis: transpose trailing pair
        perm = list(range(rnd))
        perm[-1], perm[-2] = perm[-2], perm[-1]
        t = ctx.fresh()
        ctx.emit("Transpose", [rn], [t], attr_ints("perm", perm))
        rn = t
    else:
        raise Unsupported(f"dot_general rhs contraction {rc}")
    ctx.emit("MatMul", [ln_, rn], [_out(ctx, eqn)])


def _conv_broadcast_in_dim(ctx, eqn):
    x = eqn.invars[0]
    shape = eqn.params["shape"]
    bdims = eqn.params["broadcast_dimensions"]
    xn = ctx.name_of(x)
    # Reshape to rank(len(shape)) with 1s, then Expand
    mid = [1] * len(shape)
    for src, dst in enumerate(bdims):
        mid[dst] = x.aval.shape[src]
    rs = ctx.add_const(np.asarray(mid, np.int64), "shape")
    r = ctx.fresh()
    ctx.emit("Reshape", [xn, rs], [r])
    tgt = ctx.add_const(np.asarray(shape, np.int64), "shape")
    ctx.emit("Expand", [r, tgt], [_out(ctx, eqn)])


def _conv_reshape(ctx, eqn):
    xn = ctx.name_of(eqn.invars[0])
    shp = ctx.add_const(
        np.asarray(eqn.params["new_sizes"], np.int64), "shape")
    ctx.emit("Reshape", [xn, shp], [_out(ctx, eqn)])


def _conv_transpose(ctx, eqn):
    xn = ctx.name_of(eqn.invars[0])
    ctx.emit("Transpose", [xn], [_out(ctx, eqn)],
             attr_ints("perm", eqn.params["permutation"]))


def _conv_convert(ctx, eqn):
    xn = ctx.name_of(eqn.invars[0])
    dt = str(np.dtype(eqn.params["new_dtype"]))
    if dt not in _ONNX_DTYPE:
        raise Unsupported(
            f"paddle.onnx.export: cast to '{dt}' has no ONNX tensor "
            "type in the supported inference subset. For "
            "full-fidelity deployment use the StableHLO artifact from "
            "paddle.jit.save.")
    ctx.emit("Cast", [xn], [_out(ctx, eqn)],
             attr_i("to", _ONNX_DTYPE[dt]))


def _conv_reduce(onnx_op):
    def h(ctx, eqn):
        xn = ctx.name_of(eqn.invars[0])
        axes = list(eqn.params["axes"])
        ctx.emit(onnx_op, [xn], [_out(ctx, eqn)],
                 attr_ints("axes", axes) + attr_i("keepdims", 0))
    return h


def _conv_concatenate(ctx, eqn):
    ins = [ctx.name_of(v) for v in eqn.invars]
    ctx.emit("Concat", ins, [_out(ctx, eqn)],
             attr_i("axis", eqn.params["dimension"]))


def _conv_slice(ctx, eqn):
    if eqn.params.get("strides") and \
            any(s != 1 for s in eqn.params["strides"]):
        raise Unsupported("strided slice")
    xn = ctx.name_of(eqn.invars[0])
    starts = ctx.add_const(
        np.asarray(eqn.params["start_indices"], np.int64), "starts")
    ends = ctx.add_const(
        np.asarray(eqn.params["limit_indices"], np.int64), "ends")
    axes = ctx.add_const(
        np.asarray(range(len(eqn.params["start_indices"])), np.int64),
        "axes")
    ctx.emit("Slice", [xn, starts, ends, axes], [_out(ctx, eqn)])


def _conv_select_n(ctx, eqn):
    if len(eqn.invars) != 3:
        raise Unsupported("select_n with >2 cases")
    pred, f, t = (ctx.name_of(v) for v in eqn.invars)
    # select_n(pred, x0, x1) picks x1 where pred; Where(c, X, Y) picks X
    ctx.emit("Where", [pred, t, f], [_out(ctx, eqn)])


def _conv_conv(ctx, eqn):
    p = eqn.params
    dn = p["dimension_numbers"]
    if dn.lhs_spec != tuple(range(len(dn.lhs_spec))):
        raise Unsupported("conv: only NCHW-ordered lhs")
    xn = ctx.name_of(eqn.invars[0])
    wn = ctx.name_of(eqn.invars[1])
    pads_lo = [lo for lo, _ in p["padding"]]
    pads_hi = [hi for _, hi in p["padding"]]
    attrs = attr_ints("strides", p["window_strides"]) \
        + attr_ints("pads", list(pads_lo) + list(pads_hi)) \
        + attr_ints("dilations", p["rhs_dilation"]) \
        + attr_i("group", p["feature_group_count"])
    ctx.emit("Conv", [xn, wn], [_out(ctx, eqn)], attrs)


def _conv_reduce_window_max(ctx, eqn):
    p = eqn.params
    wd = p["window_dimensions"]
    if len(wd) < 3 or wd[0] != 1 or wd[1] != 1:
        raise Unsupported(f"reduce_window_max window {wd}")
    xn = ctx.name_of(eqn.invars[0])
    pads = p["padding"]
    attrs = attr_ints("kernel_shape", wd[2:]) \
        + attr_ints("strides", p["window_strides"][2:]) \
        + attr_ints("pads", [lo for lo, _ in pads[2:]]
                    + [hi for _, hi in pads[2:]])
    ctx.emit("MaxPool", [xn], [_out(ctx, eqn)], attrs)


def _conv_stop_gradient(ctx, eqn):
    ctx.names[eqn.outvars[0]] = ctx.name_of(eqn.invars[0])


def _conv_squeeze(ctx, eqn):
    xn = ctx.name_of(eqn.invars[0])
    ctx.emit("Squeeze", [xn], [_out(ctx, eqn)],
             attr_ints("axes", eqn.params["dimensions"]))


_HANDLERS = {
    "dot_general": _conv_dot_general,
    "broadcast_in_dim": _conv_broadcast_in_dim,
    "reshape": _conv_reshape,
    "transpose": _conv_transpose,
    "convert_element_type": _conv_convert,
    "reduce_sum": _conv_reduce("ReduceSum"),
    "reduce_max": _conv_reduce("ReduceMax"),
    "reduce_min": _conv_reduce("ReduceMin"),
    "concatenate": _conv_concatenate,
    "slice": _conv_slice,
    "select_n": _conv_select_n,
    "conv_general_dilated": _conv_conv,
    "reduce_window_max": _conv_reduce_window_max,
    "stop_gradient": _conv_stop_gradient,
    "squeeze": _conv_squeeze,
    "rsqrt": _conv_rsqrt,
    "square": _conv_square,
    "erfc": _conv_erfc,
    "integer_pow": _conv_integer_pow,
    "copy": _conv_stop_gradient,
}

_CALL_PRIMS = ("pjit", "jit", "closed_call", "custom_jvp_call",
               "custom_vjp_call", "custom_vjp_call_jaxpr", "remat",
               "checkpoint", "custom_jvp_call_jaxpr")


def _convert_jaxpr(ctx, jaxpr):
    from jax._src.core import Literal
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim in _CALL_PRIMS:
            sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
            if hasattr(sub, "jaxpr"):
                consts = list(getattr(sub, "consts", ()))
                sub = sub.jaxpr
            else:
                consts = []
            for cv, c in zip(sub.constvars, consts):
                ctx.names[cv] = ctx.add_const(np.asarray(c))
            n_call_args = len(sub.invars)
            for iv, ov in zip(sub.invars,
                              eqn.invars[len(eqn.invars) - n_call_args:]):
                if isinstance(ov, Literal):
                    ctx.names[iv] = ctx.add_const(np.asarray(ov.val))
                else:
                    ctx.names[iv] = ctx.name_of(ov)
            _convert_jaxpr(ctx, sub)
            for sov, ov in zip(sub.outvars, eqn.outvars):
                ctx.names[ov] = ctx.name_of(sov)
            continue
        h = _HANDLERS.get(prim)
        if h is None:
            if prim in _ELEMENTWISE:
                _conv_elementwise(ctx, eqn, _ELEMENTWISE[prim])
                continue
            raise Unsupported(
                f"paddle.onnx.export: primitive '{prim}' is outside the "
                "supported inference subset (matmul/conv/activations/"
                "norm/pool/shape ops). For full-fidelity deployment use "
                "the StableHLO artifact from paddle.jit.save.")
        h(ctx, eqn)


def export_onnx(layer, path, input_spec, opset_version=_OPSET):
    """Trace `layer` over `input_spec` and write `path`.onnx."""
    import jax
    import jax.numpy as jnp
    from ..core.tensor import Tensor

    layer.eval()
    named = list(layer.named_parameters()) + \
        [(n, b) for n, b in layer.named_buffers()]
    tensors = [t for _, t in named]
    pvals = [t._value for t in tensors]

    from ..core import dtype as dtypes
    example = [jnp.zeros([int(d) for d in spec.shape],
                         dtypes.to_np_dtype(spec.dtype))
               for spec in input_spec]

    def fwd(pv, *xs):
        orig = [t._value for t in tensors]
        try:
            for t, v in zip(tensors, pv):
                t._value = v
            out = layer(*[Tensor(x) for x in xs])
            outs = out if isinstance(out, (tuple, list)) else (out,)
            return tuple(o._value for o in outs)
        finally:
            for t, v in zip(tensors, orig):
                t._value = v

    closed = jax.make_jaxpr(fwd)(pvals, *example)
    jaxpr = closed.jaxpr

    ctx = _Ctx()
    # params first (flattened pvals), then the user inputs
    n_params = len(pvals)
    for (pname, _), var, val in zip(named, jaxpr.invars[:n_params],
                                    pvals):
        nm = f"param.{pname}"
        ctx.names[var] = nm
        ctx.inits.append(tensor_proto(nm, np.asarray(val)))
    in_names = []
    for i, (var, spec) in enumerate(zip(jaxpr.invars[n_params:],
                                        input_spec)):
        nm = getattr(spec, "name", None) or f"x{i}"
        ctx.names[var] = nm
        in_names.append((nm, var.aval.shape, var.aval.dtype))
    for cv, c in zip(jaxpr.constvars, closed.consts):
        ctx.names[cv] = ctx.add_const(np.asarray(c))

    _convert_jaxpr(ctx, jaxpr)

    out_infos = []
    for i, ov in enumerate(jaxpr.outvars):
        nm = ctx.name_of(ov)
        out_infos.append((nm, ov.aval.shape, ov.aval.dtype))

    graph = b"".join(P.f_msg(1, n) for n in ctx.nodes)
    graph += P.f_bytes(2, "paddle_tpu_graph")
    graph += b"".join(P.f_msg(5, t) for t in ctx.inits)
    graph += b"".join(
        P.f_msg(11, value_info(n, s, d)) for n, s, d in in_names)
    graph += b"".join(
        P.f_msg(12, value_info(n, s, d)) for n, s, d in out_infos)

    model = P.f_varint(1, 8)                      # ir_version 8
    model += P.f_bytes(2, "paddle_tpu")
    model += P.f_bytes(3, "0.0")
    model += P.f_msg(7, graph)
    # the converter emits opset-11 node forms exactly (Slice takes
    # inputs: needs >=10; ReduceSum/Squeeze axes are ATTRIBUTES:
    # removed at 13) — any other declared opset would mislabel the
    # file, so the declaration is pinned at 11 regardless of request
    if int(opset_version) != _OPSET:
        import warnings
        warnings.warn(
            f"paddle.onnx.export emits opset {_OPSET} node forms; "
            f"requested opset_version={opset_version} is recorded as "
            f"{_OPSET}")
    model += P.f_msg(8, P.f_bytes(1, "") + P.f_varint(2, _OPSET))

    out_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(model)
    return out_path
