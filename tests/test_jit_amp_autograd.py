"""to_static / AMP / PyLayer tests (reference models:
unittests/dygraph_to_static/, test_amp*, test_pylayer_op.py)."""
import os
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
import paddle_tpu.amp as amp
from paddle_tpu import jit
from paddle_tpu.autograd import PyLayer


def _randn(*shape):
    return np.random.RandomState(sum(shape)).randn(*shape).astype("float32")


class TestToStatic:
    def test_function(self):
        @jit.to_static
        def f(x, y):
            return paddle.matmul(x, y) + 1.0

        a = paddle.to_tensor(_randn(2, 3), stop_gradient=False)
        b = paddle.to_tensor(_randn(3, 4))
        out = f(a, b)
        np.testing.assert_allclose(out.numpy(), a.numpy() @ b.numpy() + 1,
                                   rtol=1e-5)
        out.sum().backward()
        np.testing.assert_allclose(a.grad.numpy(),
                                   np.tile(b.numpy().sum(1), (2, 1)),
                                   rtol=1e-5)

    def test_layer_buffers_and_rng(self):
        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(8, 8)
                self.bn = nn.BatchNorm1D(8)
                self.drop = nn.Dropout(0.5)

            def forward(self, x):
                return self.drop(self.bn(self.lin(x)))

        m = jit.to_static(M())
        x = paddle.to_tensor(_randn(16, 8))
        mb = m.bn._mean.numpy().copy()
        y1 = m(x)
        assert not np.allclose(mb, m.bn._mean.numpy()), \
            "BN stats must update through the compiled program"
        y2 = m(x)
        assert not np.allclose(y1.numpy(), y2.numpy()), \
            "dropout must resample per compiled call"

    def test_closure_capture_train(self):
        model = nn.Sequential(nn.Linear(4, 16), nn.ReLU(),
                              nn.Linear(16, 1))
        optm = opt.Adam(1e-2, parameters=model.parameters())
        X = paddle.to_tensor(_randn(32, 4))
        Y = paddle.to_tensor(_randn(32, 1))
        fwd = jit.to_static(lambda x: model(x))
        losses = []
        for _ in range(30):
            loss = F.mse_loss(fwd(X), Y)
            loss.backward()
            optm.step()
            optm.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.6

    def test_eval_matches_eager(self):
        model = nn.Sequential(nn.Linear(6, 6), nn.Tanh(), nn.Linear(6, 2))
        model.eval()
        x = paddle.to_tensor(_randn(3, 6))
        eager = model(x).numpy()
        static = jit.to_static(lambda v: model(v))(x).numpy()
        np.testing.assert_allclose(static, eager, rtol=1e-5, atol=1e-6)

    def test_save_load(self, tmp_path):
        lay = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        lay.eval()
        p = str(tmp_path / "model")
        jit.save(lay, p, input_spec=[jit.InputSpec([1, 4], "float32")])
        assert os.path.exists(p + ".pdmodel")
        tl = jit.load(p)
        x = paddle.to_tensor(_randn(1, 4))
        np.testing.assert_allclose(tl(x).numpy(), lay(x).numpy(),
                                   rtol=1e-5)


class TestAmp:
    def test_o1_white_list(self):
        lin = nn.Linear(8, 8)
        x = paddle.to_tensor(_randn(2, 8))
        with amp.auto_cast():
            y = lin(x)
        assert y.dtype == "bfloat16"
        assert lin(x).dtype == "float32"

    def test_o1_black_list_keeps_f32(self):
        x = paddle.to_tensor(_randn(4, 4).astype("float32"))
        with amp.auto_cast():
            s = F.softmax(x)
        assert s.dtype == "float32"

    def test_o2(self):
        lin = nn.Linear(4, 4)
        x = paddle.to_tensor(_randn(2, 4))
        with amp.auto_cast(level="O2"):
            y = F.relu(lin(x))
        assert y.dtype == "bfloat16"

    def test_grads_flow(self):
        lin = nn.Linear(8, 8)
        x = paddle.to_tensor(_randn(2, 8), stop_gradient=False)
        with amp.auto_cast():
            loss = lin(x).cast("float32").mean()
        loss.backward()
        assert lin.weight.grad is not None
        assert x.grad is not None

    def test_grad_scaler_skips_on_inf(self):
        model = nn.Linear(2, 2)
        o = opt.SGD(0.1, parameters=model.parameters())
        scaler = amp.GradScaler(init_loss_scaling=4.0,
                                decr_every_n_nan_or_inf=1)
        before = model.weight.numpy().copy()
        model.weight.grad = paddle.to_tensor(
            np.full((2, 2), np.inf, "float32"))
        model.bias.grad = paddle.to_tensor(np.zeros(2, "float32"))
        scaler.step(o)
        np.testing.assert_allclose(model.weight.numpy(), before)
        assert scaler._scale == 2.0  # decreased

    def test_scaler_scale_value(self):
        scaler = amp.GradScaler(init_loss_scaling=8.0)
        t = paddle.to_tensor(np.array([2.0], "float32"))
        np.testing.assert_allclose(scaler.scale(t).numpy(), [16.0])


class TestPyLayer:
    def test_custom_grad(self):
        class Exp(PyLayer):
            @staticmethod
            def forward(ctx, x):
                y = x.exp()
                ctx.save_for_backward(y)
                return y

            @staticmethod
            def backward(ctx, dy):
                (y,) = ctx.saved_tensor
                return dy * y

        t = paddle.to_tensor(np.array([0.0, 1.0], "float32"),
                             stop_gradient=False)
        out = Exp.apply(t)
        out.sum().backward()
        np.testing.assert_allclose(t.grad.numpy(), np.exp(t.numpy()),
                                   rtol=1e-5)

    def test_chain(self):
        class Double(PyLayer):
            @staticmethod
            def forward(ctx, x):
                return x * 2.0

            @staticmethod
            def backward(ctx, dy):
                return dy * 2.0

        t = paddle.to_tensor(np.array([3.0], "float32"),
                             stop_gradient=False)
        z = (Double.apply(t * t)).sum()
        z.backward()
        np.testing.assert_allclose(t.grad.numpy(), [12.0], rtol=1e-6)

    def test_multiple_inputs_none_grad(self):
        class MulAdd(PyLayer):
            @staticmethod
            def forward(ctx, x, y):
                ctx.save_for_backward(x, y)
                return x * y

            @staticmethod
            def backward(ctx, dy):
                x, y = ctx.saved_tensor
                return dy * y, dy * x

        a = paddle.to_tensor(np.array([2.0], "float32"),
                             stop_gradient=False)
        b = paddle.to_tensor(np.array([5.0], "float32"),
                             stop_gradient=False)
        MulAdd.apply(a, b).backward()
        np.testing.assert_allclose(a.grad.numpy(), [5.0])
        np.testing.assert_allclose(b.grad.numpy(), [2.0])


class TestAutogradExtras:
    def test_jacobian(self):
        from paddle_tpu.autograd import jacobian
        x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], "float32"),
                             stop_gradient=False)
        y = (x * x).sum()
        # jacobian of scalar wrt x = gradient row
        j = jacobian(y, x)
        np.testing.assert_allclose(j.numpy(), [[2.0, 4.0, 6.0]], rtol=1e-6)

    def test_backward_api(self):
        from paddle_tpu import autograd
        x = paddle.to_tensor(np.ones(3, "float32"), stop_gradient=False)
        loss = (x * 3.0).sum()
        autograd.backward([loss])
        np.testing.assert_allclose(x.grad.numpy(), [3.0] * 3)


class TestRound1ReviewFixes:
    def test_o2_master_weights_accumulate_tiny_updates(self):
        # A bf16 param can't represent updates below one ulp; the fp32
        # master weight must accumulate them across steps.
        lin = nn.Linear(4, 4)
        o = opt.SGD(learning_rate=1e-4, parameters=lin.parameters())
        paddle.amp.decorate(models=lin, optimizers=o, level="O2",
                            dtype="bfloat16")
        w0 = lin.weight.numpy().astype(np.float32).copy()
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        for _ in range(64):
            y = lin(x)
            loss = y.sum()
            loss.backward()
            o.step()
            o.clear_grad()
        import jax.numpy as jnp
        st = o._accumulators[id(lin.weight)]
        assert "master_weight" in st
        assert st["master_weight"].dtype == jnp.float32
        # master moved even though each single step is sub-ulp in bf16
        delta = np.abs(np.asarray(st["master_weight"]) - w0).max()
        assert delta > 1e-4

    def test_save_format_reference_compatible(self, tmp_path):
        import pickle
        lin = nn.Linear(3, 2)
        p = str(tmp_path / "m.pdparams")
        paddle.save(lin.state_dict(), p)
        with open(p, "rb") as f:
            raw = pickle.load(f)
        # plain dict of ndarrays + the reference name table
        assert "StructuredToParameterName@@" in raw
        for k, v in raw.items():
            if k == "StructuredToParameterName@@":
                assert isinstance(v, dict)
            else:
                assert isinstance(v, np.ndarray)
        # and loads back into parameters with original names
        sd = paddle.load(p)
        lin2 = nn.Linear(3, 2)
        lin2.set_state_dict(sd)
        np.testing.assert_allclose(lin2.weight.numpy(), lin.weight.numpy())

    def test_load_reference_produced_pickle(self, tmp_path):
        # simulate a checkpoint written by the reference: dict of plain
        # ndarrays + StructuredToParameterName@@
        import pickle
        p = str(tmp_path / "ref.pdparams")
        w = np.random.RandomState(0).randn(3, 2).astype("float32")
        b = np.zeros(2, "float32")
        with open(p, "wb") as f:
            pickle.dump({"weight": w, "bias": b,
                         "StructuredToParameterName@@":
                         {"weight": "linear_0.w_0", "bias": "linear_0.b_0"}},
                        f, protocol=2)
        sd = paddle.load(p)
        np.testing.assert_allclose(sd["weight"].numpy(), w)
        assert sd["weight"].name == "linear_0.w_0"

    def test_optimizer_state_keys_reference_format(self):
        lin = nn.Linear(3, 2)
        o = opt.Adam(parameters=lin.parameters())
        y = lin(paddle.to_tensor(np.ones((1, 3), np.float32)))
        y.sum().backward()
        o.step()
        sd = o.state_dict()
        # reference accumulator naming: {param_name}_{acc}_0
        assert any(k.endswith("_moment1_0") for k in sd)
        o2 = opt.Adam(parameters=lin.parameters())
        o2.set_state_dict(sd)
        st = o2._accumulators[id(lin.weight)]
        np.testing.assert_allclose(
            np.asarray(st["moment1"]),
            np.asarray(o._accumulators[id(lin.weight)]["moment1"]))

    def test_to_static_retrace_after_param_swap(self):
        from paddle_tpu.core.tensor import Parameter
        import jax.numpy as jnp
        lin = nn.Linear(2, 2)

        @paddle.jit.to_static
        def f(x):
            return lin(x)

        x32 = paddle.to_tensor(np.ones((1, 2), np.float32))
        _ = f(x32)
        # replace the weight with a same-shape new Parameter; the cached
        # trace must NOT freeze the old weights in as constants
        new_w = Parameter(jnp.full((2, 2), 5.0, jnp.float32))
        lin.weight = new_w
        out = f(x32)
        expect = np.ones((1, 2)) @ np.full((2, 2), 5.0) + lin.bias.numpy()
        np.testing.assert_allclose(out.numpy(), expect, rtol=1e-6)

    def test_o2_backward_through_mixed_precision_boundary(self):
        # chain bf16 -> f32(black-listed op) -> reduce: the cotangent
        # crossing the precision boundary must be cast to the producer's
        # output dtype, not rejected by the vjp
        lin = nn.Linear(8, 1)
        x = paddle.to_tensor(np.ones((4, 8), "float32"))
        y = paddle.to_tensor(np.ones((4, 1), "float32"))
        with amp.auto_cast(level="O2", dtype="bfloat16"):
            pred = lin(x)
            loss = ((pred - y) ** 2).mean()
        loss.backward()
        assert lin.weight.grad is not None
        assert np.all(np.isfinite(
            lin.weight.grad.numpy().astype(np.float32)))


class TestNanInfFlag:
    def test_check_nan_inf_raises_with_op_name(self):
        import paddle_tpu as paddle
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        try:
            x = paddle.to_tensor(np.array([1.0, 0.0], "float32"))
            with pytest.raises(FloatingPointError, match="divide"):
                y = x / paddle.to_tensor(np.array([1.0, 0.0], "float32"))
            # log of a negative -> nan
            with pytest.raises(FloatingPointError, match="nan"):
                paddle.log(paddle.to_tensor(np.array([-1.0], "float32")))
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})
        # off again: non-finite values pass through silently (0/0 = nan)
        y = x / paddle.to_tensor(np.array([1.0, 0.0], "float32"))
        assert np.isnan(y.numpy()[1])

    def test_check_nan_inf_covers_backward(self):
        import paddle_tpu as paddle
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        try:
            # forward finite (sqrt(0) = 0) but d/dx sqrt at 0 = inf
            x = paddle.to_tensor(np.array([0.0, 4.0], "float32"),
                                 stop_gradient=False)
            y = paddle.sqrt(x)
            with pytest.raises(FloatingPointError, match="_grad"):
                y.sum().backward()
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})

    def test_env_var_wires_hook(self):
        import subprocess, sys
        code = (
            "import numpy as np\n"
            "import paddle_tpu as paddle\n"
            "x = paddle.to_tensor(np.array([1.0], 'float32'))\n"
            "try:\n"
            "    y = x / paddle.to_tensor(np.array([0.0], 'float32'))\n"
            "    print('NO RAISE')\n"
            "except FloatingPointError:\n"
            "    print('RAISED')\n")
        r = subprocess.run([sys.executable, "-c", code],
                           env={**__import__('os').environ,
                                "FLAGS_check_nan_inf": "1",
                                "PADDLE_TPU_FORCE_CPU_DEVICES": "1"},
                           capture_output=True, text=True, timeout=240)
        assert "RAISED" in r.stdout, (r.stdout, r.stderr[-500:])
