"""paddle.onnx.export: self-contained jaxpr -> ONNX opset-11 exporter.

Reference: python/paddle/onnx/export.py (delegates to paddle2onnx; here
the converter is in-tree). With no `onnx` runtime in the image, the
exported file is verified by parsing the protobuf wire format back with
the same dependency-free reader the writer uses (paddle_tpu/onnx/_proto)
and checking the model structure: IR/opset fields, graph inputs/outputs
with shapes and dtypes, node op_types, and bit-exact initializer
payloads against the layer's weights.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.jit.api import InputSpec
from paddle_tpu.onnx import _proto as P


def _parse_model(path):
    with open(path, "rb") as f:
        buf = f.read()
    model = P.parse_message(buf)
    graph = P.parse_message(P.one(model, 7))
    nodes = [P.parse_message(b) for b in P.many(graph, 1)]
    inits = [P.parse_message(b) for b in P.many(graph, 5)]
    ins = [P.parse_message(b) for b in P.many(graph, 11)]
    outs = [P.parse_message(b) for b in P.many(graph, 12)]
    return model, graph, nodes, inits, ins, outs


def _vi_shape(vi):
    ttype = P.parse_message(P.one(P.parse_message(P.one(vi, 2)), 1))
    shape = P.parse_message(P.one(ttype, 2))
    dims = [P.one(P.parse_message(d), 1) for d in P.many(shape, 1)]
    return P.one(ttype, 1), dims


class TestOnnxExport:
    def test_mlp_structure_and_weights(self, tmp_path):
        paddle.seed(0)
        m = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 3))
        m.eval()
        out = paddle.onnx.export(
            m, str(tmp_path / "mlp"),
            input_spec=[InputSpec([2, 4], "float32", "x")])
        assert out.endswith(".onnx")
        model, graph, nodes, inits, ins, outs = _parse_model(out)

        assert P.one(model, 1) == 8                    # ir_version
        opset = P.parse_message(P.one(model, 8))
        assert P.one(opset, 2) == 11

        ops = [P.one(n, 4).decode() for n in nodes]
        assert ops.count("MatMul") == 2
        assert "Tanh" in ops
        assert "Add" in ops                            # bias adds

        # graph I/O: x [2,4] f32 -> [2,3] f32
        assert P.one(ins[0], 1) == b"x"
        et, dims = _vi_shape(ins[0])
        assert (et, dims) == (1, [2, 4])
        et, dims = _vi_shape(outs[0])
        assert (et, dims) == (1, [2, 3])

        # initializer payloads are bit-exact copies of the weights
        by_name = {P.one(t, 8).decode(): t for t in inits}
        w0 = by_name["param.0.weight"]
        want = np.asarray(m[0].weight.numpy(), np.float32)
        assert P.many(w0, 1) == [4, 8]
        got = np.frombuffer(P.one(w0, 9), np.float32).reshape(4, 8)
        np.testing.assert_array_equal(got, want)

    def test_conv_pool_net(self, tmp_path):
        paddle.seed(1)
        m = nn.Sequential(nn.Conv2D(1, 4, 3, padding=1), nn.ReLU(),
                          nn.MaxPool2D(2, 2), nn.Flatten(),
                          nn.Linear(4 * 4 * 4, 5))
        m.eval()
        out = paddle.onnx.export(
            m, str(tmp_path / "conv"),
            input_spec=[InputSpec([1, 1, 8, 8], "float32", "img")])
        _, _, nodes, inits, ins, outs = _parse_model(out)
        ops = [P.one(n, 4).decode() for n in nodes]
        assert "Conv" in ops
        assert "MaxPool" in ops
        assert "MatMul" in ops
        conv = nodes[ops.index("Conv")]
        attrs = {P.one(P.parse_message(a), 1).decode():
                 P.parse_message(a) for a in P.many(conv, 5)}
        assert [v for _, v in attrs["strides"].get(8, [])] == [1, 1]
        assert [v for _, v in attrs["pads"].get(8, [])] == [1, 1, 1, 1]
        et, dims = _vi_shape(outs[0])
        assert dims == [1, 5]

    def test_layernorm_model(self, tmp_path):
        paddle.seed(2)
        m = nn.Sequential(nn.Linear(6, 6), nn.LayerNorm(6), nn.GELU())
        m.eval()
        out = paddle.onnx.export(
            m, str(tmp_path / "ln"),
            input_spec=[InputSpec([3, 6], "float32", "x")])
        _, _, nodes, _, _, outs = _parse_model(out)
        ops = [P.one(n, 4).decode() for n in nodes]
        # LN decomposes through reductions; GELU through Erf
        assert any(o.startswith("Reduce") for o in ops)
        assert "Erf" in ops or "Tanh" in ops

    def test_unsupported_primitive_raises_with_name(self, tmp_path):
        class WithCumsum(nn.Layer):
            def forward(self, x):
                return paddle.cumsum(x, axis=0)

        m = WithCumsum()
        with pytest.raises(NotImplementedError) as ei:
            paddle.onnx.export(
                m, str(tmp_path / "bad"),
                input_spec=[InputSpec([4], "float32", "x")])
        assert "cumsum" in str(ei.value).lower()
        assert "StableHLO" in str(ei.value)

    def test_missing_input_spec_raises(self, tmp_path):
        with pytest.raises(ValueError):
            paddle.onnx.export(nn.Linear(2, 2), str(tmp_path / "m"))
