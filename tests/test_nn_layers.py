"""nn layer tests (modelled on the reference's test_layers.py and per-op
unittests; see SURVEY.md §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

from op_test import check_grad


def _randn(*shape, dtype="float32"):
    return np.random.RandomState(sum(shape) + len(shape)).randn(
        *shape).astype(dtype)


class TestLinear:
    def test_forward(self):
        lin = nn.Linear(8, 4)
        x = paddle.to_tensor(_randn(2, 8))
        y = lin(x)
        want = x.numpy() @ lin.weight.numpy() + lin.bias.numpy()
        np.testing.assert_allclose(y.numpy(), want, rtol=1e-5, atol=1e-5)

    def test_grad(self):
        lin = nn.Linear(5, 3)
        check_grad(lambda x: lin(x), [_randn(4, 5)])

    def test_no_bias(self):
        lin = nn.Linear(8, 4, bias_attr=False)
        assert lin.bias is None
        assert lin(paddle.to_tensor(_randn(2, 8))).shape == [2, 4]


class TestConv2D:
    def test_forward_shape(self):
        conv = nn.Conv2D(3, 16, 3, stride=2, padding=1)
        y = conv(paddle.to_tensor(_randn(2, 3, 8, 8)))
        assert y.shape == [2, 16, 4, 4]

    def test_vs_numpy_1x1(self):
        conv = nn.Conv2D(4, 2, 1, bias_attr=False)
        x = _randn(1, 4, 5, 5)
        y = conv(paddle.to_tensor(x))
        w = conv.weight.numpy()  # [2, 4, 1, 1]
        want = np.einsum("nchw,oc->nohw", x, w[:, :, 0, 0])
        np.testing.assert_allclose(y.numpy(), want, rtol=1e-4, atol=1e-4)

    def test_groups(self):
        conv = nn.Conv2D(4, 8, 3, groups=2, padding=1)
        assert conv(paddle.to_tensor(_randn(2, 4, 6, 6))).shape == [2, 8, 6, 6]

    def test_grad(self):
        conv = nn.Conv2D(2, 3, 3, padding=1)
        check_grad(lambda x: conv(x), [_randn(1, 2, 5, 5)], rtol=5e-2,
                   atol=5e-3)

    def test_transpose(self):
        convt = nn.Conv2DTranspose(4, 2, 3, stride=2, padding=1,
                                   output_padding=1)
        y = convt(paddle.to_tensor(_randn(2, 4, 5, 5)))
        assert y.shape == [2, 2, 10, 10]

    def test_conv1d_3d(self):
        c1 = nn.Conv1D(3, 6, 3, padding=1)
        assert c1(paddle.to_tensor(_randn(2, 3, 10))).shape == [2, 6, 10]
        c3 = nn.Conv3D(2, 4, 3, padding=1)
        assert c3(paddle.to_tensor(_randn(1, 2, 4, 4, 4))).shape == \
            [1, 4, 4, 4, 4]


class TestPooling:
    def test_max_pool(self):
        x = paddle.to_tensor(_randn(1, 2, 4, 4))
        y = F.max_pool2d(x, 2)
        want = x.numpy().reshape(1, 2, 2, 2, 2, 2).max(axis=(3, 5))
        np.testing.assert_allclose(y.numpy(), want, rtol=1e-6)

    def test_avg_pool_padding_exclusive(self):
        x = paddle.to_tensor(np.ones((1, 1, 4, 4), "float32"))
        y = F.avg_pool2d(x, 3, stride=1, padding=1, exclusive=True)
        # all-ones input with exclusive padding -> output all ones
        np.testing.assert_allclose(y.numpy(), np.ones((1, 1, 4, 4)),
                                   rtol=1e-6)

    def test_adaptive_avg(self):
        x = paddle.to_tensor(_randn(2, 3, 7, 9))
        y = F.adaptive_avg_pool2d(x, [3, 4])
        assert y.shape == [2, 3, 3, 4]
        # divisible case equals reshape-mean
        x2 = paddle.to_tensor(_randn(1, 2, 6, 6))
        y2 = F.adaptive_avg_pool2d(x2, 3)
        want = x2.numpy().reshape(1, 2, 3, 2, 3, 2).mean(axis=(3, 5))
        np.testing.assert_allclose(y2.numpy(), want, rtol=1e-5, atol=1e-6)

    def test_adaptive_max(self):
        x = paddle.to_tensor(_randn(2, 3, 7, 7))
        assert F.adaptive_max_pool2d(x, 3).shape == [2, 3, 3, 3]

    def test_global_pool(self):
        x = paddle.to_tensor(_randn(2, 5, 6, 6))
        y = F.adaptive_avg_pool2d(x, 1)
        np.testing.assert_allclose(
            y.numpy()[:, :, 0, 0], x.numpy().mean(axis=(2, 3)), rtol=1e-5,
            atol=1e-6)


class TestNorms:
    def test_batch_norm_train_stats(self):
        bn = nn.BatchNorm2D(4, momentum=0.9)
        x = _randn(8, 4, 5, 5)
        y = bn(paddle.to_tensor(x))
        mean = x.mean(axis=(0, 2, 3))
        np.testing.assert_allclose(
            bn._mean.numpy(), 0.1 * mean, rtol=1e-4, atol=1e-5)
        got_mean = y.numpy().mean(axis=(0, 2, 3))
        np.testing.assert_allclose(got_mean, np.zeros(4), atol=1e-5)

    def test_batch_norm_eval(self):
        bn = nn.BatchNorm2D(3)
        bn.eval()
        x = _randn(2, 3, 4, 4)
        y = bn(paddle.to_tensor(x))
        np.testing.assert_allclose(y.numpy(), x / np.sqrt(1 + 1e-5),
                                   rtol=1e-5, atol=1e-5)

    def test_layer_norm(self):
        ln = nn.LayerNorm(16)
        x = _randn(4, 16)
        y = ln(paddle.to_tensor(x)).numpy()
        want = (x - x.mean(-1, keepdims=True)) / np.sqrt(
            x.var(-1, keepdims=True) + 1e-5)
        np.testing.assert_allclose(y, want, rtol=1e-4, atol=1e-4)

    def test_layer_norm_grad(self):
        ln = nn.LayerNorm(8)
        check_grad(lambda x: ln(x), [_randn(3, 8)], rtol=5e-2, atol=5e-3)

    def test_group_norm(self):
        gn = nn.GroupNorm(2, 4)
        x = _randn(2, 4, 3, 3)
        y = gn(paddle.to_tensor(x)).numpy()
        xs = x.reshape(2, 2, 2, 3, 3)
        want = ((xs - xs.mean(axis=(2, 3, 4), keepdims=True)) /
                np.sqrt(xs.var(axis=(2, 3, 4), keepdims=True) + 1e-5)
                ).reshape(2, 4, 3, 3)
        np.testing.assert_allclose(y, want, rtol=1e-4, atol=1e-4)

    def test_instance_norm(self):
        inorm = nn.InstanceNorm2D(3)
        x = _randn(2, 3, 4, 4)
        y = inorm(paddle.to_tensor(x)).numpy()
        want = (x - x.mean(axis=(2, 3), keepdims=True)) / np.sqrt(
            x.var(axis=(2, 3), keepdims=True) + 1e-5)
        np.testing.assert_allclose(y, want, rtol=1e-4, atol=1e-4)

    def test_rms_norm(self):
        rn = nn.RMSNorm(8)
        x = _randn(2, 8)
        y = rn(paddle.to_tensor(x)).numpy()
        want = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(y, want, rtol=1e-4, atol=1e-4)


class TestNormLargeOffset:
    """ADVICE r4: the raw one-pass E[x^2]-mean^2 variance loses most
    precision when |mean| >> std; the shifted one-pass
    (functional/norm.py _one_pass_stats) must track an f64 two-pass
    reference on such inputs, for every norm family."""

    def _ill(self, *shape):
        rs = np.random.RandomState(0)
        return (1000.0 + 0.1 * rs.randn(*shape)).astype(np.float32)

    def test_layer_norm_large_offset(self):
        import os
        os.environ["PADDLE_TPU_FUSED_LN"] = "0"   # exercise the jnp path
        try:
            x = self._ill(4, 64)
            got = F.layer_norm(paddle.to_tensor(x), [64]).numpy()
        finally:
            os.environ.pop("PADDLE_TPU_FUSED_LN", None)
        xf = x.astype(np.float64)
        want = (xf - xf.mean(-1, keepdims=True)) / np.sqrt(
            xf.var(-1, keepdims=True) + 1e-5)
        np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-3)

    def test_batch_norm_large_offset(self):
        x = self._ill(8, 4, 6, 6)
        bn = nn.BatchNorm2D(4)
        bn.train()
        got = bn(paddle.to_tensor(x)).numpy()
        xf = x.astype(np.float64)
        mu = xf.mean((0, 2, 3), keepdims=True)
        var = xf.var((0, 2, 3), keepdims=True)
        want = (xf - mu) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-3)

    def test_group_and_instance_norm_large_offset(self):
        x = self._ill(2, 4, 5, 5)
        xf = x.astype(np.float64)
        got = F.group_norm(paddle.to_tensor(x), 2).numpy()
        gs = xf.reshape(2, 2, 2, 5, 5)
        mu = gs.mean((2, 3, 4), keepdims=True)
        var = gs.var((2, 3, 4), keepdims=True)
        want = ((gs - mu) / np.sqrt(var + 1e-5)).reshape(x.shape)
        np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-3)

        got = F.instance_norm(paddle.to_tensor(x)).numpy()
        mu = xf.mean((2, 3), keepdims=True)
        var = xf.var((2, 3), keepdims=True)
        want = (xf - mu) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-3)


class TestActivationsAndDropout:
    def test_activations(self):
        x = _randn(3, 5)
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(F.relu(t).numpy(), np.maximum(x, 0))
        np.testing.assert_allclose(F.sigmoid(t).numpy(),
                                   1 / (1 + np.exp(-x)), rtol=1e-5)
        np.testing.assert_allclose(
            F.softmax(t).numpy().sum(-1), np.ones(3), rtol=1e-5)
        np.testing.assert_allclose(F.relu6(t).numpy(),
                                   np.clip(x, 0, 6), rtol=1e-6)

    def test_dropout_train_eval(self):
        x = paddle.to_tensor(np.ones((100, 100), "float32"))
        paddle.seed(42)
        y = F.dropout(x, 0.5, training=True)
        frac = float((y.numpy() == 0).mean())
        assert 0.4 < frac < 0.6
        # upscale keeps expectation
        assert abs(float(y.numpy().mean()) - 1.0) < 0.1
        y_eval = F.dropout(x, 0.5, training=False)
        np.testing.assert_allclose(y_eval.numpy(), x.numpy())

    def test_dropout2d_whole_channels(self):
        x = paddle.to_tensor(np.ones((4, 8, 5, 5), "float32"))
        y = F.dropout2d(x, 0.5, training=True).numpy()
        per_chan = y.reshape(4, 8, -1)
        is_zero = (per_chan == 0).all(axis=2)
        is_kept = (per_chan != 0).all(axis=2)
        assert np.all(is_zero | is_kept)


class TestLosses:
    def test_mse(self):
        a, b = _randn(4, 3), _randn(4, 3)
        got = F.mse_loss(paddle.to_tensor(a), paddle.to_tensor(b))
        np.testing.assert_allclose(float(got), ((a - b) ** 2).mean(),
                                   rtol=1e-5)

    def test_cross_entropy_matches_numpy(self):
        logits = _randn(6, 10)
        label = np.array([0, 3, 9, 2, 2, 7])
        got = float(F.cross_entropy(paddle.to_tensor(logits),
                                    paddle.to_tensor(label)))
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        want = -np.log(p[np.arange(6), label]).mean()
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_cross_entropy_ignore_index(self):
        logits = _randn(4, 5)
        label = np.array([0, -100, 2, -100])
        got = float(F.cross_entropy(paddle.to_tensor(logits),
                                    paddle.to_tensor(label),
                                    ignore_index=-100))
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        want = -np.log(p[[0, 2], [0, 2]]).mean()
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_cross_entropy_soft_label(self):
        logits = _randn(3, 4)
        soft = np.abs(_randn(3, 4))
        soft /= soft.sum(-1, keepdims=True)
        got = float(F.cross_entropy(paddle.to_tensor(logits),
                                    paddle.to_tensor(soft.astype("float32")),
                                    soft_label=True))
        logp = logits - logits.max(-1, keepdims=True)
        logp = logp - np.log(np.exp(logp).sum(-1, keepdims=True))
        want = -(soft * logp).sum(-1).mean()
        np.testing.assert_allclose(got, want, rtol=1e-4)

    def test_bce_with_logits(self):
        x, y = _randn(4, 3), (np.random.rand(4, 3) > 0.5).astype("float32")
        got = float(F.binary_cross_entropy_with_logits(
            paddle.to_tensor(x), paddle.to_tensor(y)))
        p = 1 / (1 + np.exp(-x))
        want = -(y * np.log(p) + (1 - y) * np.log(1 - p)).mean()
        np.testing.assert_allclose(got, want, rtol=1e-4)

    def test_kl_div(self):
        x = np.log(np.abs(_randn(3, 4)) + 0.1).astype("float32")
        y = np.abs(_randn(3, 4)).astype("float32")
        got = float(F.kl_div(paddle.to_tensor(x), paddle.to_tensor(y),
                             reduction="sum"))
        want = (y * (np.log(y) - x)).sum()
        np.testing.assert_allclose(got, want, rtol=1e-4)

    def test_ctc_loss_simple(self):
        # T=4, N=1, C=3 (blank=0); all-equal logits -> known loss
        T, N, C = 4, 2, 3
        logits = _randn(T, N, C)
        labels = np.array([[1, 2], [1, 1]], dtype=np.int64)
        got = F.ctc_loss(paddle.to_tensor(logits),
                         paddle.to_tensor(labels),
                         paddle.to_tensor(np.array([4, 4])),
                         paddle.to_tensor(np.array([2, 2])),
                         reduction="none")
        assert got.shape == [2]
        assert np.all(np.asarray(got.numpy()) > 0)


class TestEmbeddingPad:
    def test_embedding(self):
        emb = nn.Embedding(10, 4)
        ids = paddle.to_tensor(np.array([[1, 2], [3, 4]]))
        y = emb(ids)
        np.testing.assert_allclose(
            y.numpy(), emb.weight.numpy()[[[1, 2], [3, 4]]])

    def test_embedding_padding_idx(self):
        emb = nn.Embedding(10, 4, padding_idx=0)
        y = emb(paddle.to_tensor(np.array([0, 1])))
        np.testing.assert_allclose(y.numpy()[0], np.zeros(4))

    def test_pad2d(self):
        x = paddle.to_tensor(_randn(1, 1, 2, 2))
        y = F.pad(x, [1, 1, 2, 2])  # l, r, t, b
        assert y.shape == [1, 1, 6, 4]

    def test_interpolate_nearest(self):
        x = paddle.to_tensor(_randn(1, 2, 4, 4))
        y = F.interpolate(x, scale_factor=2, mode="nearest")
        assert y.shape == [1, 2, 8, 8]
        np.testing.assert_allclose(
            y.numpy()[:, :, ::2, ::2], x.numpy(), rtol=1e-6)


class TestAttention:
    def test_sdpa_matches_ref(self):
        q = _randn(2, 6, 4, 8)
        k = _randn(2, 6, 4, 8)
        v = _randn(2, 6, 4, 8)
        out = F.scaled_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v))
        logits = np.einsum("blhd,bmhd->bhlm", q, k) / np.sqrt(8)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        want = np.einsum("bhlm,bmhd->blhd", p, v)
        np.testing.assert_allclose(out.numpy(), want, rtol=1e-4, atol=1e-4)

    def test_sdpa_causal(self):
        q = _randn(1, 4, 2, 8)
        out = F.scaled_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(q), paddle.to_tensor(q),
            is_causal=True)
        # first position attends only to itself
        np.testing.assert_allclose(out.numpy()[:, 0], q[:, 0], rtol=1e-4,
                                   atol=1e-5)

    def test_multi_head_attention(self):
        mha = nn.MultiHeadAttention(32, 4)
        x = paddle.to_tensor(_randn(2, 6, 32), stop_gradient=False)
        y = mha(x)
        assert y.shape == [2, 6, 32]
        y.mean().backward()
        assert mha.q_proj.weight.grad is not None

    def test_mha_cache(self):
        mha = nn.MultiHeadAttention(16, 2)
        x = paddle.to_tensor(_randn(1, 3, 16))
        cache = mha.gen_cache(x, x)
        step = paddle.to_tensor(_randn(1, 1, 16))
        out, new_cache = mha(step, step, step, cache=cache)
        assert out.shape == [1, 1, 16]
        assert new_cache.k.shape[1] == 4


class TestTransformer:
    def test_encoder(self):
        layer = nn.TransformerEncoderLayer(32, 4, 64)
        enc = nn.TransformerEncoder(layer, 2)
        x = paddle.to_tensor(_randn(2, 5, 32))
        assert enc(x).shape == [2, 5, 32]
        # layers must have independent params
        p0 = enc.layers[0].linear1.weight
        p1 = enc.layers[1].linear1.weight
        assert p0 is not p1

    def test_full_transformer(self):
        t = nn.Transformer(d_model=32, nhead=4, num_encoder_layers=2,
                           num_decoder_layers=2, dim_feedforward=64)
        src = paddle.to_tensor(_randn(2, 5, 32))
        tgt = paddle.to_tensor(_randn(2, 4, 32))
        out = t(src, tgt)
        assert out.shape == [2, 4, 32]


class TestRNN:
    def test_lstm_shapes(self):
        lstm = nn.LSTM(8, 16, num_layers=2)
        x = paddle.to_tensor(_randn(3, 5, 8))
        y, (h, c) = lstm(x)
        assert y.shape == [3, 5, 16]
        assert h.shape == [2, 3, 16] and c.shape == [2, 3, 16]

    def test_gru_cell_vs_net(self):
        gru = nn.GRU(4, 8, num_layers=1)
        x = _randn(2, 3, 4)
        y, h = gru(paddle.to_tensor(x))
        # replay with the cell equations in numpy
        w_ih = gru.weight_ih_l0.numpy()
        w_hh = gru.weight_hh_l0.numpy()
        b_ih = gru.bias_ih_l0.numpy()
        b_hh = gru.bias_hh_l0.numpy()
        ht = np.zeros((2, 8), "float32")
        sig = lambda v: 1 / (1 + np.exp(-v))
        for t in range(3):
            xg = x[:, t] @ w_ih.T + b_ih
            hg = ht @ w_hh.T + b_hh
            xr, xz, xc = np.split(xg, 3, -1)
            hr, hz, hc = np.split(hg, 3, -1)
            r, z = sig(xr + hr), sig(xz + hz)
            c = np.tanh(xc + r * hc)
            ht = z * ht + (1 - z) * c
        np.testing.assert_allclose(y.numpy()[:, -1], ht, rtol=1e-4,
                                   atol=1e-4)

    def test_rnn_wrapper_cell(self):
        cell = nn.LSTMCell(6, 10)
        rnn = nn.RNN(cell)
        x = paddle.to_tensor(_randn(2, 4, 6))
        y, (h, c) = rnn(x)
        assert y.shape == [2, 4, 10]
        assert h.shape == [2, 10]


class TestLayerMechanics:
    def test_hooks(self):
        lin = nn.Linear(4, 4)
        calls = []
        h = lin.register_forward_post_hook(
            lambda layer, inp, out: calls.append(1))
        lin(paddle.to_tensor(_randn(1, 4)))
        assert calls == [1]
        h.remove()
        lin(paddle.to_tensor(_randn(1, 4)))
        assert calls == [1]

    def test_train_eval_propagate(self):
        m = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.5))
        m.eval()
        assert not m[1].training
        m.train()
        assert m[1].training

    def test_named_parameters(self):
        m = nn.Sequential(nn.Linear(4, 4), nn.Linear(4, 2))
        names = dict(m.named_parameters())
        assert "0.weight" in names and "1.bias" in names

    def test_state_dict_roundtrip(self):
        m = nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1D(8))
        sd = m.state_dict()
        assert "1._mean" in sd
        m2 = nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1D(8))
        missing, unexpected = m2.set_state_dict(sd)
        assert not missing and not unexpected
        np.testing.assert_allclose(m2[0].weight.numpy(),
                                   m[0].weight.numpy())

    def test_apply_and_sublayers(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Sequential(nn.Linear(2, 2)))
        n_layers = len(m.sublayers())
        assert n_layers == 3
        seen = []
        m.apply(lambda l: seen.append(type(l).__name__))
        assert len(seen) == 4  # includes self

    def test_clip_global_norm(self):
        clip = nn.ClipGradByGlobalNorm(1.0)
        p = paddle.to_tensor(_randn(3, 3), stop_gradient=False)
        g = paddle.to_tensor(np.full((3, 3), 10.0, "float32"))
        out = clip([(p, g)])
        norm = np.linalg.norm(out[0][1].numpy())
        np.testing.assert_allclose(norm, 1.0, rtol=1e-4)


class TestMaxPoolMask:
    def test_return_mask_unpool_roundtrip(self):
        import paddle_tpu.nn.functional as F
        rs = np.random.RandomState(0)
        x = rs.randn(2, 3, 8, 8).astype("float32")
        out, mask = F.max_pool2d(paddle.to_tensor(x), kernel_size=2,
                                 return_mask=True)
        restored = F.max_unpool2d(out, mask, kernel_size=2)
        r, o, m = restored.numpy(), out.numpy(), mask.numpy()
        flat = r.reshape(2, 3, -1)
        np.testing.assert_allclose(
            np.take_along_axis(flat, m.reshape(2, 3, -1), axis=-1),
            o.reshape(2, 3, -1))
        # pooled values are the true window maxima
        win = x.reshape(2, 3, 4, 2, 4, 2).transpose(0, 1, 2, 4, 3, 5)
        np.testing.assert_allclose(o, win.reshape(2, 3, 4, 4, 4).max(-1))

    def test_return_mask_nested_padding_and_ceil(self):
        import paddle_tpu.nn.functional as F
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(1, 2, 7, 7).astype("float32"))
        out, mask = F.max_pool2d(x, 2, padding=[[1, 1], [1, 1]],
                                 return_mask=True)
        assert out.shape == list(mask.shape)
        out, mask = F.max_pool2d(x, 2, stride=2, ceil_mode=True,
                                 return_mask=True)
        assert out.shape == list(mask.shape) == [1, 2, 4, 4]
        with pytest.raises(NotImplementedError):
            F.max_pool2d(x, 2, padding=[[1, 0], [1, 1]],
                         return_mask=True)


class TestAdaptiveMaxPoolMask:
    def _ref_mask2d(self, x, oh, ow):
        n, c, H, W = x.shape
        out = np.zeros((n, c, oh, ow), np.int64)
        for i in range(oh):
            lo_h, hi_h = (i * H) // oh, -(-((i + 1) * H) // oh)
            for j in range(ow):
                lo_w, hi_w = (j * W) // ow, -(-((j + 1) * W) // ow)
                win = x[:, :, lo_h:hi_h, lo_w:hi_w].reshape(n, c, -1)
                a = win.argmax(-1)
                ww = hi_w - lo_w
                out[:, :, i, j] = (a // ww + lo_h) * W + (a % ww + lo_w)
        return out

    def test_adaptive_max_pool2d_mask_matches_numpy(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 7, 10).astype("float32")
        out, mask = F.adaptive_max_pool2d(paddle.to_tensor(x), (3, 4),
                                          return_mask=True)
        want = self._ref_mask2d(x, 3, 4)
        np.testing.assert_array_equal(mask.numpy(), want)
        # mask indexes recover the pooled values
        flat = x.reshape(2, 3, -1)
        np.testing.assert_allclose(
            np.take_along_axis(flat, mask.numpy().reshape(2, 3, -1),
                               -1).reshape(out.shape),
            out.numpy(), rtol=1e-6)

    def test_adaptive_max_pool1d_mask(self):
        rng = np.random.RandomState(1)
        x = rng.randn(2, 4, 9).astype("float32")
        out, mask = F.adaptive_max_pool1d(paddle.to_tensor(x), 4,
                                          return_mask=True)
        flat = x.reshape(2, 4, -1)
        np.testing.assert_allclose(
            np.take_along_axis(flat, mask.numpy().reshape(2, 4, -1),
                               -1).reshape(out.shape),
            out.numpy(), rtol=1e-6)


class TestRNNTLoss:
    def _ref_rnnt(self, logits, labels, t_len, u_len, blank):
        # independent numpy DP over the alignment lattice
        lp = logits - logits.max(-1, keepdims=True)
        lp = lp - np.log(np.exp(lp).sum(-1, keepdims=True))
        T, U1, V = lp.shape
        alpha = np.full((t_len, u_len + 1), -np.inf)
        alpha[0, 0] = 0.0
        for t in range(t_len):
            for u in range(u_len + 1):
                cands = []
                if t > 0:
                    cands.append(alpha[t - 1, u] + lp[t - 1, u, blank])
                if u > 0:
                    cands.append(alpha[t, u - 1]
                                 + lp[t, u - 1, labels[u - 1]])
                if cands:
                    m = max(cands)
                    alpha[t, u] = m + np.log(
                        sum(np.exp(c - m) for c in cands))
        return -(alpha[t_len - 1, u_len]
                 + lp[t_len - 1, u_len, blank])

    def test_matches_numpy_lattice(self):
        rng = np.random.RandomState(0)
        B, T, U, V = 3, 6, 4, 8
        logits = rng.randn(B, T, U + 1, V).astype("float32")
        labels = rng.randint(1, V, (B, U)).astype("int64")
        t_lens = np.array([6, 5, 4], "int64")
        u_lens = np.array([4, 3, 2], "int64")
        got = F.rnnt_loss(paddle.to_tensor(logits),
                          paddle.to_tensor(labels),
                          paddle.to_tensor(t_lens),
                          paddle.to_tensor(u_lens),
                          blank=0, fastemit_lambda=0.0,
                          reduction="none").numpy()
        want = [self._ref_rnnt(logits[b], labels[b], int(t_lens[b]),
                               int(u_lens[b]), 0) for b in range(B)]
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_grad_flows_and_mean_reduction(self):
        rng = np.random.RandomState(1)
        logits = paddle.to_tensor(
            rng.randn(2, 4, 3, 5).astype("float32"))
        logits.stop_gradient = False
        loss = F.rnnt_loss(logits,
                           paddle.to_tensor(
                               rng.randint(1, 5, (2, 2)).astype("int64")),
                           paddle.to_tensor(np.array([4, 3], "int64")),
                           paddle.to_tensor(np.array([2, 1], "int64")))
        loss.backward()
        g = logits.grad.numpy()
        assert np.isfinite(g).all() and np.abs(g).sum() > 0


class TestHubOnnx:
    def test_hub_local_list_help_load(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "dependencies = ['numpy']\n"
            "def tiny_model(scale=2.0):\n"
            "    'builds a tiny model'\n"
            "    return ('model', scale)\n")
        import paddle_tpu.hub as hub
        assert hub.list(str(tmp_path), source="local") == ["tiny_model"]
        assert "tiny" in hub.help(str(tmp_path), "tiny_model",
                                  source="local")
        assert hub.load(str(tmp_path), "tiny_model", source="local",
                        scale=3.0) == ("model", 3.0)

    def test_hub_network_sources_gated(self, tmp_path):
        import paddle_tpu.hub as hub
        with pytest.raises(NotImplementedError):
            hub.list("PaddlePaddle/PaddleClas", source="github")
        with pytest.raises(ValueError):
            hub.list(str(tmp_path), source="bitbucket")

    def test_onnx_export_requires_input_spec(self):
        import paddle_tpu as paddle
        m = paddle.nn.Linear(2, 2)
        with pytest.raises(ValueError) as ei:
            paddle.onnx.export(m, "/tmp/m")
        assert "input_spec" in str(ei.value)
