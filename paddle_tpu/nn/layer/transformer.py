"""Transformer layers.

Reference: python/paddle/nn/layer/transformer.py (:113 MultiHeadAttention,
:456 TransformerEncoderLayer, :1181 Transformer). Same API; the attention
core routes through F.scaled_dot_product_attention, which picks the Pallas
flash-attention kernel on TPU instead of the reference's per-head matmul
chain — one custom-call instead of the fused_attention_op.cu monolith.
"""
from __future__ import annotations

import collections

import numpy as np

from ...core.tensor import Tensor
from .layers import Layer
from .common import Linear, Dropout
from .norm import LayerNorm
from .container import LayerList
from .. import functional as F

__all__ = ["MultiHeadAttention", "TransformerEncoderLayer",
           "TransformerEncoder", "TransformerDecoderLayer",
           "TransformerDecoder", "Transformer"]


def _convert_attention_mask(attn_mask, dtype):
    """bool mask (True=keep) -> additive; numeric passes through.
    reference: nn/layer/transformer.py _convert_attention_mask."""
    if attn_mask is None:
        return None
    from ...ops import math as math_ops
    if attn_mask.dtype == "bool":
        from ...ops import creation
        neg = math_ops.scale(
            math_ops.cast(math_ops.logical_not(attn_mask), dtype), -1e9)
        return neg
    return attn_mask


class MultiHeadAttention(Layer):
    """reference: python/paddle/nn/layer/transformer.py:113."""

    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.kdim = kdim if kdim is not None else embed_dim
        self.vdim = vdim if vdim is not None else embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        self.head_dim = embed_dim // num_heads
        if self.head_dim * num_heads != embed_dim:
            raise ValueError("embed_dim must be divisible by num_heads")
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _reshape_heads(self, x):
        from ...ops import manipulation
        # [B, L, E] -> [B, L, H, D]
        return manipulation.reshape(
            x, [0, 0, self.num_heads, self.head_dim])

    def gen_cache(self, key, value=None, type=Cache):
        from ...ops import manipulation, creation
        if type == MultiHeadAttention.StaticCache:
            k, v = self.k_proj(key), self.v_proj(value if value is not None
                                                 else key)
            return self.StaticCache(self._reshape_heads(k),
                                    self._reshape_heads(v))
        if value is None:
            # incremental cache seeded empty at given batch size
            batch = key.shape[0]
            from ...core import dtype as dtypes
            import jax.numpy as jnp
            k = Tensor(jnp.zeros((batch, 0, self.num_heads, self.head_dim),
                                 dtype=dtypes.get_default_dtype().np_dtype))
            return self.Cache(k, Tensor(k._value))
        return self.Cache(self._reshape_heads(self.k_proj(key)),
                          self._reshape_heads(self.v_proj(value)))

    def gen_decode_cache(self, batch_size, max_len, dtype=None):
        """Static max-length KV cache for compiled decoding (the
        reference's fused_multi_transformer in-place cache_kv — see
        nlp/generation.py DecodeCache)."""
        from ...nlp.generation import init_decode_caches
        return init_decode_caches(1, batch_size, max_len, self.num_heads,
                                  self.head_dim, dtype=dtype)[0]

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        from ...ops import manipulation
        key = query if key is None else key
        value = query if value is None else value
        q = self._reshape_heads(self.q_proj(query))
        from ...nlp.generation import DecodeCache, update_and_attend
        if isinstance(cache, DecodeCache):
            k = self._reshape_heads(self.k_proj(key))
            v = self._reshape_heads(self.v_proj(value))
            out, new_cache = update_and_attend(
                q, k, v, cache, dropout_p=self.dropout,
                training=self.training,
                attn_mask=_convert_attention_mask(attn_mask, q.dtype))
            out = manipulation.reshape(out, [0, 0, self.embed_dim])
            out = self.out_proj(out)
            outs = [out]
            if self.need_weights:
                outs.append(None)
            outs.append(new_cache)
            return tuple(outs)
        if isinstance(cache, MultiHeadAttention.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self._reshape_heads(self.k_proj(key))
            v = self._reshape_heads(self.v_proj(value))
            if isinstance(cache, MultiHeadAttention.Cache):
                k = manipulation.concat([cache.k, k], axis=1)
                v = manipulation.concat([cache.v, v], axis=1)
                cache = MultiHeadAttention.Cache(k, v)
        mask = _convert_attention_mask(attn_mask, q.dtype)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=mask, dropout_p=self.dropout,
            training=self.training)
        out = manipulation.reshape(out, [0, 0, self.embed_dim])
        out = self.out_proj(out)
        outs = [out]
        if self.need_weights:
            outs.append(None)  # weights not materialized on the flash path
        if cache is not None:
            outs.append(cache)
        return out if len(outs) == 1 else tuple(outs)


class TransformerEncoderLayer(Layer):
    """reference: python/paddle/nn/layer/transformer.py:456."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout, weight_attr=weight_attr,
            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.dropout = Dropout(act_dropout, mode="upscale_in_train")
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout, mode="upscale_in_train")
        self.dropout2 = Dropout(dropout, mode="upscale_in_train")
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, incremental_cache = self.self_attn(src, src, src, src_mask,
                                                    cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, incremental_cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList([encoder_layer] + [
            _deepcopy_layer(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask)
            else:
                output, new_cache = mod(output, src_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.dropout = Dropout(act_dropout, mode="upscale_in_train")
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm3 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout, mode="upscale_in_train")
        self.dropout2 = Dropout(dropout, mode="upscale_in_train")
        self.dropout3 = Dropout(dropout, mode="upscale_in_train")
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        else:
            tgt, incremental_cache = self.self_attn(tgt, tgt, tgt, tgt_mask,
                                                    cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        else:
            tgt, static_cache = self.cross_attn(tgt, memory, memory,
                                                memory_mask, cache[1])
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (incremental_cache,
                                                static_cache))

    def gen_cache(self, memory):
        incremental = self.self_attn.gen_cache(memory)
        static = self.cross_attn.gen_cache(
            memory, memory, type=MultiHeadAttention.StaticCache)
        return incremental, static


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList([decoder_layer] + [
            _deepcopy_layer(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask, memory_mask)
            else:
                output, new_cache = mod(output, memory, tgt_mask,
                                        memory_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        cache = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            cache = list(zip(*cache))
        return cache


def _deepcopy_layer(layer):
    """Fresh copy with re-initialized parameters (paddle uses type(layer)
    reconstruction via copy.deepcopy; params are re-created to stay
    independent)."""
    import copy
    new = copy.deepcopy(layer)
    # deep-copied jax arrays share buffers (immutable), which is fine;
    # but parameters must be distinct objects — rebuild them
    for (_, p_new), (_, p_old) in zip(new.named_parameters(),
                                      layer.named_parameters()):
        if p_new is p_old:
            raise RuntimeError("deepcopy failed to clone parameters")
    return new


class Transformer(Layer):
    """reference: python/paddle/nn/layer/transformer.py:1181."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            encoder_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            encoder_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(encoder_layer,
                                              num_encoder_layers,
                                              encoder_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            decoder_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            decoder_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(decoder_layer,
                                              num_decoder_layers,
                                              decoder_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    def generate_square_subsequent_mask(self, length):
        import jax.numpy as jnp
        from ...core import dtype as dtypes
        m = jnp.triu(jnp.full((length, length), -np.inf,
                              dtype=dtypes.get_default_dtype().np_dtype), 1)
        return Tensor(m)
