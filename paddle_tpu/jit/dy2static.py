"""dy2static: AST conversion of tensor-predicated Python control flow.

TPU-native counterpart of the reference's dy2static transformer stack
(/root/reference/python/paddle/jit/dy2static/program_translator.py:272,
ifelse_transformer.py / loop_transformer.py, convert_operators.py).
Trace-based `to_static` handles everything EXCEPT native Python
`if`/`while` on Tensor conditions (a tracer has no bool). This pass
rewrites exactly those statements into calls of the existing
`ops.cond` / `ops.while_loop` via runtime dispatchers that keep plain
Python semantics when the predicate is not a Tensor:

    if x.sum() > 0:            (out,) = __pt_ifelse(x.sum() > 0,
        y = x * 2        ->                         _true, _false, (y,))
    else:
        y = x - 1

The reference's transformer suite is ~13k LoC because it must build
ProgramDesc sub-blocks; under tracing the branches stay ordinary Python
functions, so the whole pass is variable-capture analysis:
- outputs  = names assigned in either branch (simple targets)
- params   = outputs already bound before the statement
- anything else is read through the closure unchanged.

Control transfers (reference break_continue_transformer.py:1,
return_transformer.py:1, early_return_transformer.py:1) are
functionalized with carried bool flags:

    while c:              __brk = False
        ...               while __pt_and(__pt_not(__brk), c):
        if p: break   ->      ...
        ...                   (__brk,) = __pt_ifelse(p, set_true, id, ...)
                              if __pt_not(__brk): ...rest...

`continue` sets a per-iteration flag that guards the remainder of the
body; a mid-loop `return X` sets the break flag plus a return flag and
a site index — X itself is re-evaluated AFTER the loop from the exited
carry state (guards guarantee the carried names still hold their values
from the breaking iteration), which avoids carrying a value whose
shape/dtype is unknown before the first iteration. Early-return chains
at function level (`if c: return a` ... `return b`) absorb the tail as
the else branch recursively.

Statements that still cannot be functionalized keep their original
form: yield, del/global/nonlocal, transfers inside with/try blocks,
assignments to names that are neither pre-bound nor assigned in both
branches. Those work eagerly; under tracing they raise the standard
tracer-bool error.
"""
from __future__ import annotations

import ast
import inspect
import textwrap

__all__ = ["convert_control_flow", "cfg_helpers"]

_TRUE = "__pt_true_{n}"
_FALSE = "__pt_false_{n}"
_WCOND = "__pt_wcond_{n}"
_WBODY = "__pt_wbody_{n}"
_IFELSE = "__pt_ifelse"
_WHILE = "__pt_while"


# -- runtime dispatchers ------------------------------------------------------

def _tensorize(v):
    """Python scalar -> Tensor for the lax control-flow paths: a plain
    bool/int left in the carry would be flattened into the STATIC spec
    (a baked constant), so e.g. a break flag would never update and the
    compiled while would not terminate."""
    from ..core.tensor import Tensor
    if isinstance(v, Tensor):
        return v
    from ..ops.creation import to_tensor
    import numpy as _np
    return to_tensor(_np.asarray(v))


def _tensorized_fn(fn):
    def g(*a):
        out = fn(*a)
        if isinstance(out, (tuple, list)):
            return tuple(_tensorize(o) for o in out)
        return _tensorize(out)
    return g


def _dispatch_ifelse(pred, true_fn, false_fn, args):
    from ..core.tensor import Tensor
    if isinstance(pred, Tensor):
        from ..ops import control_flow
        return control_flow.cond(
            pred, _tensorized_fn(true_fn), _tensorized_fn(false_fn),
            operands=tuple(_tensorize(a) for a in args))
    return true_fn(*args) if pred else false_fn(*args)


def _dispatch_for_range(start, stop, step, body_fn, args,
                        target_default=None):
    """for <target> in range(start, stop, step): functionalized. Python
    ints run the real for loop; Tensor bounds lower to while_loop.
    Returns (last_target_value, *carried); on an EMPTY range the target
    keeps `target_default` (its pre-loop binding), matching Python."""
    from ..core.tensor import Tensor
    if not any(isinstance(v, Tensor) for v in (start, stop, step)):
        vars_ = list(args)
        i = target_default
        for i in range(start, stop, step):
            out = body_fn(i, *vars_)
            vars_ = list(out) if isinstance(out, (list, tuple)) \
                else [out]
        return (i,) + tuple(vars_)
    from ..ops import control_flow
    from ..ops.creation import to_tensor
    import numpy as _np

    def _t(v):
        return v if isinstance(v, Tensor) else \
            to_tensor(_np.asarray(v, _np.int64))

    start, stop = _t(start), _t(stop)
    step_is_pos = not isinstance(step, Tensor) and step > 0
    step_is_neg = not isinstance(step, Tensor) and step < 0
    step = _t(step)
    last0 = _t(target_default) if isinstance(
        target_default, (int, Tensor)) else start - step

    if step_is_pos:
        def cond_fn(i, last, *vs):
            return i < stop
    elif step_is_neg:
        def cond_fn(i, last, *vs):
            return i > stop
    else:
        def cond_fn(i, last, *vs):
            return ((step > 0) & (i < stop)) | \
                ((step < 0) & (i > stop))

    def loop_body(i, last, *vs):
        out = body_fn(i, *vs)
        out = list(out) if isinstance(out, (list, tuple)) else [out]
        return [i + step, i] + out

    final = control_flow.while_loop(cond_fn, loop_body,
                                    [start, last0] + list(args))
    return (final[1],) + tuple(final[2:])


def _dispatch_while(cond_fn, body_fn, args):
    from ..core.tensor import Tensor
    vars_ = list(args)
    first = cond_fn(*vars_)
    while not isinstance(first, Tensor):
        # python predicate: run the real loop. The predicate can TURN
        # tensor mid-loop (e.g. a python range whose break flag is
        # tensor-valued after the first body run) — fall through to the
        # compiled while from the current state when it does.
        if not bool(first):
            return tuple(vars_)
        out = body_fn(*vars_)
        vars_ = list(out) if isinstance(out, (list, tuple)) else [out]
        first = cond_fn(*vars_)
    from ..ops import control_flow
    vars_ = [_tensorize(v) for v in vars_]
    return tuple(control_flow.while_loop(
        cond_fn, _tensorized_fn(body_fn), vars_))


_FORRANGE = "__pt_forrange"


def _pt_not(x):
    from ..core.tensor import Tensor
    if isinstance(x, Tensor):
        from ..ops import math as _m
        return _m.logical_not(x)
    return not x


def _pt_or(a, b):
    from ..core.tensor import Tensor
    if isinstance(a, Tensor) or isinstance(b, Tensor):
        from ..ops import math as _m
        return _m.logical_or(as_tensor_bool(a), as_tensor_bool(b))
    return a or b


def _pt_and(a, b):
    from ..core.tensor import Tensor
    if isinstance(a, Tensor) or isinstance(b, Tensor):
        from ..ops import math as _m
        return _m.logical_and(as_tensor_bool(a), as_tensor_bool(b))
    return a and b


def as_tensor_bool(v):
    from ..core.tensor import Tensor
    if isinstance(v, Tensor):
        return v
    from ..ops.creation import to_tensor
    import numpy as _np
    return to_tensor(_np.asarray(bool(v)))


def _pt_guard_test(brk, test_thunk):
    """Loop predicate under a break flag, with Python's short-circuit:
    after `break` fired, the original test must NOT be re-evaluated
    (it may rely on state the loop no longer maintains, e.g.
    `while q[0] > 0: ... break` on a now-empty list). Tensor flags
    evaluate both sides — safe, the traced test is pure."""
    from ..core.tensor import Tensor
    if isinstance(brk, Tensor):
        from ..ops import math as _m
        return _m.logical_and(_m.logical_not(brk),
                              as_tensor_bool(test_thunk()))
    if brk:
        return False
    return test_thunk()


def _pt_forcond(i, stop, step):
    """range-style continuation test with sign handling for Tensor step."""
    from ..core.tensor import Tensor
    if not any(isinstance(v, Tensor) for v in (i, stop, step)):
        return i < stop if step > 0 else i > stop
    from ..ops import math as _m
    i, stop, step = (v if isinstance(v, Tensor) else as_tensor_int(v)
                     for v in (i, stop, step))
    return _m.logical_or(_m.logical_and(step > 0, i < stop),
                         _m.logical_and(step < 0, i > stop))


def as_tensor_int(v):
    from ..ops.creation import to_tensor
    import numpy as _np
    return to_tensor(_np.asarray(v, _np.int64))


_NOT = "__pt_not"
_OR = "__pt_or"
_AND = "__pt_and"
_FORCOND = "__pt_forcond"
_GUARDTEST = "__pt_guardtest"

cfg_helpers = {_IFELSE: _dispatch_ifelse, _WHILE: _dispatch_while,
               _FORRANGE: _dispatch_for_range, _NOT: _pt_not,
               _OR: _pt_or, _AND: _pt_and, _FORCOND: _pt_forcond,
               _GUARDTEST: _pt_guard_test}


# -- analysis helpers ---------------------------------------------------------

def _assigned_names(nodes):
    """Simple-Name assignment targets in a statement list (recursing into
    nested compound statements but NOT nested function/class defs)."""
    names: set[str] = set()

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_ClassDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

        def visit_Name(self, node):
            if isinstance(node.ctx, ast.Store):
                names.add(node.id)

    for n in nodes:
        V().visit(n)
    return names


def _has_unsupported(nodes):
    """Control transfers / scope statements the functionalization cannot
    express."""
    found = []

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_ClassDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

        def generic_visit(self, node):
            if isinstance(node, (ast.Return, ast.Break, ast.Continue,
                                 ast.Yield, ast.YieldFrom, ast.Global,
                                 ast.Nonlocal, ast.Delete)):
                found.append(node)
            ast.NodeVisitor.generic_visit(self, node)

    for n in nodes:
        V().visit(n)
    return bool(found)


def _returns_cleanly(stmts):
    """Block always returns and is convertible: last statement is a
    `return` (or an if whose branches both qualify), and everything
    before it is free of control transfers EXCEPT absorbable early
    `if c: return ...` statements — `_block` folds those into nested
    else-branches, and even unconverted they remain valid Python."""
    if not stmts:
        return False
    *init, last = stmts
    for st in init:
        if isinstance(st, ast.If) and not st.orelse and \
                _returns_cleanly(st.body):
            continue
        if _has_unsupported([st]):
            return False
    if isinstance(last, ast.Return):
        return True
    if isinstance(last, ast.If) and last.orelse:
        return _returns_cleanly(last.body) and _returns_cleanly(last.orelse)
    return False


# -- loop control-transfer functionalization ---------------------------------

class _CannotGuard(Exception):
    """Transfer in a position the guard rewrite cannot express
    (inside with/try, etc.) — keep the original Python loop."""


class _TransferScan(ast.NodeVisitor):
    """Which transfers does this loop body contain at loop level (i.e.
    not inside a nested loop, which owns its own break/continue)?"""

    def __init__(self):
        self.has_break = self.has_continue = self.has_return = False
        self.in_guarded = False  # transfer under with/try

    def _skip(self, node):
        pass

    visit_FunctionDef = visit_AsyncFunctionDef = _skip
    visit_ClassDef = visit_Lambda = _skip
    visit_For = visit_AsyncFor = visit_While = _skip

    def visit_Break(self, node):
        self.has_break = True

    def visit_Continue(self, node):
        self.has_continue = True

    def visit_Return(self, node):
        self.has_return = True

    def visit_With(self, node):
        sub = _scan_transfers(node.body)
        if sub.has_break or sub.has_continue or sub.has_return:
            self.in_guarded = True

    visit_AsyncWith = visit_With

    def visit_Try(self, node):
        blocks = node.body + node.orelse + node.finalbody + \
            [s for h in node.handlers for s in h.body]
        sub = _scan_transfers(blocks)
        if sub.has_break or sub.has_continue or sub.has_return:
            self.in_guarded = True


def _scan_transfers(stmts):
    sc = _TransferScan()
    for s in stmts:
        sc.visit(s)
    return sc


def _prelude_writes(stmts):
    """Names bound by simple assignments in the body's straight-line
    prefix, whose RHS does not read the name itself — established fresh
    every iteration, so they are loop-local."""
    out: set[str] = set()
    for st in stmts:
        if isinstance(st, ast.Assign) and all(
                isinstance(t, ast.Name) for t in st.targets):
            reads = {n.id for n in ast.walk(st.value)
                     if isinstance(n, ast.Name)
                     and isinstance(n.ctx, ast.Load)}
            for t in st.targets:
                if t.id not in reads:
                    out.add(t.id)
            continue
        if isinstance(st, ast.FunctionDef):
            continue  # generated helper defs don't read bindings yet
        break
    return out


def _name(n, ctx=ast.Load):
    return ast.Name(id=n, ctx=ctx())

def _assign(n, value):
    return ast.Assign(targets=[_name(n, ast.Store)], value=value)


def _call(fn_name, *args):
    return ast.Call(func=_name(fn_name), args=list(args), keywords=[])


class _GuardRewriter:
    """Rewrite one loop body: break/continue/return -> flag sets, with
    the remainder after any flag-setting statement guarded by
    `if __pt_not(__pt_or(flags...)):` (reference
    break_continue_transformer.py:1 scheme). Return sites record a site
    index; their value expressions are re-emitted after the loop."""

    def __init__(self, brk, cont, ret, retidx):
        self.brk, self.cont, self.ret, self.retidx = brk, cont, ret, retidx
        self.sites: list = []  # return value expressions

    def _flags_or(self):
        names = [f for f in (self.brk, self.cont) if f is not None]
        test = _name(names[0])
        for f in names[1:]:
            test = _call(_OR, test, _name(f))
        return test

    def rewrite(self, stmts):
        out = []
        for idx, st in enumerate(stmts):
            rest = stmts[idx + 1:]
            if isinstance(st, ast.Break):
                out.append(_assign(self.brk, ast.Constant(value=True)))
                return out  # rest is unreachable
            if isinstance(st, ast.Continue):
                out.append(_assign(self.cont, ast.Constant(value=True)))
                return out
            if isinstance(st, ast.Return):
                k = len(self.sites)
                self.sites.append(st.value or ast.Constant(value=None))
                out.append(_assign(self.brk, ast.Constant(value=True)))
                out.append(_assign(self.ret, ast.Constant(value=True)))
                out.append(_assign(self.retidx, ast.Constant(value=k)))
                return out
            sub = _scan_transfers([st])
            if sub.in_guarded:
                raise _CannotGuard()
            if sub.has_break or sub.has_continue or sub.has_return:
                if not isinstance(st, ast.If):
                    raise _CannotGuard()  # transfer under for/with/try
                st = ast.If(test=st.test, body=self.rewrite(st.body),
                            orelse=self.rewrite(st.orelse))
                out.append(st)
                if rest:
                    out.append(ast.If(
                        test=_call(_NOT, self._flags_or()),
                        body=self.rewrite(rest), orelse=[]))
                return out
            out.append(st)
        return out


def _make_fn(name, params, body, returns):
    """def name(params): body; return (returns,)"""
    ret = ast.Return(value=ast.Tuple(
        elts=[ast.Name(id=o, ctx=ast.Load()) for o in returns],
        ctx=ast.Load()))
    args = ast.arguments(
        posonlyargs=[], args=[ast.arg(arg=p) for p in params],
        vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
        defaults=[])
    return ast.FunctionDef(name=name, args=args,
                           body=(body or [ast.Pass()]) + [ret],
                           decorator_list=[], returns=None,
                           type_params=[])


def _call_helper(helper, head_args, params):
    return ast.Call(
        func=ast.Name(id=helper, ctx=ast.Load()),
        args=head_args + [ast.Tuple(
            elts=[ast.Name(id=p, ctx=ast.Load()) for p in params],
            ctx=ast.Load())],
        keywords=[])


def _unpack_assign(outs, value):
    target = ast.Tuple(elts=[ast.Name(id=o, ctx=ast.Store())
                             for o in outs], ctx=ast.Store())
    return ast.Assign(targets=[target], value=value)


class _Converter:
    def __init__(self):
        self.n = 0
        self.changed = False

    def transform_function(self, fndef: ast.FunctionDef):
        bound = {a.arg for a in fndef.args.args +
                 fndef.args.posonlyargs + fndef.args.kwonlyargs}
        for extra in (fndef.args.vararg, fndef.args.kwarg):
            if extra is not None:
                bound.add(extra.arg)
        fndef.body = self._block(fndef.body, bound, top=True)
        return fndef

    def _block(self, stmts, bound, top=False):
        out = []
        work = list(stmts)
        while work:
            st = work.pop(0)
            # `if c: return A` + trailing code ending in return: absorb
            # the tail as the else branch (both paths then return, so
            # nothing follows the converted statement)
            if isinstance(st, ast.If) and not st.orelse and \
                    _returns_cleanly(st.body):
                rest = list(work)
                if rest and _returns_cleanly(rest):
                    st = ast.If(test=st.test, body=st.body, orelse=rest)
                    res = self._stmt(st, bound)
                    out.extend(res if isinstance(res, list) else [res])
                    return out
                if not rest and top:
                    # ONLY at the function-body level is the implicit
                    # fall-through `return None` — in a nested block the
                    # enclosing scope's code still runs after it
                    st = ast.If(test=st.test, body=st.body,
                                orelse=[ast.Return(
                                    value=ast.Constant(value=None))])
                    res = self._stmt(st, bound)
                    out.extend(res if isinstance(res, list) else [res])
                    return out
            res = self._stmt(st, bound)
            if isinstance(res, tuple) and res and res[0] == "requeue":
                # loop lowering produced fresh statements (flag inits,
                # a transfer-free while, a post-loop return chain) that
                # themselves need conversion against the real tail
                work[:0] = res[1]
                continue
            out.extend(res if isinstance(res, list) else [res])
            bound |= _assigned_names([st])
        return out

    def _stmt(self, st, bound):
        if isinstance(st, ast.If):
            return self._if(st, bound)
        if isinstance(st, ast.While):
            return self._while(st, bound)
        if isinstance(st, ast.For):
            converted = self._for_range(st, bound)
            if converted is not None:
                return converted
        # recurse into other compound statements' blocks
        if isinstance(st, (ast.For, ast.With, ast.Try)):
            for field in ("body", "orelse", "finalbody"):
                blk = getattr(st, field, None)
                if blk:
                    setattr(st, field, self._block(blk, set(bound)))
            if isinstance(st, ast.Try):
                for h in st.handlers:
                    h.body = self._block(h.body, set(bound))
        return st

    def _if(self, node: ast.If, bound):
        node.body = self._block(node.body, set(bound))
        node.orelse = self._block(node.orelse, set(bound))
        if _has_unsupported(node.body) or _has_unsupported(node.orelse):
            # return-style: both branches end in `return <expr>` and are
            # otherwise clean — convert to `return dispatch(...)` (the
            # reference's ReturnTransformer case)
            if node.orelse and _returns_cleanly(node.body) and \
                    _returns_cleanly(node.orelse):
                return self._if_returns(node, bound)
            return node
        wt = _assigned_names(node.body)
        wf = _assigned_names(node.orelse)
        outs = sorted(wt | wf)
        if not outs:
            return node  # side-effect-only branches: nothing to thread
        for o in outs:
            if o not in bound and not (o in wt and o in wf):
                return node  # may be undefined on one path: keep python
        params = [o for o in outs if o in bound]
        i = self.n
        self.n += 1
        tfn = _make_fn(_TRUE.format(n=i), params, node.body, outs)
        ffn = _make_fn(_FALSE.format(n=i), params, node.orelse, outs)
        call = _call_helper(
            _IFELSE,
            [node.test,
             ast.Name(id=tfn.name, ctx=ast.Load()),
             ast.Name(id=ffn.name, ctx=ast.Load())], params)
        self.changed = True
        return [tfn, ffn, _unpack_assign(outs, call)]

    def _if_returns(self, node: ast.If, bound):
        """Both branches return: branch functions keep their Return, the
        If becomes `return __pt_ifelse(test, t, f, (params,))`."""
        wt = _assigned_names(node.body)
        wf = _assigned_names(node.orelse)
        params = sorted((wt | wf) & bound)
        i = self.n
        self.n += 1

        def branch(name, body):
            args = ast.arguments(
                posonlyargs=[], args=[ast.arg(arg=p) for p in params],
                vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
                defaults=[])
            return ast.FunctionDef(name=name, args=args, body=body,
                                   decorator_list=[], returns=None,
                                   type_params=[])

        tfn = branch(_TRUE.format(n=i), node.body)
        ffn = branch(_FALSE.format(n=i), node.orelse)
        call = _call_helper(
            _IFELSE,
            [node.test,
             ast.Name(id=tfn.name, ctx=ast.Load()),
             ast.Name(id=ffn.name, ctx=ast.Load())], params)
        self.changed = True
        return [tfn, ffn, ast.Return(value=call)]

    def _for_range(self, node: ast.For, bound):
        """`for <name> in range(...)` -> __pt_forrange dispatch (the
        reference's loop_transformer for-range case). Returns None to
        keep the original statement."""
        it = node.iter
        if not (isinstance(it, ast.Call)
                and isinstance(it.func, ast.Name)
                and it.func.id == "range" and not it.keywords
                and 1 <= len(it.args) <= 3):
            return None
        if not isinstance(node.target, ast.Name) or node.orelse:
            return None
        scan = _scan_transfers(node.body)
        if (scan.has_break or scan.has_continue or scan.has_return) \
                and not scan.in_guarded:
            lowered = self._for_to_while(node, scan)
            if lowered is not None:
                return lowered
        # eligibility checks on the RAW body — bailing after conversion
        # would hand an already-converted body to the generic recursion
        if _has_unsupported(node.body):
            return None
        carried = sorted(_assigned_names(node.body) -
                         {node.target.id})
        if not carried or any(c not in bound for c in carried):
            # side-effect-only bodies cannot be functionalized (under
            # tracing the body would run once); keep python semantics
            return None
        node.body = self._block(node.body, set(bound))
        a = it.args
        start = a[0] if len(a) > 1 else ast.Constant(value=0)
        stop = a[1] if len(a) > 1 else a[0]
        step = a[2] if len(a) > 2 else ast.Constant(value=1)
        i = self.n
        self.n += 1
        bfn = _make_fn(_WBODY.format(n=i), [node.target.id] + carried,
                       node.body, carried)
        tdefault = (ast.Name(id=node.target.id, ctx=ast.Load())
                    if node.target.id in bound
                    else ast.Constant(value=None))
        call = ast.Call(
            func=ast.Name(id=_FORRANGE, ctx=ast.Load()),
            args=[start, stop, step,
                  ast.Name(id=bfn.name, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Name(id=c, ctx=ast.Load())
                                  for c in carried], ctx=ast.Load()),
                  tdefault],
            keywords=[])
        assign = _unpack_assign([node.target.id] + carried, call)
        self.changed = True
        return [bfn, assign]

    def _for_to_while(self, node: ast.For, scan):
        """`for t in range(...)` whose body has break/continue/return:
        lower to an explicit while (iterator increment FIRST so continue
        cannot skip it), then requeue — the while conversion applies its
        transfer machinery. Deviation from Python worth noting: the
        target is pre-bound to `start` so an empty range leaves it at
        start rather than unbound."""
        a = node.iter.args
        start = a[0] if len(a) > 1 else ast.Constant(value=0)
        stop = a[1] if len(a) > 1 else a[0]
        step = a[2] if len(a) > 2 else ast.Constant(value=1)
        i = self.n
        self.n += 1
        itn, stopn, stepn = (f"__pt_it{i}", f"__pt_stop{i}",
                             f"__pt_step{i}")
        pre = [_assign(itn, start), _assign(stopn, stop),
               _assign(stepn, step),
               _assign(node.target.id, _name(itn))]
        if isinstance(step, ast.Constant) and isinstance(step.value, int) \
                and step.value != 0:
            op = ast.Lt() if step.value > 0 else ast.Gt()
            test = ast.Compare(left=_name(itn), ops=[op],
                               comparators=[_name(stopn)])
        else:
            test = _call(_FORCOND, _name(itn), _name(stopn),
                         _name(stepn))
        body = [_assign(node.target.id, _name(itn)),
                _assign(itn, ast.BinOp(left=_name(itn), op=ast.Add(),
                                       right=_name(stepn)))] + node.body
        w = ast.While(test=test, body=body, orelse=[])
        self.changed = True
        return ("requeue", pre + [w])

    def _while(self, node: ast.While, bound):
        if not node.orelse:
            scan = _scan_transfers(node.body)
            if (scan.has_break or scan.has_continue or scan.has_return) \
                    and not scan.in_guarded:
                res = self._transfers_to_flags(node, bound, scan)
                if res is not None:
                    return res
        node.body = self._block(node.body, set(bound))
        if node.orelse or _has_unsupported(node.body):
            return node
        carried = sorted(_assigned_names(node.body))
        unbound = [c for c in carried if c not in bound]
        if unbound:
            # names (re)created by simple assignments at the top of the
            # body before anything can read them are loop-LOCAL (e.g. an
            # inner loop's counter/flags) — they need no carry and no
            # pre-binding. Only applied where conversion would otherwise
            # bail entirely; under trace a post-loop read of such a name
            # becomes NameError instead of Python's last-value leak.
            prelude = _prelude_writes(node.body)
            if all(c in prelude for c in unbound):
                carried = [c for c in carried if c not in unbound]
        if not carried or any(c not in bound for c in carried):
            return node
        i = self.n
        self.n += 1
        cfn = _make_fn(_WCOND.format(n=i), carried, [], [])
        cfn.body = [ast.Return(value=node.test)]
        bfn = _make_fn(_WBODY.format(n=i), carried, node.body, carried)
        call = _call_helper(
            _WHILE,
            [ast.Name(id=cfn.name, ctx=ast.Load()),
             ast.Name(id=bfn.name, ctx=ast.Load())], carried)
        self.changed = True
        return [cfn, bfn, _unpack_assign(carried, call)]

    def _transfers_to_flags(self, node: ast.While, bound, scan):
        """break/continue/return in a while body -> carried flags + a
        transfer-free while (requeued so the standard conversion and the
        post-loop return chain see the real surrounding block)."""
        i = self.n
        self.n += 1
        brk = f"__pt_brk{i}"  # break and return both stop the loop
        cont = f"__pt_cont{i}" if scan.has_continue else None
        ret = f"__pt_ret{i}" if scan.has_return else None
        retidx = f"__pt_retix{i}" if scan.has_return else None
        rw = _GuardRewriter(brk, cont, ret, retidx)
        try:
            new_body = rw.rewrite(node.body)
        except _CannotGuard:
            return None
        pre = [_assign(brk, ast.Constant(value=False))]
        if cont:
            pre.append(_assign(cont, ast.Constant(value=False)))
        if ret:
            pre.append(_assign(ret, ast.Constant(value=False)))
            pre.append(_assign(retidx, ast.Constant(value=0)))
        body = ([_assign(cont, ast.Constant(value=False))] if cont
                else []) + new_body
        # thunked test: __pt_guardtest short-circuits so the original
        # predicate is never re-evaluated once break/return fired
        thunk = ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                               kwonlyargs=[], kw_defaults=[],
                               kwarg=None, defaults=[]),
            body=node.test)
        test = _call(_GUARDTEST, _name(brk), thunk)
        new_while = ast.While(test=test, body=body, orelse=[])
        post = []
        if ret:
            def chain(k):
                if k == len(rw.sites) - 1:
                    return [ast.Return(value=rw.sites[k])]
                return [ast.If(
                    test=ast.Compare(
                        left=_name(retidx), ops=[ast.Eq()],
                        comparators=[ast.Constant(value=k)]),
                    body=[ast.Return(value=rw.sites[k])],
                    orelse=chain(k + 1))]
            post.append(ast.If(test=_name(ret), body=chain(0),
                               orelse=[]))
        self.changed = True
        return ("requeue", pre + [new_while] + post)


def convert_control_flow(fn):
    """Return fn with tensor-predicated if/while functionalized; fn
    unchanged when nothing applies (or source is unavailable)."""
    if inspect.ismethod(fn):
        conv = convert_control_flow(fn.__func__)
        return conv.__get__(fn.__self__) if conv is not fn.__func__ \
            else fn
    if not inspect.isfunction(fn):
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn
    fndef = tree.body[0]
    if not isinstance(fndef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fndef.decorator_list = []  # do not re-apply @to_static et al.
    conv = _Converter()
    conv.transform_function(fndef)
    if not conv.changed:
        return fn

    freevars = fn.__code__.co_freevars
    module = ast.Module(body=[fndef], type_ignores=[])
    if freevars:
        factory = ast.FunctionDef(
            name="__pt_factory",
            args=ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg=v) for v in freevars], vararg=None,
                kwonlyargs=[], kw_defaults=[], kwarg=None, defaults=[]),
            body=[fndef, ast.Return(value=ast.Name(id=fndef.name,
                                                   ctx=ast.Load()))],
            decorator_list=[], returns=None, type_params=[])
        module = ast.Module(body=[factory], type_ignores=[])
    ast.fix_missing_locations(module)
    try:
        code = compile(module, filename=f"<dy2static {fn.__qualname__}>",
                       mode="exec")
    except (ValueError, SyntaxError):
        return fn
    # exec against the REAL module globals (late-bound names defined or
    # monkeypatched after decoration must stay visible); the two
    # dispatchers use reserved __pt_* names
    ns = fn.__globals__
    for k, v in cfg_helpers.items():
        ns.setdefault(k, v)
    local: dict = {}
    exec(code, ns, local)
    if freevars:
        # share the ORIGINAL closure cells (a later rebind of an
        # enclosing-scope variable must stay visible, exactly as in the
        # unconverted function): rebuild from the inner code object when
        # its freevar ordering matches; otherwise snapshot the cells
        import types
        factory = local["__pt_factory"]
        inner_code = next(
            (c for c in factory.__code__.co_consts
             if isinstance(c, types.CodeType)
             and c.co_name == fndef.name), None)
        if inner_code is not None and \
                inner_code.co_freevars == fn.__code__.co_freevars:
            new_fn = types.FunctionType(inner_code, ns, fn.__name__,
                                        fn.__defaults__, fn.__closure__)
        else:
            try:
                cells = [c.cell_contents
                         for c in (fn.__closure__ or ())]
            except ValueError:
                return fn  # empty cell: keep the python original
            new_fn = factory(*cells)
    else:
        new_fn = local[fndef.name]
    new_fn.__defaults__ = fn.__defaults__
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    new_fn.__qualname__ = fn.__qualname__
    new_fn.__wrapped_original__ = fn
    return new_fn
