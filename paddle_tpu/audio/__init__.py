"""paddle.audio parity: spectral features.

Reference: python/paddle/audio/ (functional/functional.py hz_to_mel /
mel_to_hz / mel_frequencies / fft_frequencies / compute_fbank_matrix /
create_dct / power_to_db; features/layers.py Spectrogram /
MelSpectrogram / LogMelSpectrogram / MFCC). Built over
paddle_tpu.signal.stft — one XLA program per feature pipeline.
"""
from . import functional  # noqa: F401
from . import features  # noqa: F401
from . import backends  # noqa: F401
from . import datasets  # noqa: F401
from .backends import load, save, info  # noqa: F401

__all__ = ["functional", "features", "backends", "datasets", "load",
           "save", "info"]
