"""paddle.vision.models parity (reference: python/paddle/vision/models/)."""
from .lenet import LeNet  # noqa: F401
from .resnet import *  # noqa: F401,F403
from .vgg import *  # noqa: F401,F403
from .mobilenet import *  # noqa: F401,F403
from .mobilenetv3 import *  # noqa: F401,F403
from .alexnet import *  # noqa: F401,F403
from .densenet import *  # noqa: F401,F403
from .squeezenet import *  # noqa: F401,F403
from .shufflenetv2 import *  # noqa: F401,F403
from .googlenet import *  # noqa: F401,F403
from .inceptionv3 import *  # noqa: F401,F403
