"""Worker for distributed.spawn test (must be an importable module for
the multiprocessing spawn context to pickle by reference).

Platform env (CPU forcing) must be injected via spawn(envs=...) — by the
time this function runs, paddle_tpu was already imported to unpickle the
spawn target, and the distributed bootstrap happened at that import.
"""
import os


def worker(out_dir):
    import paddle_tpu.distributed as dist
    env = dist.init_parallel_env()
    import jax
    with open(os.path.join(out_dir, f"rank{env.rank}.txt"), "w") as f:
        f.write(f"{env.rank},{env.world_size},{jax.process_count()},"
                f"{jax.device_count()}")
