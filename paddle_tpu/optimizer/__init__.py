"""paddle.optimizer parity (reference: python/paddle/optimizer/)."""
from .optimizer import (  # noqa: F401
    Optimizer, SGD, Momentum, LarsMomentum)
from .adam import (  # noqa: F401
    Adam, AdamW, Adamax, Adagrad, RMSProp, Adadelta, Lamb, NAdam, RAdam)
from . import lr  # noqa: F401
