"""Decode roofline decomposition: where do the 2.95 ms/step go?

Times isolated compiled pieces of the GPT-124M decode step (bs16,
max_len 640) to attribute per-step time to weight streaming, KV-cache
attention, LM head, and while-loop/carry overhead. Prints a JSON report.

Reference analogue: the reference profiles its fused decoder with
nvprof over fused_multi_transformer_op.cu; here the XLA cost comes
apart the same way.
"""
from __future__ import annotations

import json
import time

import numpy as np


def timeit(fn, *args, reps=10, batches=5, warmup=3):
    """min-of-batches mean: repo convention for tunnel-noise-robust
    timing (see decode_bench.py / op_bench.py)."""
    import jax
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(batches):
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / reps)
    return best


def timeit_varying(fn, make_args, reps=10, batches=5, warmup=3):
    """Per-call distinct args (defeats identical-call caching on the
    tunneled path); args are pre-built outside the timed window."""
    import jax
    arg_sets = [make_args(i) for i in range(batches * reps + warmup)]
    jax.block_until_ready(arg_sets)
    it = iter(arg_sets)
    for _ in range(warmup):
        out = fn(*next(it))
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(batches):
        t0 = time.perf_counter()
        outs = [fn(*next(it)) for _ in range(reps)]
        jax.block_until_ready(outs)
        best = min(best, (time.perf_counter() - t0) / reps)
    return best


def timeit_chained(fn, x, cks, cvs, p, reps=10, batches=5, warmup=3):
    """For donated-cache steps: thread the output caches back in so the
    donated buffers stay alive across reps."""
    import jax
    for _ in range(warmup):
        out, cks, cvs = fn(x, cks, cvs, p)
    jax.block_until_ready((out, cks, cvs))
    best = float("inf")
    for _ in range(batches):
        t0 = time.perf_counter()
        for _ in range(reps):
            out, cks, cvs = fn(x, cks, cvs, p)
        jax.block_until_ready((out, cks, cvs))
        best = min(best, (time.perf_counter() - t0) / reps)
    return best


def main():
    import jax
    import jax.numpy as jnp

    B, LMAX, H, NH, D, NL, V = 16, 640, 768, 12, 64, 12, 50304
    FF = 4 * H
    dt = jnp.bfloat16
    key = jax.random.PRNGKey(0)

    def rnd(*shape):
        nonlocal key
        key, k = jax.random.split(key)
        return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(dt)

    # per-layer weights
    Wqkv = [rnd(H, 3 * H) for _ in range(NL)]
    Wout = [rnd(H, H) for _ in range(NL)]
    W1 = [rnd(H, FF) for _ in range(NL)]
    W2 = [rnd(FF, H) for _ in range(NL)]
    E = rnd(V, H)
    ck = [rnd(B, LMAX, NH, D) for _ in range(NL)]
    cv = [rnd(B, LMAX, NH, D) for _ in range(NL)]
    x0 = rnd(B, 1, H)
    pos = jnp.int32(400)

    def ln(x):
        xf = x.astype(jnp.float32)
        mu = xf.mean(-1, keepdims=True)
        var = ((xf - mu) ** 2).mean(-1, keepdims=True)
        return ((xf - mu) * jax.lax.rsqrt(var + 1e-5)).astype(x.dtype)

    def attend(q, k_buf, v_buf, p):
        # q [B,1,NH,D]; mask over cache axis
        qf = q.transpose(0, 2, 1, 3).astype(jnp.float32)
        kf = k_buf.transpose(0, 2, 3, 1).astype(jnp.float32)
        s = jnp.einsum("bhqd,bhdk->bhqk", qf, kf) / np.sqrt(D)
        j = jnp.arange(LMAX)[None, None, None, :]
        s = jnp.where(j <= p, s, -1e30)
        a = jax.nn.softmax(s, axis=-1)
        vf = v_buf.transpose(0, 2, 1, 3).astype(jnp.float32)
        o = jnp.einsum("bhqk,bhkd->bhqd", a, vf)
        return o.transpose(0, 2, 1, 3).astype(q.dtype)

    def layer_step(x, i, cks, cvs, p, with_attn=True):
        h = ln(x)
        qkv = h.reshape(B, H) @ Wqkv[i]
        q, kn, vn = jnp.split(qkv.reshape(B, 1, NH, 3 * D), 3, axis=-1)
        ckb = jax.lax.dynamic_update_slice(
            cks[i], kn, (0, p.astype(jnp.int32), 0, 0))
        cvb = jax.lax.dynamic_update_slice(
            cvs[i], vn, (0, p.astype(jnp.int32), 0, 0))
        if with_attn:
            o = attend(q, ckb, cvb, p)
        else:
            o = q
        x = x + (o.reshape(B, H) @ Wout[i]).reshape(B, 1, H)
        h = ln(x)
        y = jax.nn.gelu(h.reshape(B, H) @ W1[i], approximate=True)
        x = x + (y @ W2[i]).reshape(B, 1, H)
        return x, ckb, cvb

    def full_step(x, cks, cvs, p):
        ncks, ncvs = [], []
        for i in range(NL):
            x, a, b = layer_step(x, i, cks, cvs, p)
            ncks.append(a)
            ncvs.append(b)
        logits = (ln(x).reshape(B, H) @ E.T).astype(jnp.float32)
        nxt = jnp.argmax(logits, axis=-1)
        return nxt, ncks, ncvs

    def noattn_step(x, cks, cvs, p):
        ncks, ncvs = [], []
        for i in range(NL):
            x, a, b = layer_step(x, i, cks, cvs, p, with_attn=False)
            ncks.append(a)
            ncvs.append(b)
        logits = (ln(x).reshape(B, H) @ E.T).astype(jnp.float32)
        nxt = jnp.argmax(logits, axis=-1)
        return nxt, ncks, ncvs

    def mlp_only(x, step):
        # step varies per call: defeats any identical-call memoization
        # between host and device on the tunneled path
        x = x + step.astype(x.dtype) * 0
        for i in range(NL):
            h = ln(x)
            qkv = h.reshape(B, H) @ Wqkv[i]
            x = x + (qkv[:, :H]).reshape(B, 1, H)
            h = ln(x)
            y = jax.nn.gelu(h.reshape(B, H) @ W1[i], approximate=True)
            x = x + (y @ W2[i]).reshape(B, 1, H)
        return (ln(x).reshape(B, H) @ E.T).astype(jnp.float32)

    def attn_only(cks, cvs, p, step):
        q = (x0 + step.astype(x0.dtype) * 0).reshape(B, 1, NH, D)
        outs = []
        for i in range(NL):
            outs.append(attend(q, cks[i], cvs[i], p))
        return sum(outs)

    import sys
    only = sys.argv[1] if len(sys.argv) > 1 else None
    report = {}

    def note(k, v):
        report[k] = v
        print(f"  {k}: {v}", flush=True)

    if only != "layout":
        # (1) standalone full step, donated caches (true in-place)
        step_d = jax.jit(full_step, donate_argnums=(1, 2))
        t = timeit_chained(step_d, x0, [jnp.copy(a) for a in ck],
                           [jnp.copy(a) for a in cv], pos)
        note("standalone_step_donated_ms", round(t * 1e3, 3))

        # (2) standalone step, no donation (forces full cache copies)
        step_nd = jax.jit(full_step)
        t = timeit(step_nd, x0, list(ck), list(cv), pos)
        note("standalone_step_undonated_ms", round(t * 1e3, 3))

        # (3) weights-only (no attention, no cache read)
        t = timeit_chained(jax.jit(noattn_step, donate_argnums=(1, 2)),
                           x0, [jnp.copy(a) for a in ck],
                           [jnp.copy(a) for a in cv], pos)
        note("step_no_attention_ms", round(t * 1e3, 3))

        # (4) matmuls only (no cache update at all)
        mfn = jax.jit(mlp_only)
        t = timeit_varying(mfn, lambda i: (x0, jnp.float32(i)))
        note("matmuls_only_ms", round(t * 1e3, 3))

        # (5) attention reads only
        afn = jax.jit(attn_only)
        t = timeit_varying(afn, lambda i: (ck, cv, pos, jnp.float32(i)),
                           reps=6, batches=5)
        note("attention_only_ms", round(t * 1e3, 3))

        # (6) loop of 64 steps as one program (the real decode shape)
        def loop64(x, cks, cvs, p):
            cks = list(cks)
            cvs = list(cvs)

            def body(carry, _):
                x, cks, cvs, p = carry
                nxt, cks, cvs = full_step(x, tuple(cks), tuple(cvs), p)
                # feed a token-derived x back in (as real decode does via the
                # embedding) so no layer work is loop-invariant
                x2 = jnp.broadcast_to(
                    ((nxt % 997).astype(jnp.float32) * 1e-3)
                    .astype(x.dtype)[:, None, None], x.shape)
                return (x2, tuple(cks), tuple(cvs), p + 1), nxt

            (x, cks, cvs, p), toks = jax.lax.scan(
                body, (x, tuple(cks), tuple(cvs), p), None, length=64)
            return toks, list(cks), list(cvs)

        t = timeit_chained(jax.jit(loop64, donate_argnums=(1, 2)),
                           x0, [jnp.copy(a) for a in ck],
                           [jnp.copy(a) for a in cv], pos, reps=5)
        note("loop64_per_step_ms", round(t / 64 * 1e3, 3))

        # (7) weights as ARGUMENTS (the generator's shape: state passed to
        # jit, not closed over) — isolates constant-layout specialization
        Wflat = Wqkv + Wout + W1 + W2 + [E]

        def loop64_args(ws, x, cks, cvs, p):
            wqkv, wout, w1, w2 = (ws[:NL], ws[NL:2 * NL], ws[2 * NL:3 * NL],
                                  ws[3 * NL:4 * NL])
            e = ws[-1]

            def layer(x, i, cks, cvs, p):
                h = ln(x)
                qkv = h.reshape(B, H) @ wqkv[i]
                q, kn, vn = jnp.split(qkv.reshape(B, 1, NH, 3 * D), 3,
                                      axis=-1)
                ckb = jax.lax.dynamic_update_slice(
                    cks[i], kn, (0, p.astype(jnp.int32), 0, 0))
                cvb = jax.lax.dynamic_update_slice(
                    cvs[i], vn, (0, p.astype(jnp.int32), 0, 0))
                o = attend(q, ckb, cvb, p)
                x = x + (o.reshape(B, H) @ wout[i]).reshape(B, 1, H)
                h = ln(x)
                y = jax.nn.gelu(h.reshape(B, H) @ w1[i], approximate=True)
                x = x + (y @ w2[i]).reshape(B, 1, H)
                return x, ckb, cvb

            def body(carry, _):
                x, cks, cvs, p = carry
                ncks, ncvs = [], []
                for i in range(NL):
                    x, a_, b_ = layer(x, i, cks, cvs, p)
                    ncks.append(a_)
                    ncvs.append(b_)
                logits = (ln(x).reshape(B, H) @ e.T).astype(jnp.float32)
                nxt = jnp.argmax(logits, axis=-1)
                x2 = jnp.broadcast_to(
                    ((nxt % 997).astype(jnp.float32) * 1e-3)
                    .astype(x.dtype)[:, None, None], x.shape)
                return (x2, tuple(ncks), tuple(ncvs), p + 1), nxt

            (x, cks, cvs, p), toks = jax.lax.scan(
                body, (x, tuple(cks), tuple(cvs), p), None, length=64)
            return toks, list(cks), list(cvs)

        fn7 = jax.jit(loop64_args, donate_argnums=(2, 3))
        cks7 = [jnp.copy(a) for a in ck]
        cvs7 = [jnp.copy(a) for a in cv]
        for _ in range(2):
            toks, cks7, cvs7 = fn7(Wflat, x0, cks7, cvs7, pos)
        jax.block_until_ready((toks, cks7, cvs7))
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            toks, cks7, cvs7 = fn7(Wflat, x0, cks7, cvs7, pos)
            jax.block_until_ready((toks, cks7, cvs7))
            best = min(best, time.perf_counter() - t0)
        note("loop64_weights_as_args_per_step_ms", round(best / 64 * 1e3, 3))

        # (8) logits head alone in the two layouts: [H,V] constant vs
        # [V,H] argument with transpose (the generator's tied embedding)
        h_in = rnd(B, H)

        def head_t(w, h, i):
            return ((h + i.astype(h.dtype) * 0) @ w.T).astype(jnp.float32)

        Evh = rnd(V, H)
        fn8 = jax.jit(head_t)
        t = timeit_varying(fn8, lambda i: (Evh, h_in, jnp.float32(i)))
        note("lm_head_arg_transposed_ms", round(t * 1e3, 3))

        Ehv = rnd(H, V)

        def head_n(w, h, i):
            return ((h + i.astype(h.dtype) * 0) @ w).astype(jnp.float32)

        fn8b = jax.jit(head_n)
        t = timeit_varying(fn8b, lambda i: (Ehv, h_in, jnp.float32(i)))
        note("lm_head_arg_contiguous_ms", round(t * 1e3, 3))

    # (9) cache layout variant: K/V stored [B, H, L, D] (attention
    # contracts over L; no transposed reads) — candidate layout for
    # nlp/generation.py if it beats the [B, L, H, D] baseline
    ck9 = [jnp.transpose(a, (0, 2, 1, 3)) for a in ck]   # [B,H,L,D]
    cv9 = [jnp.transpose(a, (0, 2, 1, 3)) for a in cv]

    def attend_bhld(q, k_buf, v_buf, p):
        # q [B,1,NH,D] -> [B,H,1,D]; cache already [B,H,L,D]
        qf = q.transpose(0, 2, 1, 3).astype(jnp.float32)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf,
                       k_buf.astype(jnp.float32)) / np.sqrt(D)
        j = jnp.arange(LMAX)[None, None, None, :]
        s = jnp.where(j <= p, s, -1e30)
        a = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", a,
                       v_buf.astype(jnp.float32))
        return o.transpose(0, 2, 1, 3).astype(q.dtype)

    def loop64_bhld(x, cks, cvs, p):
        def layer(x, i, cks, cvs, p):
            h = ln(x)
            qkv = h.reshape(B, H) @ Wqkv[i]
            q, kn, vn = jnp.split(qkv.reshape(B, 1, NH, 3 * D), 3,
                                  axis=-1)
            kn = kn.transpose(0, 2, 1, 3)   # [B,H,1,D]
            vn = vn.transpose(0, 2, 1, 3)
            ckb = jax.lax.dynamic_update_slice(
                cks[i], kn.astype(cks[i].dtype),
                (0, 0, p.astype(jnp.int32), 0))
            cvb = jax.lax.dynamic_update_slice(
                cvs[i], vn.astype(cvs[i].dtype),
                (0, 0, p.astype(jnp.int32), 0))
            o = attend_bhld(q, ckb, cvb, p)
            x = x + (o.reshape(B, H) @ Wout[i]).reshape(B, 1, H)
            h = ln(x)
            y = jax.nn.gelu(h.reshape(B, H) @ W1[i], approximate=True)
            x = x + (y @ W2[i]).reshape(B, 1, H)
            return x, ckb, cvb

        def body(carry, _):
            x, cks, cvs, p = carry
            ncks, ncvs = [], []
            for i in range(NL):
                x, a_, b_ = layer(x, i, cks, cvs, p)
                ncks.append(a_)
                ncvs.append(b_)
            logits = (ln(x).reshape(B, H) @ E.T).astype(jnp.float32)
            nxt = jnp.argmax(logits, axis=-1)
            x2 = jnp.broadcast_to(
                ((nxt % 997).astype(jnp.float32) * 1e-3)
                .astype(x.dtype)[:, None, None], x.shape)
            return (x2, tuple(ncks), tuple(ncvs), p + 1), nxt

        (x, cks, cvs, p), toks = jax.lax.scan(
            body, (x, tuple(cks), tuple(cvs), p), None, length=64)
        return toks, list(cks), list(cvs)

    t = timeit_chained(jax.jit(loop64_bhld, donate_argnums=(1, 2)),
                       x0, [jnp.copy(a) for a in ck9],
                       [jnp.copy(a) for a in cv9], pos, reps=5)
    note("loop64_bhld_layout_per_step_ms", round(t / 64 * 1e3, 3))

    # (10) int8 K/V with in-einsum dequant at [B,H,L,D] (does XLA fuse
    # the convert into the attention reads when the layout is direct?)
    ck10 = [jnp.clip(jnp.round(a.astype(jnp.float32) * 64), -127,
                     127).astype(jnp.int8) for a in ck9]
    cv10 = [jnp.clip(jnp.round(a.astype(jnp.float32) * 64), -127,
                     127).astype(jnp.int8) for a in cv9]

    svec_h = (jnp.full((NH,), 1.0 / 64, jnp.float32)
              .reshape(1, NH, 1, 1))   # per-head consts, [1,H,1,1]

    def attend_q8(q, k8, v8, p):
        qf = q.transpose(0, 2, 1, 3).astype(jnp.float32)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf,
                       k8.astype(jnp.float32) * svec_h) / np.sqrt(D)
        j = jnp.arange(LMAX)[None, None, None, :]
        s = jnp.where(j <= p, s, -1e30)
        a = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", a,
                       v8.astype(jnp.float32) * svec_h)
        return o.transpose(0, 2, 1, 3).astype(q.dtype)

    def loop64_q8(x, cks, cvs, p):
        def layer(x, i, cks, cvs, p):
            h = ln(x)
            qkv = h.reshape(B, H) @ Wqkv[i]
            q, kn, vn = jnp.split(qkv.reshape(B, 1, NH, 3 * D), 3,
                                  axis=-1)
            kn8 = jnp.clip(jnp.round(
                kn.transpose(0, 2, 1, 3).astype(jnp.float32) * 64),
                -127, 127).astype(jnp.int8)
            vn8 = jnp.clip(jnp.round(
                vn.transpose(0, 2, 1, 3).astype(jnp.float32) * 64),
                -127, 127).astype(jnp.int8)
            ckb = jax.lax.dynamic_update_slice(
                cks[i], kn8, (0, 0, p.astype(jnp.int32), 0))
            cvb = jax.lax.dynamic_update_slice(
                cvs[i], vn8, (0, 0, p.astype(jnp.int32), 0))
            o = attend_q8(q, ckb, cvb, p)
            x = x + (o.reshape(B, H) @ Wout[i]).reshape(B, 1, H)
            h = ln(x)
            y = jax.nn.gelu(h.reshape(B, H) @ W1[i], approximate=True)
            x = x + (y @ W2[i]).reshape(B, 1, H)
            return x, ckb, cvb

        def body(carry, _):
            x, cks, cvs, p = carry
            ncks, ncvs = [], []
            for i in range(NL):
                x, a_, b_ = layer(x, i, cks, cvs, p)
                ncks.append(a_)
                ncvs.append(b_)
            logits = (ln(x).reshape(B, H) @ E.T).astype(jnp.float32)
            nxt = jnp.argmax(logits, axis=-1)
            x2 = jnp.broadcast_to(
                ((nxt % 997).astype(jnp.float32) * 1e-3)
                .astype(x.dtype)[:, None, None], x.shape)
            return (x2, tuple(ncks), tuple(ncvs), p + 1), nxt

        (x, cks, cvs, p), toks = jax.lax.scan(
            body, (x, tuple(cks), tuple(cvs), p), None, length=64)
        return toks, list(cks), list(cvs)

    t = timeit_chained(jax.jit(loop64_q8, donate_argnums=(1, 2)),
                       x0, ck10, cv10, pos, reps=5)
    note("loop64_kv_int8_bhld_headscale_per_step_ms", round(t / 64 * 1e3, 3))

    # (11) int8 K/V in the ORIGINAL [B,L,H,D] layout with a constant
    # per-head scale vector (the production shape: does the dequant
    # still fuse when the scale is a [H] constant broadcast?)
    svec = jnp.full((NH,), 1.0 / 64, jnp.float32)   # per-head consts
    ck11 = [jnp.clip(jnp.round(a.astype(jnp.float32) * 64), -127,
                     127).astype(jnp.int8) for a in ck]
    cv11 = [jnp.clip(jnp.round(a.astype(jnp.float32) * 64), -127,
                     127).astype(jnp.int8) for a in cv]

    def attend_q8_blhd(q, k8, v8, p):
        kf = (k8.astype(jnp.float32)
              * svec[None, None, :, None]).transpose(0, 2, 3, 1)
        qf = q.transpose(0, 2, 1, 3).astype(jnp.float32)
        s = jnp.einsum("bhqd,bhdk->bhqk", qf, kf) / np.sqrt(D)
        j = jnp.arange(LMAX)[None, None, None, :]
        s = jnp.where(j <= p, s, -1e30)
        a = jax.nn.softmax(s, axis=-1)
        vf = (v8.astype(jnp.float32)
              * svec[None, None, :, None]).transpose(0, 2, 1, 3)
        o = jnp.einsum("bhqk,bhkd->bhqd", a, vf)
        return o.transpose(0, 2, 1, 3).astype(q.dtype)

    def loop64_q8_blhd(x, cks, cvs, p):
        def layer(x, i, cks, cvs, p):
            h = ln(x)
            qkv = h.reshape(B, H) @ Wqkv[i]
            q, kn, vn = jnp.split(qkv.reshape(B, 1, NH, 3 * D), 3,
                                  axis=-1)
            kn8 = jnp.clip(jnp.round(
                kn.astype(jnp.float32)
                / svec[None, None, :, None]), -127,
                127).astype(jnp.int8)
            vn8 = jnp.clip(jnp.round(
                vn.astype(jnp.float32)
                / svec[None, None, :, None]), -127,
                127).astype(jnp.int8)
            ckb = jax.lax.dynamic_update_slice(
                cks[i], kn8, (0, p.astype(jnp.int32), 0, 0))
            cvb = jax.lax.dynamic_update_slice(
                cvs[i], vn8, (0, p.astype(jnp.int32), 0, 0))
            o = attend_q8_blhd(q, ckb, cvb, p)
            x = x + (o.reshape(B, H) @ Wout[i]).reshape(B, 1, H)
            h = ln(x)
            y = jax.nn.gelu(h.reshape(B, H) @ W1[i], approximate=True)
            x = x + (y @ W2[i]).reshape(B, 1, H)
            return x, ckb, cvb

        def body(carry, _):
            x, cks, cvs, p = carry
            ncks, ncvs = [], []
            for i in range(NL):
                x, a_, b_ = layer(x, i, cks, cvs, p)
                ncks.append(a_)
                ncvs.append(b_)
            logits = (ln(x).reshape(B, H) @ E.T).astype(jnp.float32)
            nxt = jnp.argmax(logits, axis=-1)
            x2 = jnp.broadcast_to(
                ((nxt % 997).astype(jnp.float32) * 1e-3)
                .astype(x.dtype)[:, None, None], x.shape)
            return (x2, tuple(ncks), tuple(ncvs), p + 1), nxt

        (x, cks, cvs, p), toks = jax.lax.scan(
            body, (x, tuple(cks), tuple(cvs), p), None, length=64)
        return toks, list(cks), list(cvs)

    t = timeit_chained(jax.jit(loop64_q8_blhd, donate_argnums=(1, 2)),
                       x0, ck11, cv11, pos, reps=5)
    note("loop64_kv_int8_blhd_headscale_per_step_ms",
         round(t / 64 * 1e3, 3))

    # (12) paged decode attention A/B at the same shapes: the gather
    # impl materializes each row's [max_pages * page_size] logical view
    # per layer; the ragged kernel walks the page table and streams
    # only live pages (on CPU this times its pure-JAX reference — run
    # on the chip for the real number)
    from paddle_tpu.ops.pallas.paged_attention import \
        paged_decode_attention
    from paddle_tpu.nlp.generation import _paged_gather_fwd
    PS = 16
    MP = LMAX // PS
    NPAGES = B * MP + 1
    kpool = rnd(NPAGES, PS, NH, D)
    vpool = rnd(NPAGES, PS, NH, D)
    ptab = jnp.asarray(
        np.arange(1, B * MP + 1, dtype=np.int32).reshape(B, MP))
    posv = jnp.full((B,), 400, jnp.int32)
    qrow = rnd(B, 1, NH, D)

    def paged_gather_attend(q, kp_, vp_, pt_, p_):
        kf = _paged_gather_fwd(kp_, pt_)
        vf = _paged_gather_fwd(vp_, pt_)
        qf = q.transpose(0, 2, 1, 3).astype(jnp.float32)
        s = jnp.einsum("bhqd,bkhd->bhqk", qf,
                       kf.astype(jnp.float32)) / np.sqrt(D)
        j = jnp.arange(MP * PS)[None, None, None, :]
        s = jnp.where(j <= p_[:, None, None, None], s, -1e30)
        a = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bhqd", a, vf.astype(jnp.float32))
        return o.transpose(0, 2, 1, 3).astype(q.dtype)

    t = timeit(jax.jit(paged_gather_attend), qrow, kpool, vpool, ptab,
               posv)
    note("paged_attn_gather_ms", round(t * 1e3, 3))
    t = timeit(jax.jit(paged_decode_attention), qrow, kpool, vpool,
               ptab, posv)
    note("paged_attn_kernel_ms", round(t * 1e3, 3))

    # (13) ragged-mix A/B — the unified-step attention shape: half the
    # batch decoding (q_len 1), half mid-prefill (q_len = W), over the
    # same pools. "unified" is ONE ragged invocation; "alternating" is
    # the old two-family shape — the single-token kernel over the
    # decode rows plus one batch-1 chunk attend per prefill row (what
    # an engine step used to dispatch). On CPU this times the pure-JAX
    # references; run on the chip for the kernel's dead-block skipping.
    from paddle_tpu.ops.pallas.paged_attention import \
        ragged_paged_attention
    W = 16
    qlen_mix = np.ones((B,), np.int32)
    qlen_mix[B // 2:] = W
    qlen_mixv = jnp.asarray(qlen_mix)
    qrag = rnd(B, W, NH, D)

    t = timeit(jax.jit(ragged_paged_attention), qrag, kpool, vpool,
               ptab, posv, qlen_mixv)
    note("ragged_mix_unified_ms", round(t * 1e3, 3))

    def alternating(qr, kp_, vp_, pt_, p_):
        # decode family: one single-token kernel call over the
        # decoding half; prefill family: one batch-1 W-wide gathered
        # attend per mid-prefill row (timing shape of the old chunk
        # programs — the window math differs per query but the cost
        # does not)
        outs = [paged_decode_attention(
            qr[:B // 2, :1], kp_, vp_, pt_[:B // 2], p_[:B // 2])]
        for b in range(B // 2, B):
            outs.append(paged_gather_attend(
                qr[b:b + 1], kp_, vp_, pt_[b:b + 1], p_[b:b + 1]))
        return outs

    t = timeit(jax.jit(alternating), qrag, kpool, vpool, ptab, posv)
    note("ragged_mix_alternating_ms", round(t * 1e3, 3))

    # (14) quantized-pool A/B at the same ragged mix: the int8 lane
    # streams HALF the KV bytes per page (codes + rowwise scales vs
    # fp16/32 values) with dequant fused into the softmax loop — on
    # HBM-bound hardware the decode step's dominant stream halves. On
    # CPU this times the pure-JAX q8 reference (gather + dequantize),
    # so treat the CPU delta as op overhead, not the HBM win; run on
    # the chip for the real number.
    from paddle_tpu.ops.pallas.paged_attention import \
        ragged_paged_attention_q8
    from paddle_tpu.nlp.generation import quantize_kv_rowwise
    kcodes, kscales = quantize_kv_rowwise(kpool)
    vcodes, vscales = quantize_kv_rowwise(vpool)
    t = timeit(jax.jit(ragged_paged_attention_q8), qrag, kcodes,
               vcodes, kscales, vscales, ptab, posv, qlen_mixv)
    note("ragged_mix_unified_int8_ms", round(t * 1e3, 3))

    # (15) grouped-vs-flat walk at a HIGH-PREFIX-SHARE decode mix:
    # every row decodes (q_len 1) and ALL rows share their first
    # MP//2 physical pages (one group — the system-prompt shape). The
    # flat walk streams the shared span B times per step, the grouped
    # walk once: on HBM-bound hardware the delta approaches
    # (B-1)/B x shared-fraction of the KV stream. On CPU both time
    # the SAME pure-JAX reference (grouping is an HBM hint, not a
    # math change), so the CPU delta is op overhead; run on the chip
    # for the real number.
    from paddle_tpu.ops.pallas.paged_attention import \
        ragged_paged_attention_grouped
    SHARED = MP // 2
    ptab_sh = np.asarray(ptab).copy()
    ptab_sh[:, :SHARED] = ptab_sh[0, :SHARED]
    ptab_shv = jnp.asarray(ptab_sh)
    qlen_dec = jnp.ones((B,), jnp.int32)
    gid = jnp.zeros((B,), jnp.int32)
    gld = jnp.zeros((B,), jnp.int32)
    gcn = jnp.asarray([SHARED] + [0] * (B - 1), jnp.int32)
    t = timeit(jax.jit(ragged_paged_attention), qrag[:, :1], kpool,
               vpool, ptab_shv, posv, qlen_dec)
    note("shared_prefix_flat_ms", round(t * 1e3, 3))
    t = timeit(jax.jit(ragged_paged_attention_grouped), qrag[:, :1],
               kpool, vpool, ptab_shv, posv, qlen_dec, gid, gld, gcn)
    note("shared_prefix_grouped_ms", round(t * 1e3, 3))

    # roofline bookkeeping
    wbytes = sum(int(np.prod(w.shape)) for w in Wqkv + Wout + W1 + W2) * 2
    ebytes = int(np.prod(E.shape)) * 2
    kvbytes = 2 * NL * B * LMAX * NH * D * 2
    report["weight_bytes_mb"] = round((wbytes + ebytes) / 1e6, 1)
    report["kv_bytes_mb"] = round(kvbytes / 1e6, 1)
    report["hbm_ideal_ms"] = round(
        (wbytes + ebytes + kvbytes) / 819e9 * 1e3, 3)
    print(json.dumps(report, indent=1))


if __name__ == "__main__":
    main()
