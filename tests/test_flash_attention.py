"""Pallas flash-attention kernel parity (interpret mode on CPU; the
same kernels compile under Mosaic on TPU).

Covers VERDICT r2 item 3: additive bias masks, key-padding vector
masks (the BERT path), and in-kernel dropout — forward AND backward —
against a plain-jnp oracle that shares the kernel's position-hash keep
mask (reference semantics: fused_attention_op.cu / fmha_ref.h
softmax-then-dropout)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu  # noqa: F401  (device/x64 init)
import paddle_tpu as paddle
from paddle_tpu.ops.pallas import flash_attention as fa


@pytest.fixture(autouse=True)
def _interpret(monkeypatch):
    monkeypatch.setattr(fa, "_INTERPRET", True)


def _rand(shape, seed):
    return jnp.asarray(np.random.RandomState(seed)
                       .randn(*shape).astype("float32")) * 0.5


def _keep_full(seeds, BH, Lq, Lk, p):
    thresh = fa._drop_thresh(p)
    qpos = jnp.broadcast_to(jnp.arange(Lq, dtype=jnp.int32)[:, None],
                            (Lq, Lk))
    kpos = jnp.broadcast_to(jnp.arange(Lk, dtype=jnp.int32)[None, :],
                            (Lq, Lk))
    return jnp.stack([fa.dropout_keep(seeds[0], seeds[1], bh,
                                      qpos, kpos, thresh)
                      for bh in range(BH)])


def _oracle(q, k, v, bias=None, kvec=None, causal=False, scale=None,
            dropout_p=0.0, seeds=None):
    """[B, L, H, D] oracle sharing the kernel's keep-mask hash."""
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    if scale is None:
        scale = 1.0 / np.sqrt(D)
    logits = jnp.einsum("blhd,bmhd->bhlm", q, k).astype(jnp.float32) \
        * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if kvec is not None:
        logits = logits + kvec.astype(jnp.float32)[:, None, None, :]
    if causal:
        cm = jnp.tril(jnp.ones((Lq, Lk), dtype=bool), Lk - Lq)
        logits = jnp.where(cm, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_p > 0.0:
        keep = _keep_full(seeds, B * H, Lq, Lk, dropout_p) \
            .reshape(B, H, Lq, Lk)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    return jnp.einsum("bhlm,bmhd->blhd", probs.astype(q.dtype), v)


def _check(kern_fn, ref_fn, q, k, v, rtol=2e-3, atol=2e-3):
    out = kern_fn(q, k, v)
    ref = ref_fn(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=rtol, atol=atol)
    w = _rand(out.shape, 99)
    gk = jax.grad(lambda q_, k_, v_: jnp.sum(kern_fn(q_, k_, v_) * w),
                  argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q_, k_, v_: jnp.sum(ref_fn(q_, k_, v_) * w),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b, nm in zip(gk, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=rtol, atol=atol,
                                   err_msg=f"d{nm}")


class TestFlashKernelMasks:
    B, H, L, D = 2, 2, 256, 64

    def _qkv(self, lk=None):
        lk = lk or self.L
        return (_rand((self.B, self.L, self.H, self.D), 0),
                _rand((self.B, lk, self.H, self.D), 1),
                _rand((self.B, lk, self.H, self.D), 2))

    @pytest.mark.parametrize("causal", [False, True])
    def test_plain(self, causal):
        q, k, v = self._qkv()
        _check(lambda q_, k_, v_: fa.flash_attention_blhd(
                   q_, k_, v_, causal=causal),
               lambda q_, k_, v_: _oracle(q_, k_, v_, causal=causal),
               q, k, v)

    @pytest.mark.parametrize("causal", [False, True])
    def test_key_padding_vector(self, causal):
        """The BERT shape: additive [B, Lk] from a [B,1,1,Lk] mask."""
        q, k, v = self._qkv()
        pad = np.zeros((self.B, self.L), "float32")
        pad[0, 200:] = -1e30
        pad[1, 150:] = -1e30
        kvec = jnp.asarray(pad)
        _check(lambda q_, k_, v_: fa.flash_attention_blhd(
                   q_, k_, v_, kvec=kvec, causal=causal),
               lambda q_, k_, v_: _oracle(q_, k_, v_, kvec=kvec,
                                          causal=causal),
               q, k, v)

    @pytest.mark.parametrize("bshape", [(2, 2), (1, 1), (2, 1)])
    def test_full_bias(self, bshape):
        q, k, v = self._qkv()
        bias = _rand((bshape[0], bshape[1], self.L, self.L), 5)
        _check(lambda q_, k_, v_: fa.flash_attention_blhd(
                   q_, k_, v_, bias=bias),
               lambda q_, k_, v_: _oracle(q_, k_, v_, bias=bias),
               q, k, v)

    def test_ragged_length_with_kvec(self):
        q, k, v = self._qkv(lk=200)
        q = q[:, :200]
        pad = np.zeros((self.B, 200), "float32")
        pad[:, 180:] = -1e30
        kvec = jnp.asarray(pad)
        _check(lambda q_, k_, v_: fa.flash_attention_blhd(
                   q_, k_, v_, kvec=kvec),
               lambda q_, k_, v_: _oracle(q_, k_, v_, kvec=kvec),
               q, k, v)

    @pytest.mark.parametrize("causal", [False, True])
    def test_dropout(self, causal):
        """Kernel dropout == oracle with the SAME hash keep-mask, fwd
        and bwd (the position-keyed hash makes the mask reproducible
        across the three kernels)."""
        q, k, v = self._qkv()
        seeds = jnp.asarray([12345, 67890], jnp.int32)
        p = 0.3
        _check(lambda q_, k_, v_: fa.flash_attention_blhd(
                   q_, k_, v_, seeds=seeds, causal=causal, dropout_p=p),
               lambda q_, k_, v_: _oracle(q_, k_, v_, causal=causal,
                                          dropout_p=p, seeds=seeds),
               q, k, v)

    def test_dropout_rate_and_determinism(self):
        keep = _keep_full(jnp.asarray([1, 2], jnp.int32), 4, 256, 256,
                          0.3)
        rate = float(jnp.mean(keep.astype(jnp.float32)))
        assert abs(rate - 0.7) < 0.01
        keep2 = _keep_full(jnp.asarray([1, 2], jnp.int32), 4, 256, 256,
                           0.3)
        assert bool(jnp.all(keep == keep2))
        keep3 = _keep_full(jnp.asarray([3, 2], jnp.int32), 4, 256, 256,
                           0.3)
        assert not bool(jnp.all(keep == keep3))

    def test_dropout_with_kvec_mask(self):
        q, k, v = self._qkv()
        pad = np.zeros((self.B, self.L), "float32")
        pad[:, 220:] = -1e30
        kvec = jnp.asarray(pad)
        seeds = jnp.asarray([7, 11], jnp.int32)
        p = 0.2
        _check(lambda q_, k_, v_: fa.flash_attention_blhd(
                   q_, k_, v_, kvec=kvec, seeds=seeds, dropout_p=p),
               lambda q_, k_, v_: _oracle(q_, k_, v_, kvec=kvec,
                                          dropout_p=p, seeds=seeds),
               q, k, v)


class TestSdpaRouting:
    def test_mask_mapping(self):
        from paddle_tpu.nn.functional.attention import (
            _mask_to_kernel_operands)
        B, H, Lq, Lk = 4, 8, 128, 128
        pad = jnp.ones((B, 1, 1, Lk), bool)
        kind, kv = _mask_to_kernel_operands(pad, B, H, Lq, Lk)
        assert kind == "kvec" and kv.shape == (B, Lk)
        full = jnp.zeros((B, H, Lq, Lk), jnp.float32)
        kind, b = _mask_to_kernel_operands(full, B, H, Lq, Lk)
        assert kind == "bias"
        bcast = jnp.zeros((1, 1, Lq, Lk), jnp.float32)
        kind, b = _mask_to_kernel_operands(bcast, B, H, Lq, Lk)
        assert kind == "bias" and b.shape == (1, 1, Lq, Lk)
        bad = jnp.zeros((B, H, 7, Lk), jnp.float32)
        assert _mask_to_kernel_operands(bad, B, H, Lq, Lk) is None
        # per-head key mask [B, H, 1, Lk]: a singleton Lq would be
        # zero-padded (not broadcast) by the bias streamer -> fallback
        perhead = jnp.zeros((B, H, 1, Lk), jnp.float32)
        assert _mask_to_kernel_operands(perhead, B, H, Lq, Lk) is None

    def test_return_softmax_is_real(self):
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F
        rng = np.random.RandomState(0)
        q = paddle.to_tensor(rng.randn(2, 16, 2, 8).astype("float32"))
        k = paddle.to_tensor(rng.randn(2, 16, 2, 8).astype("float32"))
        v = paddle.to_tensor(rng.randn(2, 16, 2, 8).astype("float32"))
        out, sm = F.flash_attention(q, k, v, causal=True,
                                    return_softmax=True)
        assert sm is not None and sm.shape == [2, 2, 16, 16]
        np.testing.assert_allclose(
            np.asarray(sm.numpy().sum(-1)), 1.0, rtol=1e-5)


class TestSparseAttention:
    """paddle.nn.functional.sparse_attention (reference:
    python/paddle/nn/functional/sparse_attention.py — CSR-pattern
    block-sparse attention, the CUDA 11.3 kernel's API)."""

    def _csr_causal(self, B, H, L):
        """Causal pattern as fixed-width CSR (every (b,h) same nnz)."""
        rows = [i for i in range(L) for _ in range(i + 1)]
        cols = [j for i in range(L) for j in range(i + 1)]
        counts = [i + 1 for i in range(L)]
        offset = np.concatenate([[0], np.cumsum(counts)]).astype("int32")
        off = np.broadcast_to(offset, (B, H, L + 1)).copy()
        col = np.broadcast_to(np.asarray(cols, "int32"),
                              (B, H, len(cols))).copy()
        return off, col, np.asarray(rows), np.asarray(cols)

    def test_matches_dense_causal_softmax(self):
        import paddle_tpu.nn.functional as F
        rs = np.random.RandomState(0)
        B, H, L, D = 2, 3, 6, 8
        q = rs.randn(B, H, L, D).astype("float32")
        k = rs.randn(B, H, L, D).astype("float32")
        v = rs.randn(B, H, L, D).astype("float32")
        off, col, rows, cols = self._csr_causal(B, H, L)
        out = F.sparse_attention(
            paddle.to_tensor(q), paddle.to_tensor(k),
            paddle.to_tensor(v), paddle.to_tensor(off),
            paddle.to_tensor(col)).numpy()
        logits = np.einsum("bhld,bhmd->bhlm", q, k) / np.sqrt(D)
        mask = np.tril(np.ones((L, L), bool))
        logits = np.where(mask, logits, -np.inf)
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        want = np.einsum("bhlm,bhmd->bhld", p, v)
        np.testing.assert_allclose(out, want, rtol=2e-4, atol=2e-5)

    def test_key_padding_mask(self):
        import paddle_tpu.nn.functional as F
        rs = np.random.RandomState(1)
        B, H, L, D = 1, 2, 4, 4
        q = rs.randn(B, H, L, D).astype("float32")
        k = rs.randn(B, H, L, D).astype("float32")
        v = rs.randn(B, H, L, D).astype("float32")
        off, col, _, _ = self._csr_causal(B, H, L)
        kpm = np.zeros((B, L), "float32")
        kpm[:, 3] = -1e30  # key 3 masked out
        out = F.sparse_attention(
            paddle.to_tensor(q), paddle.to_tensor(k),
            paddle.to_tensor(v), paddle.to_tensor(off),
            paddle.to_tensor(col),
            key_padding_mask=paddle.to_tensor(kpm)).numpy()
        logits = np.einsum("bhld,bhmd->bhlm", q, k) / np.sqrt(D)
        mask = np.tril(np.ones((L, L), bool))
        logits = np.where(mask, logits, -np.inf)
        logits[..., 3] = np.where(mask[:, 3], -1e30,
                                  -np.inf)[None, None]
        # row 3's only unmasked key... all keys up to 3 valid except 3
        logits2 = np.einsum("bhld,bhmd->bhlm", q, k) / np.sqrt(D)
        logits2 = np.where(mask, logits2, -np.inf) + kpm[:, None, None, :]
        p = np.exp(logits2 - logits2.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        want = np.einsum("bhlm,bhmd->bhld", p, v)
        np.testing.assert_allclose(out, want, rtol=2e-4, atol=2e-5)

    def test_gradients_flow(self):
        import paddle_tpu.nn.functional as F
        rs = np.random.RandomState(2)
        B, H, L, D = 1, 1, 4, 4
        q = paddle.to_tensor(rs.randn(B, H, L, D).astype("float32"),
                             stop_gradient=False)
        k = paddle.to_tensor(rs.randn(B, H, L, D).astype("float32"),
                             stop_gradient=False)
        v = paddle.to_tensor(rs.randn(B, H, L, D).astype("float32"),
                             stop_gradient=False)
        off, col, _, _ = self._csr_causal(B, H, L)
        out = F.sparse_attention(q, k, v, paddle.to_tensor(off),
                                 paddle.to_tensor(col))
        out.sum().backward()
        for t in (q, k, v):
            assert t.grad is not None
            assert np.isfinite(t.grad.numpy()).all()


class TestKernelAutotune:
    """incubate.autotune.set_config kernel tuning (reference:
    python/paddle/incubate/autotune.py:24 over
    phi/kernels/autotune/switch_autotune.cc) — per-signature
    (block_q, block_k) sweep for the Pallas flash kernel."""

    def test_config_roundtrip_and_cache(self):
        from paddle_tpu.incubate import autotune as at
        at.set_config({"kernel": {"enable": True,
                                  "tuning_range": [1, 2]}})
        cfg = at.get_config()
        assert cfg["kernel"]["enable"] is True
        calls = []

        def measure(bq, bk):
            calls.append((bq, bk))
            return 0.01 if (bq, bk) == (256, 512) else 0.02

        sig = (2, 1024, 1024, 4, 64, "bfloat16", True)
        best = at.kernel_blocks_for(sig, measure)
        assert best == (256, 512)
        n = len(calls)
        # cached: no re-measurement
        assert at.kernel_blocks_for(sig, measure) == (256, 512)
        assert len(calls) == n
        # disabled -> None
        at.set_config({"kernel": {"enable": False}})
        assert at.kernel_blocks_for(sig, measure) is None

    def test_sdpa_path_with_explicit_blocks_matches_default(self):
        """block attrs thread through the sdpa ops without changing
        numerics (CPU falls back to the reference path regardless)."""
        import paddle_tpu.nn.functional as F
        from paddle_tpu.ops._helpers import apply_op, as_tensor
        rs = np.random.RandomState(0)
        q = rs.randn(1, 8, 2, 16).astype("float32")
        want = F.scaled_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(q),
            paddle.to_tensor(q), is_causal=True,
            training=False).numpy()
        got = apply_op("sdpa", as_tensor(paddle.to_tensor(q)),
                       as_tensor(paddle.to_tensor(q)),
                       as_tensor(paddle.to_tensor(q)),
                       attrs=dict(causal=True, scale=0.25,
                                  dropout_p=0.0, block_q=256,
                                  block_k=512)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
