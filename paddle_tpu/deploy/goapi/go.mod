module paddle_tpu/goapi

go 1.19
