"""paddle_tpu.serving.http — streaming HTTP front-end for ServingEngine.

Stdlib-only network surface over the continuous-batching engine:
`EngineDriver` gives each engine replica its own pump thread,
`Router` does least-loaded placement / failover / drain across N
replicas, and `ServingHTTPServer` exposes OpenAI-style
`POST /v1/completions` (JSON + SSE streaming) plus `/healthz`,
`/readyz` and Prometheus `/metrics`:

    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.serving.http import serve

    engines = [ServingEngine(model, num_slots=8, max_len=256)
               for _ in range(2)]
    server = serve(engines, port=8000)       # starts drivers + HTTP
    ...
    server.drain()                           # graceful shutdown

    curl -N localhost:8000/v1/completions -d \
      '{"prompt": [3, 14, 15], "max_tokens": 8, "stream": true}'
"""
from typing import Optional, Sequence

from ..controlplane import (DeadlineInfeasible, FleetController,  # noqa: F401,E501
                            resolve_controlplane)
from ..faults import FaultInjector, InjectedFault, resolve_faults  # noqa: F401,E501
from .driver import EngineDriver, ReplicaDead, ReplicaHung  # noqa: F401
from .protocol import (CompletionRequest, ProtocolError,  # noqa: F401
                       parse_completion_request)
from .ratelimit import RateLimiter, TokenBucket  # noqa: F401
from .router import (CircuitBreaker, ReplicaWatchdog,  # noqa: F401
                     Router, Ticket)
from .server import ServingHTTPServer  # noqa: F401

__all__ = ["EngineDriver", "ReplicaDead", "ReplicaHung", "Router",
           "Ticket", "CircuitBreaker", "ReplicaWatchdog",
           "ServingHTTPServer", "ProtocolError", "CompletionRequest",
           "parse_completion_request", "RateLimiter", "TokenBucket",
           "FaultInjector", "InjectedFault", "resolve_faults",
           "FleetController", "DeadlineInfeasible",
           "resolve_controlplane", "serve"]


def serve(engines: Sequence, host: str = "127.0.0.1", port: int = 0,
          *, model_name: str = "paddle-tpu",
          default_timeout_s: Optional[float] = None,
          max_retries: int = 3,
          max_migrations: int = 8,
          poll_interval_s: float = 0.05,
          rate_limit: Optional[float] = None,
          rate_limit_burst: Optional[float] = None,
          watchdog_timeout_s: Optional[float] = None,
          breaker_failures: int = 3,
          breaker_open_s: float = 1.0,
          faults: Optional[FaultInjector] = None,
          controller=None,
          debug_endpoints=None) -> ServingHTTPServer:
    """One-call assembly: wrap each engine in a driver, front them with
    a router, start the HTTP server on (host, port) — port 0 picks a
    free one (see `server.url`). `rate_limit`/`rate_limit_burst` turn
    on per-client token-bucket limiting (429 + Retry-After per API
    key / remote address). `watchdog_timeout_s` starts the heartbeat
    watchdog (a replica whose pump stalls that long is condemned and
    its streams migrate; size it above the worst-case step time
    including first-use compilation). `faults` injects a deterministic
    fault schedule (serving/faults.py) — when omitted, the
    PADDLE_TPU_FAULTS env spec is parsed (unset = no injection).
    `debug_endpoints=True` (or PADDLE_TPU_DEBUG=on) exposes the
    `/debug/state`, `/debug/requests/<id>` and `/debug/flight`
    introspection routes (serving/obs.py) — off by default, they
    carry prompt metadata. `controller` attaches a fleet control
    plane (serving/controlplane.py: SLO-aware placement,
    deadline-aware admission, burn-rate autoscaling) — pass a
    `FleetController`, True/False, or a spec string; when omitted,
    the PADDLE_TPU_CONTROLPLANE env spec is resolved (unset = off).
    Returns the STARTED server; call `drain()`
    (or `install_signal_handlers()` for SIGTERM) to stop."""
    if faults is None:
        faults = resolve_faults()
    if not isinstance(controller, FleetController):
        cp_cfg = resolve_controlplane(controller)
        controller = (None if cp_cfg is None
                      else FleetController(cp_cfg))
    drivers = [EngineDriver(e, name=f"replica-{i}", faults=faults)
               for i, e in enumerate(engines)]
    router = Router(drivers, max_retries=max_retries,
                    max_migrations=max_migrations,
                    default_timeout_s=default_timeout_s,
                    watchdog_timeout_s=watchdog_timeout_s,
                    breaker_failures=breaker_failures,
                    breaker_open_s=breaker_open_s,
                    controller=controller)
    server = ServingHTTPServer(router, host, port,
                               model_name=model_name,
                               poll_interval_s=poll_interval_s,
                               rate_limit=rate_limit,
                               rate_limit_burst=rate_limit_burst,
                               debug_endpoints=debug_endpoints)
    return server.start()
