"""Host-side page bookkeeping for the paged KV pool.

The device state is a shared per-layer pool [num_pages, page_size, H, D]
plus a per-slot page table [S, max_pages] (see nlp/generation.py's paged
DecodeCache). This module owns the HOST half: which pages are free,
which belong to which request, and how prompts are cut into
power-of-two chunk buckets so the compiled prefill-trace count stays
O(log max_len) instead of one trace per distinct prompt length.

Page 0 is reserved as the TRASH page: it is never handed out, free
slots' page-table rows point every entry at it, and the device scatter
redirects out-of-window writes into it — so membership changes never
reshape or retrace the compiled programs.

Pages are REFERENCE COUNTED so the prefix cache (serving/prefix.py) can
share one physical page between any number of requests plus the radix
tree. Every page is in exactly one of three states:

- FREE      — on the free list, allocatable;
- USED      — refcount >= 1: held by running request(s) and/or
              protected mid-operation (COW source during the copy);
- CACHED    — refcount == 0 but still resident: the page belongs to the
              prefix cache's radix tree and nobody references it right
              now. Cached pages are NOT allocatable; the cache evicts
              (frees) them under page pressure.

Invariants are enforced, not assumed: double free, freeing a page that
is still shared (refcount > 1), retaining a free page, and parking a
referenced page all raise. `assert_quiesced()` is the engine-shutdown
leak check: after drain/abort every page must be FREE or CACHED.
"""
from __future__ import annotations

from typing import Iterable, List, Optional

__all__ = ["PagePool", "TRASH_PAGE", "pages_needed", "chunk_bucket"]

TRASH_PAGE = 0      # reserved: never allocated, absorbs masked writes


class PagePool:
    """Refcounted free-list allocator over page ids 1..num_pages-1
    (0 is trash).

    Allocation is all-or-nothing per request: the scheduler admits a
    request only when its whole page budget is free, so a half-admitted
    request can never wedge the pool. `retain`/`release` move shared
    pages' refcounts for the prefix cache; `park` turns an unreferenced
    page into cache-resident state instead of freeing it.
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is the "
                             "reserved trash page)")
        self.num_pages = int(num_pages)
        # LIFO free list: recently freed pages are reused first, which
        # keeps the hot working set of pages small
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self._free_set = set(self._free)
        self._ref = [0] * self.num_pages
        self._is_cached = [False] * self.num_pages
        self._n_cached = 0

    # -- introspection -----------------------------------------------------
    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def cached_pages(self) -> int:
        """Unreferenced-but-resident pages parked by the prefix cache."""
        return self._n_cached

    @property
    def used_pages(self) -> int:
        """Pages referenced by at least one live request."""
        return (self.num_pages - 1) - len(self._free) - self._n_cached

    def refcount(self, page: int) -> int:
        self._check_range(page)
        return self._ref[page]

    def is_cached(self, page: int) -> bool:
        self._check_range(page)
        return self._is_cached[page]

    def _check_range(self, p: int):
        if not (0 < p < self.num_pages):
            raise ValueError(f"page id {p} out of range")

    # -- allocation --------------------------------------------------------
    def alloc(self, n: int) -> Optional[List[int]]:
        """n pages at refcount 1, or None (without side effects) if not
        enough free."""
        if n < 0:
            raise ValueError("n must be >= 0")
        if n > len(self._free):
            return None
        taken = self._free[-n:] if n else []
        del self._free[len(self._free) - n:]
        for p in taken:
            self._free_set.discard(p)
            self._ref[p] = 1
        return taken

    # -- sharing (prefix cache) --------------------------------------------
    def retain(self, pages: Iterable[int]):
        """refcount++ on resident pages. A CACHED page leaves the
        cache-resident state (it is referenced again); a FREE page
        cannot be retained — that is a use-after-free."""
        pages = list(pages)
        for p in pages:
            self._check_range(p)
            if p in self._free_set:
                raise ValueError(f"retain of free page {p} "
                                 "(use-after-free)")
        for p in pages:
            if self._is_cached[p]:
                self._is_cached[p] = False
                self._n_cached -= 1
            self._ref[p] += 1

    def release(self, pages: Iterable[int]) -> List[int]:
        """refcount-- on each page; returns the pages that dropped to
        zero. The caller (the prefix cache) decides their fate: `park`
        the tree-resident ones, `free` the rest."""
        pages = list(pages)
        for p in pages:
            self._check_range(p)
            if p in self._free_set or self._ref[p] < 1:
                raise ValueError(f"release of unreferenced page {p}")
        zeroed = []
        for p in pages:
            self._ref[p] -= 1
            if self._ref[p] == 0:
                zeroed.append(p)
        return zeroed

    def park(self, pages: Iterable[int]):
        """Mark unreferenced pages cache-resident (the prefix cache's
        LRU pool) instead of freeing them."""
        pages = list(pages)
        for p in pages:
            self._check_range(p)
            if p in self._free_set:
                raise ValueError(f"park of free page {p}")
            if self._ref[p] != 0:
                raise ValueError(f"park of referenced page {p} "
                                 f"(refcount {self._ref[p]})")
            if self._is_cached[p]:
                raise ValueError(f"page {p} already cache-resident")
        for p in pages:
            self._is_cached[p] = True
            self._n_cached += 1

    # -- freeing -----------------------------------------------------------
    def free(self, pages: Iterable[int]):
        """Return pages to the free list. Raises on double free and on
        freeing a page some OTHER holder still references (refcount
        > 1): a shared page must be `release`d, never freed through."""
        pages = list(pages)
        for p in pages:
            self._check_range(p)
            if p in self._free_set:
                raise ValueError(f"double free of page {p}")
            if self._ref[p] > 1:
                raise ValueError(
                    f"free of page {p} still referenced "
                    f"(refcount {self._ref[p]}); release shared pages "
                    "instead of freeing through them")
        for p in pages:
            if self._is_cached[p]:
                self._is_cached[p] = False
                self._n_cached -= 1
            self._ref[p] = 0
            self._free.append(p)
            self._free_set.add(p)

    # -- invariants --------------------------------------------------------
    def assert_quiesced(self):
        """Engine-shutdown leak check: every page FREE or CACHED (no
        request reference survived retirement), and the accounting
        closes: free + cached == allocatable pool size."""
        leaked = [p for p in range(1, self.num_pages) if self._ref[p] > 0]
        if leaked:
            raise RuntimeError(
                f"page leak: pages {leaked} still referenced after "
                "shutdown (refcounts "
                f"{[self._ref[p] for p in leaked]})")
        if len(self._free) + self._n_cached != self.num_pages - 1:
            raise RuntimeError(
                f"page accounting broken: free {len(self._free)} + "
                f"cached {self._n_cached} != pool size "
                f"{self.num_pages - 1}")


def pages_needed(prompt_len: int, max_new_tokens: int,
                 page_size: int) -> int:
    """Admission budget: pages covering every position the request can
    legitimately occupy (prompt + full output allowance)."""
    return -(-(int(prompt_len) + int(max_new_tokens)) // int(page_size))


def chunk_bucket(remaining: int, chunk_len: int, min_chunk: int = 8
                 ) -> int:
    """Length of the next prefill chunk: full `chunk_len` chunks while
    the remainder is large, then ONE power-of-two bucket >= the tail
    (clamped to [min_chunk, chunk_len]). Distinct bucket values over
    all prompts are {chunk_len} ∪ {min_chunk * 2**i <= chunk_len}, so
    the engine compiles O(log chunk_len) prefill programs total."""
    if remaining <= 0:
        raise ValueError("remaining must be > 0")
    if remaining >= chunk_len:
        return chunk_len
    b = min_chunk
    while b < remaining:
        b *= 2
    return min(b, chunk_len)
