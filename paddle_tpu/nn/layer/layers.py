"""Layer: the module system.

TPU-native replacement for Paddle's dygraph Layer (reference:
python/paddle/fluid/dygraph/layers.py:108 class Layer). Semantics match:
parameter/buffer/sublayer registries via __setattr__, forward pre/post
hooks, train/eval propagation, state_dict with structured names. The TPU
difference is invisible here — parameters wrap immutable jax.Arrays and
optimizers rebind them — so this file is almost pure API parity.
"""
from __future__ import annotations

import collections
import copy as copy_mod
from typing import Callable, Iterator, Optional

import numpy as np
import jax.numpy as jnp

from ...core import dtype as dtypes
from ...core.tensor import Tensor, Parameter
from ..initializer import Initializer, Constant, XavierUniform, Uniform

__all__ = ["Layer", "ParamAttr"]


class ParamAttr:
    """Parameter attribute bundle (reference: python/paddle/fluid/param_attr.py)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, Initializer):
            return ParamAttr(initializer=attr)
        if attr is False:
            return False
        raise TypeError(f"Bad ParamAttr spec: {attr!r}")


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


_layer_counts: dict = collections.defaultdict(int)


def _unique_name(prefix):
    n = _layer_counts[prefix]
    _layer_counts[prefix] += 1
    return f"{prefix}_{n}"


class Layer:
    """Base class for all network layers (paddle.nn.Layer parity)."""

    def __init__(self, name_scope=None, dtype="float32"):
        if name_scope is None:
            name_scope = _unique_name(self.__class__.__name__.lower())
        self._full_name = name_scope
        self._dtype = dtypes.convert_dtype(dtype) if dtype is not None else None
        self.training = True
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names_set = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = [0]
        self._casted_by_pure_fp16 = False

    # -- identity ----------------------------------------------------------
    def full_name(self):
        return self._full_name

    # -- mode --------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # -- parameter creation ------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """reference: fluid/dygraph/layers.py create_parameter + LayerHelper."""
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtypes.convert_dtype(dtype) if dtype is not None else \
            (self._dtype or dtypes.get_default_dtype())
        init = attr.initializer or default_initializer
        if init is None:
            init = Constant(0.0) if is_bias else XavierUniform()
        value = init.init_array(shape, dtype)
        p = Parameter(value, name=attr.name, trainable=attr.trainable)
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def create_variable(self, name=None, persistable=None, dtype=None):
        dtype = dtypes.convert_dtype(dtype) if dtype is not None else \
            (self._dtype or dtypes.get_default_dtype())
        t = Tensor(jnp.zeros((), dtype=dtype.np_dtype), name=name)
        t.persistable = bool(persistable)
        return t

    def create_tensor(self, name=None, persistable=None, dtype=None):
        return self.create_variable(name, persistable, dtype)

    # -- registration ------------------------------------------------------
    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter or None")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        if sublayer is not None and not isinstance(sublayer, Layer):
            raise TypeError("add_sublayer expects a Layer or None")
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            raise TypeError("register_buffer expects a Tensor or None")
        self._buffers[name] = tensor
        if tensor is not None:
            tensor.persistable = persistable
        if not persistable:
            self._non_persistable_buffer_names_set.add(name)
        else:
            self._non_persistable_buffer_names_set.discard(name)
        return tensor

    # -- attribute magic ---------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning params")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            self.__dict__.pop(name, None)   # plain attr must not shadow
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning layers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            self.__dict__.pop(name, None)   # plain attr must not shadow
            layers[name] = value
        elif params is not None and name in params:
            if value is None:
                params[name] = None
            elif isinstance(value, Tensor):
                params[name].set_value(value)
            else:
                raise TypeError(f"cannot assign {type(value)} to parameter "
                                f"{name}")
        elif buffers is not None and name in buffers:
            if value is None or isinstance(value, Tensor):
                buffers[name] = value
            else:
                object.__setattr__(self, name, value)
        elif layers is not None and name in layers and value is None:
            layers[name] = None
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for registry in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(registry)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for registry in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(registry)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        extra = []
        for registry in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(registry)
            if d:
                extra += list(d.keys())
        return list(super().__dir__()) + extra

    # -- traversal ---------------------------------------------------------
    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self):
        seen = set()
        for name, l in self._sub_layers.items():
            if l is not None and id(l) not in seen:
                seen.add(id(l))
                yield name, l

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, l in self.named_children():
            if l is None or id(l) in layers_set:
                continue
            sub_prefix = prefix + ("." if prefix else "") + name
            yield from l.named_sublayers(prefix=sub_prefix, include_self=True,
                                         layers_set=layers_set)

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        if include_sublayers:
            gen = self.named_sublayers(prefix=prefix, include_self=True)
        else:
            gen = [(prefix, self)]
        for layer_prefix, layer in gen:
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (layer_prefix + ("." if layer_prefix else "") + name, p)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        if include_sublayers:
            gen = self.named_sublayers(prefix=prefix, include_self=True)
        else:
            gen = [(prefix, self)]
        for layer_prefix, layer in gen:
            for name, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (layer_prefix + ("." if layer_prefix else "") + name, b)

    def apply(self, fn):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # -- hooks -------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id[0] += 1
        hid = self._hook_id[0]
        self._forward_pre_hooks[hid] = hook
        return HookRemoveHelper(self._forward_pre_hooks, hid)

    def register_forward_post_hook(self, hook):
        self._hook_id[0] += 1
        hid = self._hook_id[0]
        self._forward_post_hooks[hid] = hook
        return HookRemoveHelper(self._forward_post_hooks, hid)

    # -- call --------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            o = hook(self, inputs, outputs)
            if o is not None:
                outputs = o
        return outputs

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = collections.OrderedDict() if destination is None else destination
        prefix = structured_name_prefix.rstrip(".")
        for name, p in self.named_parameters(
                prefix=prefix, include_sublayers=include_sublayers):
            dest[name] = p
        gen = (self.named_sublayers(prefix=prefix, include_self=True)
               if include_sublayers else [(prefix, self)])
        seen = set()
        for layer_prefix, layer in gen:
            for name, b in layer._buffers.items():
                if (b is None or id(b) in seen
                        or name in layer._non_persistable_buffer_names_set):
                    continue
                seen.add(id(b))
                dest[layer_prefix + ("." if layer_prefix else "") + name] = b
        return dest

    to_static_state_dict = state_dict

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Returns (missing_keys, unexpected_keys) like paddle."""
        own = self.state_dict()
        missing, unexpected = [], []
        matched = {}
        for k, v in state_dict.items():
            if k in own:
                matched[k] = v
            else:
                unexpected.append(k)
        for k in own:
            if k not in matched:
                missing.append(k)
        for k, v in matched.items():
            target = own[k]
            arr = v._value if isinstance(v, Tensor) else np.asarray(v)
            arr = jnp.asarray(arr, dtype=target._value.dtype)
            if tuple(arr.shape) != tuple(target.shape):
                raise ValueError(
                    f"shape mismatch for {k}: loaded {tuple(arr.shape)} vs "
                    f"param {tuple(target.shape)}")
            target._rebind(arr)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # -- dtype / device movement -------------------------------------------
    def _transform(self, fn):
        for _, p in self.named_parameters():
            p._rebind(fn(p._value))
        for _, b in self.named_buffers():
            b._rebind(fn(b._value))
        return self

    def to(self, device=None, dtype=None, blocking=None):
        import jax
        from ...core import device as devices
        if dtype is not None:
            np_dt = dtypes.to_np_dtype(dtype)
            self._transform(lambda v: v.astype(np_dt)
                            if np.dtype(v.dtype).kind in "fc" else v)
            for l in self.sublayers(include_self=True):
                l._dtype = dtypes.convert_dtype(dtype)
        if device is not None:
            dev = devices.jax_device(device)
            self._transform(lambda v: jax.device_put(v, dev))
        return self

    def astype(self, dtype=None):
        return self.to(dtype=dtype)

    def float(self, excluded_layers=None):
        return self.to(dtype="float32")

    def float16(self, excluded_layers=None):
        return self.to(dtype="float16")

    def bfloat16(self, excluded_layers=None):
        return self.to(dtype="bfloat16")

    # -- misc --------------------------------------------------------------
    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, l in self.named_children():
            mod_str = repr(l)
            mod_str = "\n  ".join(mod_str.split("\n"))
            lines.append(f"({name}): {mod_str}")
        main = self.__class__.__name__ + "("
        if extra:
            main += extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"
