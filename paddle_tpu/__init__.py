"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's
capability set, built on JAX/XLA/Pallas rather than ported from CUDA.

Public surface mirrors `paddle.*` (reference: python/paddle/__init__.py)
so reference users can switch by changing the import.
"""
from __future__ import annotations

import os as _os

# Make multi-device CPU testing work out of the box when no accelerator is
# configured and the user asked for a virtual mesh.
if _os.environ.get("PADDLE_TPU_FORCE_CPU_DEVICES"):
    _n = _os.environ["PADDLE_TPU_FORCE_CPU_DEVICES"]
    flags = _os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        _os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={_n}").strip()
    import jax as _jx
    _jx.config.update("jax_platforms", "cpu")

# Multi-process rendezvous must happen BEFORE any jax backend query (the
# first device touch freezes the process-local backend). The launcher
# (paddle_tpu.distributed.launch) sets this env; matching the reference's
# import-time PADDLE_TRAINER_ID pickup in python/paddle/distributed/
# parallel.py.
if (_os.environ.get("PADDLE_MASTER")
        and int(_os.environ.get("PADDLE_TRAINERS_NUM", "1")) > 1):
    import jax as _jx2
    # guard precisely against double-init; a rendezvous FAILURE must
    # propagate (silently continuing single-host would train each rank
    # independently with no gradient sync)
    if not _jx2.distributed.is_initialized():
        _jx2.distributed.initialize(
            coordinator_address=_os.environ["PADDLE_MASTER"],
            num_processes=int(_os.environ["PADDLE_TRAINERS_NUM"]),
            process_id=int(_os.environ.get("PADDLE_TRAINER_ID", "0")))

import jax as _jax  # noqa: E402

# Paddle defaults integer tensors to int64 and supports float64; enable
# x64 so those dtypes are real. Default float stays float32 (weak-typed
# python scalars do not promote f32 arrays), and the TPU hot path is
# explicitly bf16/f32 throughout.
_jax.config.update("jax_enable_x64", True)

# Paddle's float32 matmul is true float32; this XLA build defaults f32 dots
# to reduced (bf16-pass) precision. Default to full precision — bf16/fp16
# compute (the TPU fast path) is unaffected by this setting. Opt back into
# fast f32 via set_matmul_precision("default") (e.g. benchmarks).
_jax.config.update("jax_default_matmul_precision", "highest")


def set_matmul_precision(level: str):
    """'highest' (true f32), 'high' (bf16x3), or 'default' (fastest)."""
    _jax.config.update("jax_default_matmul_precision", level)
    from .core.dispatch import clear_caches as _cc
    _cc()


from .version import __version__  # noqa: E402
# seed FLAGS_* from the environment at import (and wire env-activated
# debug hooks like FLAGS_check_nan_inf)
from .utils import flags as _flags_boot  # noqa: E402

from .core.dtype import (  # noqa: E402,F401
    dtype, float16, bfloat16, float32, float64, int8, int16, int32, int64,
    uint8, uint16, uint32, uint64, bool_, complex64, complex128,
    float8_e4m3fn, float8_e5m2, set_default_dtype, get_default_dtype)
from .core.device import (  # noqa: E402,F401
    CPUPlace, TPUPlace, XLAPlace, CUDAPlace, CUDAPinnedPlace, set_device,
    get_device, device_count, is_compiled_with_cuda, is_compiled_with_rocm,
    is_compiled_with_xpu, is_compiled_with_npu, is_compiled_with_mlu,
    is_compiled_with_ipu, is_compiled_with_cinn, is_compiled_with_distribute)
from .core.tensor import (  # noqa: E402,F401
    Tensor, to_tensor, no_grad, enable_grad, is_grad_enabled,
    set_grad_enabled, grad)
from .core.random import seed, get_rng_state, set_rng_state  # noqa: E402,F401
from .core import random as _random_mod  # noqa: E402

from .ops import *  # noqa: E402,F401,F403
from .ops import creation as _creation  # noqa: E402

# modules (populated progressively)
from . import ops  # noqa: E402,F401
from .ops import linalg  # noqa: E402,F401
from . import nn  # noqa: E402,F401
from . import optimizer  # noqa: E402,F401
from . import regularizer  # noqa: E402,F401
from . import amp  # noqa: E402,F401
from . import autograd  # noqa: E402,F401
from . import jit  # noqa: E402,F401
from . import io  # noqa: E402,F401
from . import vision  # noqa: E402,F401
from . import framework  # noqa: E402,F401
from . import distributed  # noqa: E402,F401
from .distributed.parallel import DataParallel  # noqa: E402,F401
from .regularizer import L1Decay, L2Decay  # noqa: E402,F401
from .nn.layer.layers import ParamAttr  # noqa: E402,F401
from . import metric  # noqa: E402,F401
from . import profiler  # noqa: E402,F401
from . import distribution  # noqa: E402,F401
from . import fft  # noqa: E402,F401
from . import signal  # noqa: E402,F401
from . import device  # noqa: E402,F401
from . import sparse  # noqa: E402,F401
from . import incubate  # noqa: E402,F401
from . import text  # noqa: E402,F401
from . import audio  # noqa: E402,F401
from . import inference  # noqa: E402,F401
from . import serving  # noqa: E402,F401
from . import quantization  # noqa: E402,F401
from . import geometric  # noqa: E402,F401
from . import static  # noqa: E402,F401
from .static import enable_static, disable_static  # noqa: E402,F401
from . import hapi  # noqa: E402,F401
from .hapi import Model  # noqa: E402,F401
from .hapi import callbacks  # noqa: E402,F401
from .hapi.summary import summary, flops  # noqa: E402,F401
from . import hub  # noqa: E402,F401
from . import onnx  # noqa: E402,F401


def iinfo(dtype):
    import numpy as _np
    from .core import dtype as _dt
    return _np.iinfo(_dt.to_np_dtype(dtype))


def finfo(dtype):
    import numpy as _np
    from .core import dtype as _dt
    return _np.finfo(_dt.to_np_dtype(dtype))


def batch(reader, batch_size, drop_last=False):
    """Legacy reader combinator (reference: python/paddle/reader)."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


class LazyGuard:
    """reference: paddle.LazyGuard defers parameter initialization to
    first use; here parameters are jax arrays whose real allocation is
    already lazy under PJRT, so the guard is scope-only."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

bool = bool_  # paddle.bool


def save(obj, path, protocol=4, **configs):
    from .framework.io import save as _save
    return _save(obj, path, protocol=protocol, **configs)


def load(path, **configs):
    from .framework.io import load as _load
    return _load(path, **configs)


def is_grad_enabled_():
    return is_grad_enabled()


def in_dynamic_mode():
    from .jit.api import in_to_static_trace
    return not (static.in_static_mode() or in_to_static_trace())


def in_dygraph_mode():
    return in_dynamic_mode()


def get_flags(flags):
    from .utils import flags as _flags
    return _flags.get_flags(flags)


def set_flags(flags):
    from .utils import flags as _flags
    return _flags.set_flags(flags)
