"""vision.ops detection operators + long-tail tensor/functional ops.

Reference test model: unittests/test_nms_op.py, test_roi_align_op.py,
test_box_coder_op.py, test_yolo_box_op.py (numpy-reference checks) and
the per-API tensor op tests.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.vision import ops as vops


class TestNMS:
    def test_greedy_suppression(self):
        boxes = np.array([[0, 0, 10, 10],
                          [1, 1, 11, 11],     # overlaps box 0
                          [20, 20, 30, 30],
                          [21, 21, 31, 31]],  # overlaps box 2
                         "float32")
        scores = np.array([0.9, 0.8, 0.7, 0.95], "float32")
        keep = vops.nms(paddle.to_tensor(boxes), 0.5,
                        paddle.to_tensor(scores))
        # box 3 beats box 2; box 0 beats box 1
        assert set(keep.numpy().tolist()) == {0, 3}
        # sorted by descending score
        assert keep.numpy().tolist() == [3, 0]

    def test_nms_categories(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11]], "float32")
        scores = np.array([0.9, 0.8], "float32")
        cats = np.array([0, 1], "int64")
        keep = vops.nms(paddle.to_tensor(boxes), 0.5,
                        paddle.to_tensor(scores),
                        category_idxs=paddle.to_tensor(cats),
                        categories=[0, 1])
        assert len(keep.numpy()) == 2  # different categories both kept

    def test_top_k(self):
        boxes = np.array([[0, 0, 1, 1], [5, 5, 6, 6], [10, 10, 11, 11]],
                         "float32")
        keep = vops.nms(paddle.to_tensor(boxes), 0.5,
                        paddle.to_tensor(
                            np.array([0.3, 0.9, 0.5], "float32")),
                        top_k=2)
        assert keep.numpy().tolist() == [1, 2]


class TestRoI:
    def test_roi_align_uniform_feature(self):
        # constant feature map: every roi bin must read that constant
        x = np.full((1, 3, 16, 16), 5.0, "float32")
        boxes = np.array([[2.0, 2.0, 10.0, 10.0]], "float32")
        out = vops.roi_align(paddle.to_tensor(x),
                             paddle.to_tensor(boxes),
                             paddle.to_tensor(np.array([1], "int32")),
                             output_size=4)
        assert out.shape == [1, 3, 4, 4]
        np.testing.assert_allclose(out.numpy(), 5.0, rtol=1e-5)

    def test_roi_align_gradient(self):
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(1, 2, 8, 8).astype("float32"),
            stop_gradient=False)
        boxes = paddle.to_tensor(np.array([[1.0, 1.0, 6.0, 6.0]],
                                          "float32"))
        out = vops.roi_align(x, boxes,
                             paddle.to_tensor(np.array([1], "int32")),
                             output_size=2)
        out.sum().backward()
        assert x.grad is not None
        assert float(np.abs(x.grad.numpy()).sum()) > 0

    def test_roi_pool_max(self):
        x = np.zeros((1, 1, 8, 8), "float32")
        x[0, 0, 3, 3] = 9.0
        out = vops.roi_pool(paddle.to_tensor(x),
                            paddle.to_tensor(
                                np.array([[0.0, 0.0, 7.0, 7.0]],
                                         "float32")),
                            paddle.to_tensor(np.array([1], "int32")),
                            output_size=1)
        assert abs(float(out.numpy()[0, 0, 0, 0]) - 9.0) < 1e-5


class TestBoxCoderYolo:
    def test_box_coder_roundtrip(self):
        rs = np.random.RandomState(0)
        prior = np.abs(rs.randn(5, 4)).astype("float32")
        prior[:, 2:] = prior[:, :2] + np.abs(rs.randn(5, 2)) + 1.0
        target = np.abs(rs.randn(3, 4)).astype("float32")
        target[:, 2:] = target[:, :2] + np.abs(rs.randn(3, 2)) + 1.0
        var = np.ones((5, 4), "float32")
        enc = vops.box_coder(paddle.to_tensor(prior),
                             paddle.to_tensor(var),
                             paddle.to_tensor(target),
                             code_type="encode_center_size")
        assert enc.shape == [3, 5, 4]
        dec = vops.box_coder(paddle.to_tensor(prior),
                             paddle.to_tensor(var), enc,
                             code_type="decode_center_size", axis=0)
        # decoding its own encoding recovers each target against every
        # prior; check prior-0 column
        np.testing.assert_allclose(dec.numpy()[:, 0], target, rtol=1e-4,
                                   atol=1e-4)

    def test_yolo_box_shapes(self):
        n, na, c, h, w = 2, 3, 4, 5, 5
        x = np.random.RandomState(0).randn(
            n, na * (5 + c), h, w).astype("float32")
        img = np.full((n, 2), 320, "int32")
        boxes, scores = vops.yolo_box(
            paddle.to_tensor(x), paddle.to_tensor(img),
            anchors=[10, 13, 16, 30, 33, 23], class_num=c)
        assert boxes.shape == [n, na * h * w, 4]
        assert scores.shape == [n, na * h * w, c]
        b = boxes.numpy()
        assert (b >= 0).all() and (b <= 319).all()  # clipped


class TestFunctionalLongTail:
    def test_affine_grid_identity(self):
        theta = np.zeros((1, 2, 3), "float32")
        theta[0, 0, 0] = 1.0
        theta[0, 1, 1] = 1.0
        grid = F.affine_grid(paddle.to_tensor(theta), [1, 1, 4, 4])
        assert grid.shape == [1, 4, 4, 2]
        g = grid.numpy()
        np.testing.assert_allclose(g[0, 0, 0], [-1, -1], atol=1e-6)
        np.testing.assert_allclose(g[0, -1, -1], [1, 1], atol=1e-6)

    def test_grid_sample_identity(self):
        x = np.random.RandomState(0).randn(1, 2, 6, 6).astype("float32")
        theta = np.zeros((1, 2, 3), "float32")
        theta[0, 0, 0] = 1.0
        theta[0, 1, 1] = 1.0
        grid = F.affine_grid(paddle.to_tensor(theta), [1, 2, 6, 6])
        out = F.grid_sample(paddle.to_tensor(x), grid)
        np.testing.assert_allclose(out.numpy(), x, rtol=1e-4, atol=1e-5)

    def test_sequence_mask(self):
        m = F.sequence_mask(paddle.to_tensor(np.array([1, 3], "int64")),
                            maxlen=4)
        np.testing.assert_array_equal(
            m.numpy(), [[1, 0, 0, 0], [1, 1, 1, 0]])

    def test_temporal_shift(self):
        x = np.arange(2 * 4 * 2 * 2, dtype="float32").reshape(2, 4, 2, 2)
        out = F.temporal_shift(paddle.to_tensor(x), seg_num=2,
                               shift_ratio=0.25)
        assert out.shape == [2, 4, 2, 2]
        o = out.numpy()
        # first fold channel shifts left: frame0 gets frame1's channel 0
        np.testing.assert_allclose(o[0, 0], x[1, 0])
        np.testing.assert_allclose(o[1, 0], 0.0)  # pad

    def test_max_unpool2d(self):
        x = np.array([[[[5.0]]]], "float32")
        idx = np.array([[[[3]]]], "int64")  # position 3 of 2x2
        out = F.max_unpool2d(paddle.to_tensor(x), paddle.to_tensor(idx),
                             kernel_size=2)
        np.testing.assert_allclose(
            out.numpy(), [[[[0, 0], [0, 5.0]]]])


class TestTensorLongTail:
    def test_cdist(self):
        a = np.random.RandomState(0).randn(3, 4).astype("float32")
        b = np.random.RandomState(1).randn(5, 4).astype("float32")
        d = paddle.cdist(paddle.to_tensor(a), paddle.to_tensor(b))
        want = np.linalg.norm(a[:, None] - b[None, :], axis=-1)
        np.testing.assert_allclose(d.numpy(), want, rtol=1e-5)

    def test_trapezoid_vander_renorm(self):
        y = np.array([1.0, 2.0, 3.0], "float32")
        assert abs(float(paddle.trapezoid(paddle.to_tensor(y))) - 4.0) \
            < 1e-6
        v = paddle.vander(paddle.to_tensor(y), n=3)
        np.testing.assert_allclose(v.numpy(), np.vander(y, 3), rtol=1e-5)
        x = np.ones((2, 3), "float32") * 3.0
        r = paddle.renorm(paddle.to_tensor(x), p=2.0, axis=0,
                          max_norm=1.0)
        np.testing.assert_allclose(
            np.linalg.norm(r.numpy(), axis=1), 1.0, rtol=1e-4)

    def test_index_fill_diagonal_scatter_unflatten(self):
        x = paddle.to_tensor(np.zeros((3, 4), "float32"))
        out = paddle.index_fill(x, paddle.to_tensor(
            np.array([0, 2], "int64")), 0, 7.0)
        assert (out.numpy()[[0, 2]] == 7.0).all()
        assert (out.numpy()[1] == 0.0).all()

        m = paddle.to_tensor(np.zeros((3, 3), "float32"))
        d = paddle.diagonal_scatter(m, paddle.to_tensor(
            np.array([1.0, 2.0, 3.0], "float32")))
        np.testing.assert_allclose(np.diag(d.numpy()), [1, 2, 3])

        u = paddle.unflatten(paddle.to_tensor(
            np.arange(12, dtype="float32")), 0, [3, -1])
        assert u.shape == [3, 4]

    def test_sgn_signbit(self):
        x = paddle.to_tensor(np.array([-2.0, 0.0, 5.0], "float32"))
        np.testing.assert_allclose(paddle.sgn(x).numpy(), [-1, 0, 1])
        np.testing.assert_array_equal(paddle.signbit(x).numpy(),
                                      [True, False, False])
        z = paddle.to_tensor(np.array([3 + 4j], "complex64"))
        s = paddle.sgn(z).numpy()
        np.testing.assert_allclose(s, [0.6 + 0.8j], rtol=1e-5)


class TestReviewRegressions:
    def test_roi_pool_exact_max_even_coords(self):
        # the max lives at an even coordinate a sampling grid would skip
        x = np.zeros((1, 1, 8, 8), "float32")
        x[0, 0, 2, 2] = 9.0
        out = vops.roi_pool(paddle.to_tensor(x),
                            paddle.to_tensor(
                                np.array([[0.0, 0.0, 7.0, 7.0]],
                                         "float32")),
                            paddle.to_tensor(np.array([1], "int32")),
                            output_size=1)
        assert abs(float(out.numpy()[0, 0, 0, 0]) - 9.0) < 1e-5

    def test_grid_sample_reflection(self):
        x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
        # coordinate beyond -1: reflection samples the mirrored interior
        grid = np.full((1, 1, 1, 2), -1.5, "float32")
        out = F.grid_sample(paddle.to_tensor(x), paddle.to_tensor(grid),
                            padding_mode="reflection",
                            align_corners=True)
        # x=-1.5 -> unnorm -0.75 -> reflect 0.75; same for y
        want = (x[0, 0, 0, 0] * 0.25 * 0.25 + x[0, 0, 0, 1] * 0.25 * 0.75
                + x[0, 0, 1, 0] * 0.75 * 0.25
                + x[0, 0, 1, 1] * 0.75 * 0.75)
        assert abs(float(out.numpy()[0, 0, 0, 0]) - want) < 1e-4

    def test_sequence_mask_multidim(self):
        lengths = np.array([[1, 2], [3, 0]], "int64")
        m = F.sequence_mask(paddle.to_tensor(lengths), maxlen=4)
        assert m.shape == [2, 2, 4]
        np.testing.assert_array_equal(m.numpy()[1, 0], [1, 1, 1, 0])

    def test_temporal_shift_nhwc(self):
        x = np.arange(2 * 2 * 2 * 4, dtype="float32").reshape(2, 2, 2, 4)
        out_nhwc = F.temporal_shift(paddle.to_tensor(x), seg_num=2,
                                    data_format="NHWC")
        want = F.temporal_shift(
            paddle.to_tensor(x.transpose(0, 3, 1, 2)),
            seg_num=2).numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(out_nhwc.numpy(), want)

    def test_max_unpool2d_nonsquare(self):
        x = np.ones((1, 1, 2, 3), "float32")
        idx = np.arange(6, dtype="int64").reshape(1, 1, 2, 3)
        out = F.max_unpool2d(paddle.to_tensor(x), paddle.to_tensor(idx),
                             kernel_size=(2, 4))
        assert out.shape == [1, 1, 4, 12]

    def test_diagonal_scatter_3d(self):
        x = paddle.to_tensor(np.zeros((2, 2, 3), "float32"))
        # diagonal over axes (0, 1): paddle y layout [3, 2] (diag last)
        y = paddle.to_tensor(np.array([[1.0, 2.0], [3.0, 4.0],
                                       [5.0, 6.0]], "float32"))
        out = paddle.diagonal_scatter(x, y, axis1=0, axis2=1)
        o = out.numpy()
        np.testing.assert_allclose(o[0, 0], [1, 3, 5])
        np.testing.assert_allclose(o[1, 1], [2, 4, 6])
        np.testing.assert_allclose(o[0, 1], 0.0)

    def test_box_coder_decode_axis1_var(self):
        prior = np.array([[0, 0, 4, 4], [1, 1, 5, 5]], "float32")
        var = np.full((2, 4), 0.5, "float32")
        deltas = np.zeros((2, 3, 4), "float32")  # priors on axis 1? no:
        # axis=1 -> priors on axis 0 of the output grid: [N=2, M=3, 4]
        out = vops.box_coder(paddle.to_tensor(prior),
                             paddle.to_tensor(var),
                             paddle.to_tensor(deltas),
                             code_type="decode_center_size", axis=1)
        assert out.shape == [2, 3, 4]
        # zero deltas decode back to the prior boxes regardless of var
        np.testing.assert_allclose(out.numpy()[0, 0], prior[0],
                                   rtol=1e-5)
        np.testing.assert_allclose(out.numpy()[1, 2], prior[1],
                                   rtol=1e-5)


class TestDetectionLongTail:
    """prior_box / distribute_fpn_proposals / iou_similarity / box_clip /
    matrix_nms / generate_proposals (reference:
    paddle/fluid/operators/detection/, python/paddle/vision/ops.py)."""

    def test_prior_box_shapes_and_geometry(self):
        from paddle_tpu.vision import ops as vops
        feat = paddle.to_tensor(np.zeros((1, 3, 4, 6), "float32"))
        img = paddle.to_tensor(np.zeros((1, 3, 8, 12), "float32"))
        boxes, var = vops.prior_box(feat, img, min_sizes=[2.0, 4.0],
                                    aspect_ratios=[1.0, 2.0],
                                    flip=True, clip=True)
        # priors per position: per min_size -> ar{1,2,0.5} = 3 -> 6
        assert boxes.shape == [4, 6, 6, 4]
        assert var.shape == [4, 6, 6, 4]
        b = boxes.numpy()
        assert (b >= 0).all() and (b <= 1).all()  # clipped
        # center of cell (0,0): offset 0.5 * step (12/6=2, 8/4=2) = (1,1)
        ms = 2.0
        np.testing.assert_allclose(
            b[0, 0, 0], [(1 - ms / 2) / 12, (1 - ms / 2) / 8,
                         (1 + ms / 2) / 12, (1 + ms / 2) / 8],
            rtol=1e-5)
        np.testing.assert_allclose(var.numpy()[0, 0, 0],
                                   [0.1, 0.1, 0.2, 0.2], rtol=1e-6)

    def test_distribute_fpn_proposals_levels_and_restore(self):
        from paddle_tpu.vision import ops as vops
        rois = np.array([[0, 0, 10, 10],      # scale 10  -> low level
                         [0, 0, 224, 224],    # scale 224 -> refer level
                         [0, 0, 500, 500],    # scale 500 -> higher
                         [0, 0, 30, 30]], "float32")
        multi, restore, per_level = vops.distribute_fpn_proposals(
            paddle.to_tensor(rois), min_level=2, max_level=5,
            refer_level=4, refer_scale=224,
            rois_num=paddle.to_tensor(np.array([4], "int32")))
        assert len(multi) == 4 and len(per_level) == 4
        total = sum(m.shape[0] for m in multi)
        assert total == 4
        # restore index is a permutation
        r = restore.numpy().reshape(-1)
        assert sorted(r.tolist()) == [0, 1, 2, 3]
        # concat(multi)[restore] == original order
        cat = np.concatenate([m.numpy() for m in multi])
        np.testing.assert_allclose(cat[r], rois)
        assert sum(int(p.numpy()[0]) for p in per_level) == 4

    def test_iou_similarity_and_box_clip(self):
        from paddle_tpu.vision import ops as vops
        a = np.array([[0, 0, 10, 10], [5, 5, 15, 15]], "float32")
        b = np.array([[0, 0, 10, 10]], "float32")
        iou = vops.iou_similarity(paddle.to_tensor(a),
                                  paddle.to_tensor(b)).numpy()
        np.testing.assert_allclose(iou[0, 0], 1.0, rtol=1e-5)
        np.testing.assert_allclose(iou[1, 0], 25.0 / 175.0, rtol=1e-4)
        boxes = np.array([[-5, -5, 50, 50]], "float32")
        im_info = np.array([[20.0, 30.0, 1.0]], "float32")
        clipped = vops.box_clip(paddle.to_tensor(boxes),
                                paddle.to_tensor(im_info)).numpy()
        np.testing.assert_allclose(clipped[0], [0, 0, 29, 19],
                                   rtol=1e-5)

    def test_matrix_nms_decays_overlaps(self):
        from paddle_tpu.vision import ops as vops
        bboxes = np.array([[[0, 0, 10, 10], [0, 0, 10.5, 10.5],
                            [20, 20, 30, 30]]], "float32")
        scores = np.array([[[0.9, 0.8, 0.7]]], "float32")  # 1 class
        out, rois_num, index = vops.matrix_nms(
            paddle.to_tensor(bboxes), paddle.to_tensor(scores),
            score_threshold=0.1, post_threshold=0.0, nms_top_k=10,
            keep_top_k=10, background_label=-1, return_index=True)
        o = out.numpy()
        assert o.shape[1] == 6
        assert int(rois_num.numpy()[0]) == 3
        # top box keeps its score; the overlapping one is decayed below
        np.testing.assert_allclose(o[0, 1], 0.9, rtol=1e-5)
        decayed = o[np.argsort(-o[:, 1])][1:]
        box2_row = [r for r in o if abs(r[1] - 0.7) < 0.05]
        assert len(box2_row) == 1  # far box not decayed
        overlap_rows = [r for r in o if r[1] < 0.6]
        assert len(overlap_rows) == 1  # heavy overlap decayed hard

    def test_generate_proposals_end_to_end(self):
        from paddle_tpu.vision import ops as vops
        rs = np.random.RandomState(0)
        H = W = 4
        A = 3
        scores = rs.rand(1, A, H, W).astype("float32")
        deltas = (rs.randn(1, 4 * A, H, W) * 0.1).astype("float32")
        base = np.array([[0, 0, 16, 16], [0, 0, 32, 32],
                         [0, 0, 48, 48]], "float32")
        anchors = np.zeros((H, W, A, 4), "float32")
        for y in range(H):
            for x in range(W):
                shift = np.array([x * 16, y * 16, x * 16, y * 16],
                                 "float32")
                anchors[y, x] = base + shift
        variances = np.ones_like(anchors)
        rois, rscores, num = vops.generate_proposals(
            paddle.to_tensor(scores), paddle.to_tensor(deltas),
            paddle.to_tensor(np.array([[64.0, 64.0]], "float32")),
            paddle.to_tensor(anchors), paddle.to_tensor(variances),
            pre_nms_top_n=20, post_nms_top_n=5, nms_thresh=0.5,
            min_size=1.0, return_rois_num=True)
        r = rois.numpy()
        assert r.shape[0] == int(num.numpy()[0]) <= 5
        assert (r[:, 0] >= 0).all() and (r[:, 2] <= 64).all()
        s = rscores.numpy()
        assert (np.diff(s) <= 1e-6).all()  # sorted descending

    def test_matrix_nms_chained_overlap_compensation(self):
        """Code-review regression: decay must compensate with each
        predecessor's OWN iou_max (reference Decay semantics) — C
        overlapping only B (which was itself decayed by A) keeps its
        score."""
        from paddle_tpu.vision import ops as vops
        bboxes = np.array([[[0, 0, 10, 10], [0, 5, 10, 15],
                            [0, 10, 10, 20]]], "float32")
        scores = np.array([[[0.9, 0.8, 0.7]]], "float32")
        out, _ = vops.matrix_nms(
            paddle.to_tensor(bboxes), paddle.to_tensor(scores),
            score_threshold=0.1, post_threshold=0.0, nms_top_k=10,
            keep_top_k=10, background_label=-1)
        o = out.numpy()
        c_row = o[np.isclose(o[:, 4], 10.0) & np.isclose(o[:, 5], 20.0)]
        # IoU(C,A)=0; IoU(C,B)=1/3 with iou_max[B]=1/3 ->
        # decay = (1-1/3)/(1-1/3) = 1 -> C keeps 0.7
        np.testing.assert_allclose(c_row[0, 1], 0.7, rtol=1e-5)

    def test_box_clip_per_image(self):
        from paddle_tpu.vision import ops as vops
        boxes = np.array([[-5, -5, 500, 500],
                          [-5, -5, 500, 500]], "float32")
        im_info = np.array([[100, 100, 1.0], [300, 400, 1.0]],
                           "float32")
        out = vops.box_clip(paddle.to_tensor(boxes),
                            paddle.to_tensor(im_info),
                            rois_num=paddle.to_tensor(
                                np.array([1, 1], "int32"))).numpy()
        np.testing.assert_allclose(out[0], [0, 0, 99, 99])
        np.testing.assert_allclose(out[1], [0, 0, 399, 299])
        with pytest.raises(ValueError, match="rois_num"):
            vops.box_clip(paddle.to_tensor(boxes),
                          paddle.to_tensor(im_info))

    def test_generate_proposals_keep_all_and_eta(self):
        """pre_nms_top_n<=0 keeps all anchors; eta<1 runs adaptive NMS
        (code-review regressions)."""
        from paddle_tpu.vision import ops as vops
        rs = np.random.RandomState(1)
        H = W = 2
        A = 2
        scores = rs.rand(1, A, H, W).astype("float32")
        deltas = np.zeros((1, 4 * A, H, W), "float32")
        base = np.array([[0, 0, 8, 8], [0, 0, 16, 16]], "float32")
        anchors = np.zeros((H, W, A, 4), "float32")
        for y in range(H):
            for x in range(W):
                anchors[y, x] = base + np.array(
                    [x * 8, y * 8, x * 8, y * 8], "float32")
        var = np.ones_like(anchors)
        rois, rscores = vops.generate_proposals(
            paddle.to_tensor(scores), paddle.to_tensor(deltas),
            paddle.to_tensor(np.array([[32.0, 32.0]], "float32")),
            paddle.to_tensor(anchors), paddle.to_tensor(var),
            pre_nms_top_n=0, post_nms_top_n=100, nms_thresh=0.99,
            min_size=1.0)
        assert rois.shape[0] == H * W * A  # nothing dropped pre-NMS
        rois2, _ = vops.generate_proposals(
            paddle.to_tensor(scores), paddle.to_tensor(deltas),
            paddle.to_tensor(np.array([[32.0, 32.0]], "float32")),
            paddle.to_tensor(anchors), paddle.to_tensor(var),
            pre_nms_top_n=0, post_nms_top_n=100, nms_thresh=0.9,
            min_size=1.0, eta=0.6)
        # adaptive threshold decays below overlaps -> fewer kept
        assert rois2.shape[0] <= rois.shape[0]

    def test_box_clip_count_mismatch_raises(self):
        from paddle_tpu.vision import ops as vops
        boxes = np.zeros((5, 4), "float32")
        im_info = np.array([[10, 10, 1.0], [10, 10, 1.0]], "float32")
        with pytest.raises(ValueError, match="sum\\(rois_num\\)"):
            vops.box_clip(paddle.to_tensor(boxes),
                          paddle.to_tensor(im_info),
                          rois_num=paddle.to_tensor(
                              np.array([2, 2], "int32")))


class TestNewModelFamilies:
    """DenseNet/SqueezeNet/ShuffleNetV2/GoogLeNet/InceptionV3/
    MobileNetV3 (reference: python/paddle/vision/models/)."""

    def _smoke(self, model, size=64, out_shape=None):
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(2, 3, size, size)
                             .astype("float32"))
        model.eval()
        out = model(x)
        if isinstance(out, tuple):
            out = out[0]
        assert out.shape == (out_shape or [2, 10])
        assert np.isfinite(out.numpy()).all()
        return out

    def test_densenet121(self):
        from paddle_tpu.vision.models import densenet121
        paddle.seed(0)
        self._smoke(densenet121(num_classes=10))

    @pytest.mark.slow
    def test_squeezenet(self):
        from paddle_tpu.vision.models import squeezenet1_0, \
            squeezenet1_1
        paddle.seed(0)
        self._smoke(squeezenet1_0(num_classes=10), size=96)
        self._smoke(squeezenet1_1(num_classes=10), size=96)

    @pytest.mark.slow
    def test_shufflenet(self):
        from paddle_tpu.vision.models import shufflenet_v2_x0_25, \
            shufflenet_v2_swish
        paddle.seed(0)
        self._smoke(shufflenet_v2_x0_25(num_classes=10))
        self._smoke(shufflenet_v2_swish(num_classes=10))

    def test_googlenet_aux_heads(self):
        from paddle_tpu.vision.models import googlenet
        paddle.seed(0)
        m = googlenet(num_classes=10)
        m.eval()
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(2, 3, 64, 64).astype("float32"))
        main, aux1, aux2 = m(x)
        assert main.shape == [2, 10]
        assert aux1.shape == [2, 10] and aux2.shape == [2, 10]

    def test_mobilenet_v3(self):
        from paddle_tpu.vision.models import mobilenet_v3_small
        paddle.seed(0)
        self._smoke(mobilenet_v3_small(num_classes=10))

    @pytest.mark.slow
    def test_inception_v3(self):
        # ~45s: the 299x299 forward is the heaviest smoke in the
        # family — slow lane keeps tier-1 inside its 870s budget
        # (densenet/googlenet/mobilenet/... forwards stay tier-1)
        from paddle_tpu.vision.models import inception_v3
        paddle.seed(0)
        self._smoke(inception_v3(num_classes=10), size=299)

    @pytest.mark.slow
    def test_densenet_trains(self):
        # ~70s of eager densenet121 train steps — the convergence
        # check rides the slow lane; tier-1 keeps the densenet121
        # forward smoke (test_densenet121)
        from paddle_tpu.vision.models import densenet121
        import paddle_tpu.optimizer as opt
        import paddle_tpu.nn.functional as F
        paddle.seed(0)
        m = densenet121(num_classes=4)
        sgd = opt.Momentum(learning_rate=0.05, momentum=0.9,
                           parameters=m.parameters())
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(4, 3, 32, 32).astype("float32"))
        y = paddle.to_tensor(np.arange(4) % 4)
        losses = []
        for _ in range(3):
            loss = F.cross_entropy(m(x), y)
            loss.backward()
            sgd.step()
            sgd.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_pretrained_raises_no_egress(self):
        from paddle_tpu.vision.models import densenet121
        with pytest.raises(RuntimeError, match="egress"):
            densenet121(pretrained=True)


class TestTransformsLongTail:
    """ColorJitter/Grayscale/RandomRotation/RandomAffine/RandomErasing +
    contrast/saturation/hue (reference: vision/transforms/transforms.py
    :831-:1790)."""

    def _img(self):
        rs = np.random.RandomState(0)
        return (rs.rand(16, 16, 3) * 255).astype("uint8")

    def test_grayscale(self):
        from paddle_tpu.vision import transforms as T
        g1 = T.Grayscale(1)(self._img())
        g3 = T.Grayscale(3)(self._img())
        assert g1.shape == (16, 16, 1) and g3.shape == (16, 16, 3)
        np.testing.assert_array_equal(g3[..., 0], g3[..., 1])

    def test_color_jitter_runs_and_preserves_shape_dtype(self):
        from paddle_tpu.vision import transforms as T
        import random as pyrandom
        pyrandom.seed(0)
        out = T.ColorJitter(0.4, 0.4, 0.4, 0.2)(self._img())
        assert out.shape == (16, 16, 3) and out.dtype == np.uint8

    def test_hue_identity_at_zero(self):
        from paddle_tpu.vision import transforms as T
        img = self._img()
        np.testing.assert_array_equal(T.HueTransform(0.0)(img), img)
        out = T.HueTransform(0.3)(img)
        assert out.shape == img.shape

    def test_rotation_90_matches_rot90(self):
        from paddle_tpu.vision.transforms import RandomRotation
        img = self._img()
        t = RandomRotation((90, 90))
        out = t._apply_image(img)
        # nearest-neighbor rotation by exactly 90 degrees == rot90
        np.testing.assert_array_equal(out, np.rot90(img, k=1, axes=(0, 1)))

    def test_random_affine_translate_only(self):
        from paddle_tpu.vision.transforms import RandomAffine
        img = self._img()
        t = RandomAffine(degrees=(0, 0))
        out = t._apply_image(img)
        np.testing.assert_array_equal(out, img)  # identity affine

    def test_random_erasing(self):
        from paddle_tpu.vision.transforms import RandomErasing
        import random as pyrandom
        pyrandom.seed(3)
        img = np.full((20, 20, 3), 200, "uint8")
        out = RandomErasing(prob=1.0, value=0)(img)
        assert (out == 0).any()
        assert out.shape == img.shape

    def test_hue_and_jitter_pass_grayscale_through(self):
        """code-review regression: L-mode images must not crash hue."""
        from paddle_tpu.vision import transforms as T
        gray = (np.random.RandomState(0).rand(8, 8) * 255) \
            .astype("uint8")
        out = T.HueTransform(0.3)(gray)
        assert out.shape == (8, 8, 1)
        out2 = T.ColorJitter(0.4, 0.4, 0.4, 0.2)(gray)
        assert out2.shape == (8, 8, 1)

    def test_rotation_expand_grows_canvas(self):
        from paddle_tpu.vision.transforms import RandomRotation
        img = np.full((10, 20, 3), 255, "uint8")
        out = RandomRotation((90, 90), expand=True)._apply_image(img)
        assert out.shape[0] == 20 and out.shape[1] == 10

    def test_affine_y_shear_applied(self):
        from paddle_tpu.vision.transforms import RandomAffine
        import random as pyrandom
        pyrandom.seed(0)
        img = np.zeros((21, 21, 1), "uint8")
        img[10, :, 0] = 255  # horizontal line
        t = RandomAffine(degrees=(0, 0), shear=[0, 0, 30, 30])
        out = t._apply_image(img)
        # y-shear tilts the horizontal line: multiple rows now hold it
        rows = np.nonzero(out[..., 0].sum(axis=1))[0]
        assert len(rows) > 1
