"""Automatic prefix cache: a token-id radix tree over the paged KV pool.

Production traffic is dominated by shared prefixes — system prompts,
few-shot templates, multi-turn conversations re-sending their history.
The paged KV pool (serving/paging.py) stores KV at page granularity
precisely so those prefixes can be SHARED: the KV vector written for
position p is a deterministic function of tokens[0..p], so any two
requests whose token ids agree on [0, L) can point their page tables at
the same physical pages for those positions and skip prefilling them.

Structure
---------
A radix tree over page-aligned token spans. Each node's edge covers
exactly one FULL page: `page_size` consecutive token ids mapped to one
page id in the shared per-layer pools; a path root->node spells a
page-aligned token prefix and the list of page ids holding its KV.
Children are keyed by the next page's token ids (exact-match dict hop),
so a lookup costs O(prompt_len / page_size) dict probes. Divergence
inside a page is NOT shared at page granularity — two prompts that
split mid-page get separate pages — which is what keeps sharing free of
partial-page aliasing.

Leaves may additionally carry PARTIAL pages: a page whose first
`len(tokens) < page_size` positions are valid (the tail of a finished
request). A new prompt that matches into a partial page (or into the
head of a full page) cannot attach it directly — the request will keep
writing KV into that page's remaining positions — so the match is
granted COPY-ON-WRITE: the engine allocates a fresh page, performs one
single-page device copy, and the page table points at the private copy.
A shared page is never written through.

Lifecycle
---------
- `acquire(prompt, max_new)` — admission: longest-prefix match, then
  refcount++ the matched full pages (zero prefill work, zero copies),
  allocate the fresh tail (evicting LRU unreferenced leaves first under
  page pressure), and return a `PrefixGrant` with the page-table order
  and the number of cached tokens. Refusal (even after eviction) has no
  side effects — admission backpressure degrades to exactly the
  cache-off behavior.
- `insert(tokens, pages, valid)` — retirement of a normally finished
  request: its full pages become tree nodes (the partial tail page a
  partial leaf) so multi-turn follow-ups hit; pages already in the tree
  are deduplicated (the request's duplicate copy is freed). All of the
  request's references are dropped; pages that hit refcount 0 are
  PARKED as cache-resident rather than freed.
- `release(pages)` — retirement of cancelled/aborted/timed-out
  requests: refcount--; tree pages park, private pages free.
- `spill(need)` — the HOST-RAM tier (stage 1 of the ROADMAP's
  fleet-scale prefix cache): under page pressure, unreferenced parked
  pages are SPILLED to host memory before anything is dropped — the
  device page frees (PagePool.swap_out), the node stays in the tree
  with a host slot instead of a device page, and a later match
  RESTORES it (swap-in into a freshly allocated page) instead of
  re-prefilling. Wired by the engine via `set_host_tier`; without it
  spill is a no-op and eviction behaves exactly as before.
- `evict(need)` — leaf-to-root LRU: only unreferenced leaves (and
  partial pages) are freed, oldest last-use first; a node referenced by
  any running request is never touched. Eviction happens inside
  `acquire` AFTER spilling and before admission backpressure, so a
  cold or thrashing cache behaves exactly like no cache at all.

The compiled decode/prefill programs never see any of this: hits, COW
and eviction only change which page ids the host page tables carry.

Fleet fabric (serving/fabric.py) extends the same tree across
replicas: `collect_chain`/`graft` move one committed page chain
between two trees (disaggregated prefill handoff), `snapshot`/`load`
move the WHOLE tree across an engine restart (warm deploys), and
`fingerprints` summarizes the tree as hashed page-aligned prefixes
for the router's affinity ranking. All of them speak the engine's
opaque page payloads (`_extract_page` blocks) — the tree never looks
inside a page.
"""
from __future__ import annotations

import heapq
import itertools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from .paging import PagePool, TRASH_PAGE, pages_needed

__all__ = ["RadixPrefixCache", "PrefixGrant",
           "resolve_prefix_cache_flag", "shared_prefix_groups"]


def shared_prefix_groups(page_tables, q_len):
    """Prefix-sharing groups for one engine step (the grouped-walk
    operands of `ragged_paged_attention_grouped`): rows whose page
    tables carry IDENTICAL page ids for a leading span are attending
    the same physical pages — the radix cache attached them — and the
    kernel can stream that span once per group instead of once per
    row.

    `page_tables` is the host page table [S, max_pages] int32 (trash
    page 0 marks unallocated entries), `q_len` [S] the step's per-row
    live query counts (rows at q_len 0 idle this step and are never
    grouped). Rows are partitioned by recursive refinement: all rows
    sharing page 0, split at the first column where they diverge (a
    mid-span COW page is private by construction, so the COW'd row
    falls out of the group exactly at its divergence point). Returns
    (group_id [S], group_leader [S], group_cnt [S]) int32 — row ->
    group, group -> representative row, group -> shared page count
    (0 for singletons; group ids are compact but arbitrary). Shared
    pages always hold committed KV at or below every member's pos (a
    prefix match never exceeds the prompt), which is the operand
    contract the two-phase kernel assumes."""
    pt = np.asarray(page_tables)
    q_len = np.asarray(q_len)
    S, mp = pt.shape
    group_id = np.arange(S, dtype=np.int32)
    group_leader = np.zeros(S, dtype=np.int32)
    group_cnt = np.zeros(S, dtype=np.int32)
    next_gid = [0]

    def close(rows, depth):
        g = next_gid[0]
        next_gid[0] += 1
        for r in rows:
            group_id[r] = g
        group_leader[g] = rows[0]
        group_cnt[g] = depth if len(rows) >= 2 else 0

    def best(rows, depth):
        """Best grouping of `rows` (which share pages [0, depth)):
        either keep them ONE group closed at this depth, or split at
        the first divergence and group the sub-buckets deeper —
        whichever saves more page reads ((members - 1) * shared_span
        per group). Returns (savings, [(rows, span), ...])."""
        if len(rows) == 1:
            return 0, [(rows, 0)]
        if depth >= mp:
            return (len(rows) - 1) * depth, [(rows, depth)]
        buckets: Dict[int, List[int]] = {}
        for r in rows:
            buckets.setdefault(int(pt[r, depth]), []).append(r)
        if len(buckets) == 1:
            page = next(iter(buckets))
            if page != TRASH_PAGE:
                return best(rows, depth + 1)   # still together
            return (len(rows) - 1) * depth, [(rows, depth)]
        keep = (len(rows) - 1) * depth         # one group, close here
        split_sav, split_plan = 0, []
        for page, sub in sorted(buckets.items()):
            if page == TRASH_PAGE:
                s, p = ((len(sub) - 1) * depth, [(sub, depth)])
            elif len(sub) == 1:
                s, p = 0, [(sub, 0)]
            else:
                s, p = best(sub, depth + 1)
            split_sav += s
            split_plan.extend(p)
        if keep >= split_sav:
            return keep, [(rows, depth)]
        return split_sav, split_plan

    live = [r for r in range(S)
            if q_len[r] > 0 and pt[r, 0] != TRASH_PAGE]
    buckets: Dict[int, List[int]] = {}
    for r in live:
        buckets.setdefault(int(pt[r, 0]), []).append(r)
    for page, rows in sorted(buckets.items()):
        if len(rows) == 1:
            close(rows, 0)
        else:
            _, plan = best(rows, 1)
            for sub, span in plan:
                close(sub, span)
    live_set = set(live)
    for r in range(S):
        if r not in live_set:
            g = next_gid[0]
            next_gid[0] += 1
            group_id[r] = g
            group_leader[g] = r
            group_cnt[g] = 0
    return group_id, group_leader, group_cnt


def resolve_prefix_cache_flag(override=None) -> bool:
    """Whether the engine runs the automatic prefix cache: an explicit
    `ServingEngine(prefix_cache=...)` wins; otherwise the
    PADDLE_TPU_PREFIX_CACHE env var (default on)."""
    import os
    if override is not None:
        if isinstance(override, bool):
            return override
        flag = str(override)
    else:
        flag = os.environ.get("PADDLE_TPU_PREFIX_CACHE", "on")
    low = flag.strip().lower()
    if low in ("on", "1", "true", "yes"):
        return True
    if low in ("off", "0", "false", "no"):
        return False
    raise ValueError(
        "PADDLE_TPU_PREFIX_CACHE / prefix_cache must be on|off, "
        f"got {flag!r}")


class _Node:
    """One radix edge: a full page of `page_size` token ids. A node
    whose content was spilled to the host tier keeps `page=None` and a
    `host` slot id until a match restores it."""

    __slots__ = ("tokens", "page", "parent", "children", "partials",
                 "last_used", "host", "pin_until")

    def __init__(self, tokens: Optional[np.ndarray], page: Optional[int],
                 parent: Optional["_Node"]):
        self.tokens = tokens          # int64 [page_size]; None at root
        self.page = page              # pool page id; None at root/spilled
        self.parent = parent
        self.children: Dict[bytes, "_Node"] = {}
        self.partials: List["_Partial"] = []
        self.last_used = 0
        self.host = None              # host-tier slot id when spilled
        self.pin_until = 0.0          # session-pin TTL deadline (clock)


class _Partial:
    """A leaf-only partially filled page: positions [0, len(tokens))
    of `page` hold valid KV for `tokens` (< page_size of them)."""

    __slots__ = ("tokens", "page", "last_used")

    def __init__(self, tokens: np.ndarray, page: int):
        self.tokens = tokens
        self.page = page
        self.last_used = 0


@dataclass
class PrefixGrant:
    """Everything the engine needs to admit a cache-hit request:
    `pages` in page-table order (shared fulls, then the COW copy if
    any, then fresh tail pages), the prefill cursor start
    (`cached_len`), and the pending single-page COW copy. `cow_src`
    stays refcount-protected until the engine reports the copy done
    via `RadixPrefixCache.cow_done`."""

    pages: List[int]
    cached_len: int
    cow_src: Optional[int] = None
    cow_dst: Optional[int] = None
    matched_full_pages: int = 0
    fresh_pages: List[int] = field(default_factory=list)


def _tok(seq) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(seq).reshape(-1),
                                dtype=np.int64)


def _common_prefix(a: np.ndarray, b: np.ndarray) -> int:
    n = min(a.size, b.size)
    if n == 0:
        return 0
    neq = np.nonzero(a[:n] != b[:n])[0]
    return int(neq[0]) if neq.size else n


class RadixPrefixCache:
    """Radix-tree prefix cache over one engine's `PagePool`.

    Single-threaded by construction, like everything else that touches
    page tables: the engine calls it only between compiled steps.
    """

    def __init__(self, pool: PagePool, page_size: int, clock=None):
        self.pool = pool
        self.page_size = int(page_size)
        # injectable clock for the session-pin TTL tier (tests drive
        # expiry deterministically; the engine passes its own clock)
        self._clock = clock if clock is not None else time.monotonic
        self.root = _Node(None, None, None)
        # TENANT ISOLATION (multi-tenant LoRA serving): the tree is
        # namespaced by adapter id — KV written under adapter i is a
        # function of (tokens, adapter i's weights), so an identical
        # prompt under adapter j must MISS it. One root per adapter
        # id; adapter 0 (the base model) keeps the classic root.
        self._roots: Dict[int, _Node] = {0: self.root}
        # page id -> owning _Node/_Partial, for release() routing and
        # O(1) "is this page tree-resident"
        self._owner: Dict[int, object] = {}
        self._tick = itertools.count(1)
        # counters (mirrored into ServingMetrics at step boundaries)
        self.lookups = 0
        self.hits = 0
        self.cached_tokens_total = 0
        self.evicted_pages_total = 0
        self.cow_copies_total = 0
        self.inserted_pages_total = 0
        self.spilled_pages_total = 0
        self.restored_pages_total = 0
        # host tier callbacks (engine-wired; None = no host tier):
        # _host_store(page) -> host slot or None (copies the device
        # page's KV to host RAM; the cache then swap_out's the page),
        # _host_load(host_slot) -> device page or None (allocates a
        # fresh page, restores into it, returns it PARKED cache-
        # resident), _host_drop(host_slot) (discard a spilled page's
        # host copy — evicted from the tree while swapped)
        self._host_store = None
        self._host_load = None
        self._host_drop = None
        self._n_spilled = 0

    # -- introspection -----------------------------------------------------
    @property
    def tree_pages(self) -> int:
        """Pages the radix tree currently indexes (referenced or
        cache-resident)."""
        return len(self._owner)

    @property
    def spilled_nodes(self) -> int:
        """Tree nodes whose page currently lives in the host tier."""
        return self._n_spilled

    def set_host_tier(self, store, load, drop):
        """Wire the host-RAM page tier (engine callbacks — see the
        attribute docs in __init__). With these set, page pressure
        SPILLS parked pages to host before evicting, and a match on a
        spilled node swap-ins instead of falling back to prefill."""
        self._host_store = store
        self._host_load = load
        self._host_drop = drop

    @property
    def pinned_pages(self) -> int:
        """Device-resident tree pages currently under an unexpired
        session pin (the `prefix_pinned_pages` gauge)."""
        now = self._clock()
        count = 0
        stack = list(self._roots.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node.page is not None and node.pin_until > now:
                count += 1
        return count

    def stats(self) -> dict:
        return {
            "lookups": self.lookups,
            "hits": self.hits,
            "cached_tokens": self.cached_tokens_total,
            "evicted_pages": self.evicted_pages_total,
            "cow_copies": self.cow_copies_total,
            "inserted_pages": self.inserted_pages_total,
            "spilled_pages": self.spilled_pages_total,
            "restored_pages": self.restored_pages_total,
            "spilled_nodes": self._n_spilled,
            "pinned_pages": self.pinned_pages,
            "tree_pages": self.tree_pages,
            "resident_pages": self.pool.cached_pages,
            "hit_rate": (self.hits / self.lookups) if self.lookups
            else None,
        }

    def _touch(self, obj):
        obj.last_used = next(self._tick)

    def _root_for(self, adapter_id: int) -> _Node:
        """The adapter's namespace root (created on first use —
        adapter id joins the match key, so tenant A's pages are
        unreachable from tenant B's walks by construction)."""
        root = self._roots.get(int(adapter_id))
        if root is None:
            root = self._roots[int(adapter_id)] = _Node(None, None,
                                                        None)
        return root

    # -- matching ----------------------------------------------------------
    def _match_full(self, tok: np.ndarray, limit: int, acquire: bool
                    = True, root: Optional[_Node] = None
                    ) -> Tuple[_Node, List[int], int]:
        """Walk full-page edges: returns (last node, matched page ids,
        matched token count). Only whole pages match here; `limit`
        caps the match so at least one prompt token always prefills
        (the sampler needs the last token's logits). With `acquire`
        (the reservation path) each matched page is RETAINED as it is
        walked — so the restore/spill machinery below can never touch
        the match in progress — and a SPILLED node on the path is
        RESTORED from the host tier (swap-in into a fresh device
        page, spilling another LRU page to make room if needed); if
        restore fails (host tier gone / truly no page) the walk stops
        there and the tail simply prefills. `acquire=False` (the
        side-effect-free lookup probe) counts spilled spans as
        matchable without touching anything."""
        ps = self.page_size
        node, pages, depth = (self.root if root is None else root,
                              [], 0)
        while depth + ps <= limit:
            child = node.children.get(tok[depth:depth + ps].tobytes())
            if child is None:
                break
            if child.page is None:            # spilled to host
                if not acquire:
                    node = child
                    depth += ps
                    continue
                if not self._restore(child):
                    break
            node = child
            if acquire:
                self.pool.retain([child.page])
            pages.append(child.page)
            depth += ps
            self._touch(child)
        return node, pages, depth

    def _restore(self, node: _Node) -> bool:
        """Swap a spilled node's page back in from the host tier. The
        engine's load callback returns the restored device page
        already PARKED (cache-resident, refcount 0) so the caller's
        retain path treats it exactly like any other tree page."""
        if self._host_load is None:
            return False
        page = self._host_load(node.host)
        if page is None:
            return False
        node.page = page
        node.host = None
        self._owner[page] = node
        self._n_spilled -= 1
        self.restored_pages_total += 1
        return True

    def _best_tail(self, node: _Node, tail: np.ndarray
                   ) -> Tuple[int, Optional[int]]:
        """Best copy-on-write candidate below `node` for the remaining
        (sub-page) prompt tokens: a partial leaf or the head of a full
        child page sharing the longest prefix with `tail`. Returns
        (matched token count, source page id)."""
        best_k, best_page, best_obj = 0, None, None
        for part in node.partials:
            k = _common_prefix(tail, part.tokens)
            if k > best_k:
                best_k, best_page, best_obj = k, part.page, part
        for child in node.children.values():
            if child.page is None:
                continue      # spilled: not a COW source on device
            k = _common_prefix(tail, child.tokens)
            if k > best_k:
                best_k, best_page, best_obj = k, child.page, child
        if best_obj is not None:
            self._touch(best_obj)
        return best_k, best_page

    def lookup(self, prompt, adapter_id: int = 0) -> int:
        """Side-effect-free probe: how many tokens of `prompt` the
        cache could serve right now (full pages — device or spilled —
        plus the best COW tail) within `adapter_id`'s namespace."""
        tok = _tok(prompt)
        limit = max(0, tok.size - 1)
        node, _, depth = self._match_full(
            tok, limit, acquire=False, root=self._root_for(adapter_id))
        k, _ = self._best_tail(node, tok[depth:limit])
        return depth + k

    # -- admission ---------------------------------------------------------
    def acquire(self, prompt, max_new_tokens: int,
                adapter_id: int = 0) -> Optional[PrefixGrant]:
        """Longest-prefix match + page reservation for one request.
        On success every page in the grant holds one reference for the
        request (shared pages refcount++, fresh pages refcount 1, the
        COW source an extra protection ref until `cow_done`). On
        refusal — only when even evicting every unreferenced cached
        page cannot cover the fresh tail — nothing changed."""
        ps = self.page_size
        tok = _tok(prompt)
        plen = tok.size
        self.lookups += 1
        limit = plen - 1        # >= 1 token must prefill for logits
        node, shared, depth = self._match_full(
            tok, limit, root=self._root_for(adapter_id))
        cow_k, cow_src = self._best_tail(node, tok[depth:limit])
        total = pages_needed(plen, max_new_tokens, ps)
        need_fresh = total - len(shared)
        # the matched pages are already retained (the walk retains as
        # it goes, protecting them from the spill/eviction below and
        # from later admissions at this same boundary); only the COW
        # source still needs its protection reference
        if cow_src is not None:
            self.pool.retain([cow_src])
        fresh = self.pool.alloc(need_fresh)
        if fresh is None:
            # page pressure: SPILL parked pages to the host tier first
            # (their KV survives, a later match swap-ins instead of
            # re-prefilling), then EVICT whatever pressure remains
            short = need_fresh - self.pool.free_pages
            short -= self.spill(short)
            if short > 0:
                self.evict(short)
            fresh = self.pool.alloc(need_fresh)
        if fresh is None and cow_src is not None:
            # the COW claim can be the very page blocking admission: a
            # request whose budget spans the whole pool retains its
            # COW source, which spill/evict then must skip — a
            # permanent self-deadlock at the queue head. A partial-
            # page match is never worth a refusal: forfeit the claim
            # (the page parks, becoming spillable/evictable again) and
            # admit with the shorter full-page match instead.
            self.release([cow_src])
            cow_src, cow_k = None, 0
            short = need_fresh - self.pool.free_pages
            short -= self.spill(short)
            if short > 0:
                self.evict(short)
            fresh = self.pool.alloc(need_fresh)
        if fresh is None:
            # roll back: the match returns to exactly its prior state
            self.release(shared)
            return None
        cached = depth + cow_k
        if cached:
            self.hits += 1
            self.cached_tokens_total += cached
        grant = PrefixGrant(
            pages=shared + fresh, cached_len=cached,
            matched_full_pages=len(shared), fresh_pages=fresh)
        if cow_src is not None:
            self.cow_copies_total += 1
            grant.cow_src = cow_src
            # the fresh page covering page index len(shared) — the one
            # the table points at for the partially-cached span
            grant.cow_dst = fresh[0]
        return grant

    def cow_done(self, grant: PrefixGrant):
        """The engine finished the single-page device copy: drop the
        COW source's protection reference."""
        if grant.cow_src is not None:
            self.release([grant.cow_src])
            grant.cow_src = None

    # -- retirement --------------------------------------------------------
    def release(self, pages: List[int]):
        """Drop one reference per page; pages that hit refcount 0 park
        (tree-resident) or free (private)."""
        zeroed = self.pool.release(pages)
        park = [p for p in zeroed if p in self._owner]
        if park:
            self.pool.park(park)
        gone = [p for p in zeroed if p not in self._owner]
        if gone:
            self.pool.free(gone)

    def insert(self, tokens, pages: List[int], valid: int,
               adapter_id: int = 0):
        """Index a finished request's written pages so future prompts
        hit — within `adapter_id`'s namespace: the KV is a function
        of the adapter's weights too, so tenants never see each
        other's pages. `tokens` is its prompt + generated ids,
        `valid` how many positions actually hold KV (prompt_len +
        emitted tokens); trailing unconsumed budget pages are simply
        freed. Duplicates (another request cached the same span
        first) are freed, the tree keeps its original. Finally drops
        ALL of the request's page references."""
        ps = self.page_size
        tok = _tok(tokens)
        valid = int(valid)
        if valid > tok.size or valid > len(pages) * ps:
            raise ValueError(
                f"valid={valid} exceeds tokens ({tok.size}) or page "
                f"capacity ({len(pages) * ps})")
        node = self._root_for(adapter_id)
        n_full = valid // ps
        for i in range(n_full):
            span = tok[i * ps:(i + 1) * ps]
            key = span.tobytes()
            child = node.children.get(key)
            if child is None:
                page = pages[i]
                child = _Node(np.array(span), page, node)
                node.children[key] = child
                self._owner[page] = child
                self.inserted_pages_total += 1
            node = child
            self._touch(node)
        rem = valid - n_full * ps
        if rem > 0:
            ptoks = np.array(tok[n_full * ps:valid])
            page = pages[n_full]
            if page not in self._owner and self._tail_is_new(node, ptoks):
                part = _Partial(ptoks, page)
                node.partials.append(part)
                self._owner[page] = part
                self.inserted_pages_total += 1
                self._touch(part)
        self.release(pages)

    def _tail_is_new(self, node: _Node, ptoks: np.ndarray) -> bool:
        """A partial tail is worth keeping only if no resident page
        already covers it (an equal-or-longer partial, or a full child
        whose head matches)."""
        for part in node.partials:
            if part.tokens.size >= ptoks.size and \
                    _common_prefix(part.tokens, ptoks) == ptoks.size:
                return False
        for child in node.children.values():
            if _common_prefix(child.tokens, ptoks) == ptoks.size:
                return False
        return True

    # -- session pinning ---------------------------------------------------
    def _pinned(self, node: _Node) -> bool:
        return node.pin_until > self._clock()

    def pin(self, tokens, ttl_s: float, adapter_id: int = 0) -> int:
        """Session pinning: hold the full-page chain covering `tokens`
        in a TTL tier between "referenced" and "evictable" — pinned
        pages are skipped by LRU eviction AND host-tier spill until
        the deadline passes, so a chat session's turn-2 follow-up hits
        warm device KV by contract, not by LRU luck. Re-pinning
        extends the deadline (max, never shortens); an EXPIRED pin
        needs no sweep — `_pinned` compares against the injectable
        clock, so the node simply becomes ordinary LRU fodder again.
        Returns the number of pages pinned."""
        if ttl_s <= 0:
            return 0
        deadline = self._clock() + float(ttl_s)
        tok = _tok(tokens)
        ps = self.page_size
        node = self._root_for(adapter_id)
        pinned = 0
        for i in range(tok.size // ps):
            child = node.children.get(tok[i * ps:(i + 1) * ps].tobytes())
            if child is None:
                break
            child.pin_until = max(child.pin_until, deadline)
            self._touch(child)
            pinned += 1
            node = child
        return pinned

    # -- spill (host tier) -------------------------------------------------
    def spill(self, need: int) -> int:
        """Move up to `need` unreferenced parked FULL pages to the
        host tier, LRU first: the device page frees
        (PagePool.swap_out) but the tree node survives with a host
        slot — a later match restores it instead of re-prefilling.
        Any node (leaf or interior) may spill; only its PAGE moves,
        the tree structure stays walkable. Returns the number of
        device pages actually freed (0 without a wired host tier)."""
        if need <= 0 or self._host_store is None:
            return 0
        heap = []
        stack = list(self._roots.values())   # every tenant namespace
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if (node.tokens is not None and node.page is not None
                    and self.pool.refcount(node.page) == 0
                    and not self._pinned(node)):
                heapq.heappush(heap, (node.last_used, id(node), node))
        spilled = 0
        while spilled < need and heap:
            _, _, node = heapq.heappop(heap)
            if node.page is None or self.pool.refcount(node.page) != 0:
                continue
            slot = self._host_store(node.page)
            if slot is None:
                break                       # host tier full: stop
            self.pool.swap_out([node.page], spill=True)
            del self._owner[node.page]
            node.host = slot
            node.page = None
            self._n_spilled += 1
            self.spilled_pages_total += 1
            spilled += 1
        return spilled

    # -- eviction ----------------------------------------------------------
    def _evictable(self, obj) -> bool:
        if isinstance(obj, _Partial):
            return self.pool.refcount(obj.page) == 0
        if obj.children or obj.partials:
            return False
        if self._pinned(obj):
            return False      # session-pinned: TTL tier, not LRU
        if obj.page is None:
            return True       # spilled leaf: only a host copy to drop
        return self.pool.refcount(obj.page) == 0

    def evict(self, need: int) -> int:
        """Free at least `need` unreferenced cached pages, LRU leaves
        first, walking leaf-to-root as parents become childless. Pages
        referenced by running requests are never touched. Returns the
        number of pages actually freed."""
        if need <= 0:
            return 0
        # seed the heap with every current leaf candidate (across
        # every tenant namespace — eviction is global LRU; isolation
        # is a MATCHING property, not a placement one)
        heap = []
        stack = list(self._roots.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            for part in node.partials:
                heapq.heappush(heap, (part.last_used, id(part), part,
                                      node))
            if node.tokens is not None and self._evictable(node):
                heapq.heappush(heap, (node.last_used, id(node), node,
                                      node.parent))
        freed = 0
        while freed < need and heap:
            _, _, obj, parent = heapq.heappop(heap)
            if isinstance(obj, _Partial):
                if obj not in parent.partials or \
                        self.pool.refcount(obj.page) != 0:
                    continue
                parent.partials.remove(obj)
            else:
                if obj.parent is None or not self._evictable(obj) or \
                        parent.children.get(obj.tokens.tobytes()) is not obj:
                    continue
                del parent.children[obj.tokens.tobytes()]
                obj.parent = None
            if getattr(obj, "page", None) is None:
                # spilled node: only its host copy exists — drop it.
                # Frees no device page, but may unblock the parent.
                self._host_drop(obj.host)
                obj.host = None
                self._n_spilled -= 1
            else:
                del self._owner[obj.page]
                self.pool.free([obj.page])
                self.evicted_pages_total += 1
                freed += 1
            # the parent may have just become an evictable leaf
            # (tokens None = a namespace root, never evictable)
            if parent.tokens is not None and self._evictable(parent):
                heapq.heappush(heap, (parent.last_used, id(parent),
                                      parent, parent.parent))
        return freed

    def clear(self) -> int:
        """Drop every unreferenced cached page — device-resident AND
        spilled (e.g. tests forcing a cold cache). Session pins do
        NOT survive a clear (it is the explicit drop-everything
        escape hatch); referenced nodes do."""
        stack = list(self._roots.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            node.pin_until = 0.0
        return self.evict(self.tree_pages + self._n_spilled)

    # -- fleet fabric (serving/fabric.py) ----------------------------------
    def fingerprints(self, limit: int = 4096) -> set:
        """Hashed summary of every page-aligned prefix this tree can
        serve — the per-replica summary the router ranks prefix
        affinity against. Each full-page edge contributes one CRC
        chained from its ancestors' spans and seeded by the adapter id
        (`fabric.fp_step`/`fp_seed` — byte-identical to the router's
        `prompt_fingerprints` walk over a prompt). Spilled nodes count:
        a match restores them, which is the whole point. BFS so a
        `limit` cap keeps the SHALLOW prefixes — the ones most prompts
        share — when the tree outgrows the summary budget."""
        from collections import deque

        from .fabric import fp_seed, fp_step
        out: set = set()
        queue = deque((root, fp_seed(aid))
                      for aid, root in self._roots.items())
        while queue and len(out) < limit:
            node, fp = queue.popleft()
            for child in node.children.values():
                cfp = fp_step(fp, child.tokens)
                out.add(cfp)
                if len(out) >= limit:
                    break
                queue.append((child, cfp))
        return out

    def collect_chain(self, tokens, adapter_id: int = 0
                      ) -> Tuple[int, List[Tuple[str, int]]]:
        """The resident page chain covering `tokens`' full pages, for
        the transfer path: walks full-page edges WITHOUT acquiring or
        restoring, returning (covered token count, [("page", id) |
        ("host", slot), ...]) — the engine reads device pages with its
        swap-out program and host slots straight from the host pool,
        so a spilled node ships without a device round-trip. Stops at
        the first miss (a transfer is one contiguous chain or
        nothing). Single-threaded like every other tree call: the
        chain stays valid until the next engine step."""
        ps = self.page_size
        tok = _tok(tokens)
        node = self._root_for(adapter_id)
        refs: List[Tuple[str, int]] = []
        depth = 0
        while depth + ps <= tok.size:
            child = node.children.get(tok[depth:depth + ps].tobytes())
            if child is None:
                break
            if child.page is not None:
                refs.append(("page", child.page))
            elif child.host is not None:
                refs.append(("host", child.host))
            else:
                break
            node = child
            depth += ps
            self._touch(child)
        return depth, refs

    def graft(self, tokens, payloads: List, valid: int,
              adapter_id: int = 0, *, alloc_restore) -> int:
        """`insert`'s twin for pages arriving from ANOTHER replica:
        index a transferred chain so the very next `acquire` hits it.
        `payloads` are opaque engine page payloads (one per page of
        `tokens[:valid]`); `alloc_restore(payload)` is the engine
        callback that allocates a device page (spilling/evicting under
        pressure), writes the payload into it, and returns it PARKED —
        or None, which ends the graft at that depth (a partial graft
        is still a valid shorter prefix; the chain property holds
        because grafting proceeds root-ward first). Spans the tree
        already holds are deduplicated without spending a page —
        re-transfer of a popular prefix costs nothing device-side.
        Returns the number of pages actually grafted."""
        ps = self.page_size
        tok = _tok(tokens)
        valid = int(valid)
        if valid > tok.size or valid > len(payloads) * ps:
            raise ValueError(
                f"valid={valid} exceeds tokens ({tok.size}) or "
                f"payload capacity ({len(payloads) * ps})")
        node = self._root_for(adapter_id)
        n_full = valid // ps
        grafted = 0
        for i in range(n_full):
            span = tok[i * ps:(i + 1) * ps]
            key = span.tobytes()
            child = node.children.get(key)
            if child is None:
                page = alloc_restore(payloads[i])
                if page is None:
                    return grafted
                child = _Node(np.array(span), page, node)
                node.children[key] = child
                self._owner[page] = child
                self.inserted_pages_total += 1
                grafted += 1
            node = child
            self._touch(node)
        rem = valid - n_full * ps
        if rem > 0 and n_full < len(payloads) and \
                self._tail_is_new(node, tok[n_full * ps:valid]):
            page = alloc_restore(payloads[n_full])
            if page is not None:
                part = _Partial(np.array(tok[n_full * ps:valid]), page)
                node.partials.append(part)
                self._owner[page] = part
                self.inserted_pages_total += 1
                self._touch(part)
                grafted += 1
        return grafted

    def snapshot(self, extract_page, host_payload=None) -> dict:
        """Serialize the whole tree — structure AND page contents —
        into a plain host-side record for warm restarts. Every node
        (device-resident via `extract_page(page)`, spilled via
        `host_payload(slot)`) becomes one entry {adapter, parent
        index, token span, opaque payload}; parents always precede
        children so `load` rebuilds in one pass. A node whose payload
        is unreachable (host tier dropped it) is skipped WITH its
        subtree — a chain with a hole is not a prefix. Meant for
        quiesced engines (the router snapshots after drain), but only
        reads pages, so a live snapshot is merely a stale one."""
        nodes: List[dict] = []
        for aid, root in sorted(self._roots.items()):
            stack: List[Tuple[object, int]] = [(root, -1)]
            while stack:
                node, pidx = stack.pop()
                if node.tokens is None:
                    midx = -1
                else:
                    if node.page is not None:
                        payload = extract_page(node.page)
                    elif node.host is not None and \
                            host_payload is not None:
                        payload = host_payload(node.host)
                    else:
                        continue
                    if payload is None:
                        continue
                    midx = len(nodes)
                    nodes.append({"adapter": aid, "parent": pidx,
                                  "tokens": np.array(node.tokens),
                                  "payload": payload,
                                  "partial": False})
                for part in node.partials:
                    pay = extract_page(part.page)
                    if pay is not None:
                        nodes.append({"adapter": aid, "parent": midx,
                                      "tokens": np.array(part.tokens),
                                      "payload": pay, "partial": True})
                for child in node.children.values():
                    stack.append((child, midx))
        return {"version": 1, "page_size": self.page_size,
                "nodes": nodes}

    def load(self, snap: dict, *, alloc_restore) -> int:
        """Rebuild a `snapshot` into THIS tree (typically empty — a
        fresh engine warming from its predecessor), parent-first, with
        the same `alloc_restore` contract and dedup as `graft`. An
        entry whose page cannot be allocated is dropped with its
        descendants (they never find their parent placed); everything
        restored is parked cache-resident, so the first prompts after
        a deploy hit instead of re-prefilling. Returns pages
        restored."""
        if snap.get("version") != 1:
            raise ValueError(
                f"prefix snapshot version {snap.get('version')!r} "
                "not supported")
        if int(snap.get("page_size", -1)) != self.page_size:
            raise ValueError(
                f"prefix snapshot page_size {snap.get('page_size')} "
                f"!= cache page_size {self.page_size}")
        restored = 0
        placed: Dict[int, _Node] = {}
        for i, ent in enumerate(snap["nodes"]):
            pidx = int(ent["parent"])
            if pidx < 0:
                parent = self._root_for(int(ent["adapter"]))
            else:
                parent = placed.get(pidx)
                if parent is None:
                    continue
            toks = _tok(ent["tokens"])
            if ent.get("partial"):
                if not self._tail_is_new(parent, toks):
                    continue
                page = alloc_restore(ent["payload"])
                if page is None:
                    continue
                part = _Partial(np.array(toks), page)
                parent.partials.append(part)
                self._owner[page] = part
                self.inserted_pages_total += 1
                self._touch(part)
                restored += 1
            else:
                key = toks.tobytes()
                child = parent.children.get(key)
                if child is None:
                    page = alloc_restore(ent["payload"])
                    if page is None:
                        continue
                    child = _Node(np.array(toks), page, parent)
                    parent.children[key] = child
                    self._owner[page] = child
                    self.inserted_pages_total += 1
                    restored += 1
                placed[i] = child
                self._touch(child)
        return restored
